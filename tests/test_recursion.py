"""Cross-DC recursion tests — two real in-process binder servers acting as
remote datacenters.

The reference has ZERO automated tests for lib/recursion.js (SURVEY §4:
"Recursion … zero automated tests"); this suite covers the forwarding
matrix it leaves untested.
"""
import asyncio


from binder_tpu.dns import ARecord, Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.recursion import Recursion, StaticResolverSource
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


def make_remote_fixture(dc, ip):
    """A remote DC's binder mirrors names under <x>.<dc>.foo.com."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json(f"/com/foo/{dc}", {"type": "service",
                                      "service": {"port": 53}})
    store.put_json(f"/com/foo/{dc}/web",
                   {"type": "host", "host": {"address": ip, "ttl": 44}})
    store.start_session()
    return cache


async def start_remote(dc, ip):
    server = BinderServer(zk_cache=make_remote_fixture(dc, ip),
                          dns_domain=DOMAIN, datacenter_name=dc,
                          host="127.0.0.1", port=0,
                          collector=MetricsCollector())
    await server.start()
    return server


async def start_local(dcs, server_kw=None, **rkw):
    """Local binder with empty cache + recursion to the given dc map."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.start_session()
    recursion = Recursion(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="local",
        source=StaticResolverSource(dcs),
        nic_provider=lambda: [],  # tests use 127.0.0.1 resolvers
        **rkw)
    await recursion.wait_ready()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="local", recursion=recursion,
                          host="127.0.0.1", port=0,
                          collector=MetricsCollector(),
                          **(server_kw or {}))
    await server.start()
    return server, recursion


async def udp_ask_wire(port, name, qtype, rd=True, timeout=5.0,
                       payload=1232):
    """Ask and return the RAW response wire (flag-level conformance)."""
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=3, rd=rd,
                                        edns_payload=payload).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return data


async def udp_ask(port, name, qtype, rd=True, timeout=5.0, payload=1232):
    return Message.decode(await udp_ask_wire(
        port, name, qtype, rd=rd, timeout=timeout, payload=payload))


class TestForwarding:
    def test_cross_dc_a_query(self):
        async def run():
            remote = await start_remote("east", "10.77.0.1")
            server, recursion = await start_local(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            r = await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
            await server.stop()
            await recursion.close()
            await remote.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "10.77.0.1"
        assert r.answers[0].name == "web.east.foo.com"
        assert r.answers[0].ttl == 44  # upstream ttl preserved

    def test_unknown_dc_refused(self):
        async def run():
            server, recursion = await start_local({"east": ["127.0.0.1:1"]})
            r = await udp_ask(server.udp_port, "web.west.foo.com", Type.A)
            await server.stop()
            await recursion.close()
            return r

        assert asyncio.run(run()).rcode == Rcode.REFUSED

    def test_no_rd_means_no_recursion(self):
        async def run():
            remote = await start_remote("east", "10.77.0.1")
            server, recursion = await start_local(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            r = await udp_ask(server.udp_port, "web.east.foo.com", Type.A,
                              rd=False)
            await server.stop()
            await recursion.close()
            await remote.stop()
            return r

        assert asyncio.run(run()).rcode == Rcode.REFUSED

    def test_dead_upstream_refused(self):
        async def run():
            # unroutable upstream: rely on the 3s timeout -> use a short one
            from binder_tpu.recursion import DnsClient
            server, recursion = await start_local(
                {"east": ["127.0.0.1:9"]},  # discard port, nothing listens
                client=DnsClient(concurrency=2, timeout=0.3))
            r = await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
            await server.stop()
            await recursion.close()
            return r

        assert asyncio.run(run()).rcode == Rcode.REFUSED

    def test_upstream_refused_maps_to_refused(self):
        async def run():
            # remote knows nothing about this name -> remote REFUSED
            remote = await start_remote("east", "10.77.0.1")
            server, recursion = await start_local(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            r = await udp_ask(server.udp_port, "other.east.foo.com", Type.A)
            await server.stop()
            await recursion.close()
            await remote.stop()
            return r

        assert asyncio.run(run()).rcode == Rcode.REFUSED


class TestPtrFanout:
    def test_ptr_tries_all_dcs(self):
        async def run():
            r1 = await start_remote("east", "10.77.0.1")
            r2 = await start_remote("west", "10.88.0.1")
            server, recursion = await start_local({
                "east": [f"127.0.0.1:{r1.udp_port}"],
                "west": [f"127.0.0.1:{r2.udp_port}"],
            })
            # only the west binder can answer this PTR
            resp = await udp_ask(server.udp_port,
                                 "1.0.88.10.in-addr.arpa", Type.PTR)
            await server.stop()
            await recursion.close()
            await r1.stop()
            await r2.stop()
            return resp

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].target == "web.west.foo.com"


class TestSelfFiltering:
    def test_own_addresses_filtered(self):
        async def run():
            remote = await start_remote("east", "10.77.0.1")
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.start_session()
            # NIC provider claims the remote's address is ours
            recursion = Recursion(
                zk_cache=cache, dns_domain=DOMAIN, datacenter_name="local",
                source=StaticResolverSource(
                    {"east": [f"127.0.0.1:{remote.udp_port}"]}),
                nic_provider=lambda: ["127.0.0.1"])
            await recursion.wait_ready()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="local",
                                  recursion=recursion, host="127.0.0.1",
                                  port=0, collector=MetricsCollector())
            await server.start()
            r = await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
            await server.stop()
            await recursion.close()
            await remote.stop()
            return r

        # everything filtered -> best-effort gives up with REFUSED
        assert asyncio.run(run()).rcode == Rcode.REFUSED

    def test_local_addresses_returns_something(self):
        from binder_tpu.utils.netif import local_addresses
        addrs = local_addresses()
        assert "127.0.0.1" in addrs


class TestDiscovery:
    def test_refresh_updates_dc_map(self):
        async def run():
            source = StaticResolverSource({"east": ["10.0.0.1"]})
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.start_session()
            recursion = Recursion(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="local", source=source)
            await recursion.wait_ready()
            before = dict(recursion.dcs)
            source._dcs = {"east": ["10.0.0.1"], "west": ["10.0.0.2"]}
            await recursion.refresh()
            after = dict(recursion.dcs)
            await recursion.close()
            return before, after

        before, after = asyncio.run(run())
        assert before == {"east": ["10.0.0.1"]}
        assert after == {"east": ["10.0.0.1"], "west": ["10.0.0.2"]}

    def test_init_failure_is_best_effort(self):
        class FailingSource(StaticResolverSource):
            async def init(self, cache):
                raise RuntimeError("ufds down")

        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.start_session()
            recursion = Recursion(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="local",
                                  source=FailingSource({}))
            # must become ready despite init failure (15s retry continues)
            await asyncio.wait_for(recursion.wait_ready(), timeout=2)
            await recursion.close()
            return True

        assert asyncio.run(run())


class TestReviewRegressions:
    """Regressions from the third code-review pass."""

    def test_malformed_resolver_string_fails_fast(self):
        """A bad resolver entry must produce REFUSED, not a hung lookup."""
        async def run():
            from binder_tpu.recursion import DnsClient, UpstreamError
            client = DnsClient(concurrency=2, timeout=0.5)
            try:
                await asyncio.wait_for(
                    client.lookup("x.foo.com", Type.A, ["10.0.0.1:notaport"]),
                    timeout=2)
            except UpstreamError:
                return "upstream-error"
            return "no-error"

        assert asyncio.run(run()) == "upstream-error"

    def test_ipv6_resolver_self_filter(self):
        from binder_tpu.recursion.recursion import _host_of
        assert _host_of("fd00::1") == "fd00::1"
        assert _host_of("[fd00::1]:53") == "fd00::1"
        assert _host_of("10.0.0.1:53") == "10.0.0.1"
        assert _host_of("10.0.0.1") == "10.0.0.1"

    # (the truncated-upstream-counts-as-failure case moved to
    # TestTcpFallback below, where tc=1 now triggers a TCP retry first)


class TestTcpFallback:
    """tc=1 upstream answers must be retried over TCP, not counted as
    failures (VERDICT r1 item 3; reference capability
    lib/recursion.js:253-279 via mname-client)."""

    def test_truncating_udp_only_upstream_still_fails(self):
        """No TCP listener behind the resolver: the TCP retry fails and
        the upstream counts against the threshold (no hang, no win)."""
        async def run():
            from binder_tpu.recursion import DnsClient, UpstreamError
            loop = asyncio.get_running_loop()

            class TruncatingServer(asyncio.DatagramProtocol):
                def connection_made(self, transport):
                    self.transport = transport

                def datagram_received(self, data, addr):
                    q = Message.decode(data)
                    resp = bytearray(Message(
                        id=q.id, qr=True, tc=True,
                        questions=list(q.questions)).encode())
                    # echo the question verbatim like a real server: the
                    # client 0x20-validates the case mask it sent
                    qlen = len(resp) - 12
                    resp[12:] = data[12:12 + qlen]
                    self.transport.sendto(bytes(resp), addr)

            transport, _ = await loop.create_datagram_endpoint(
                TruncatingServer, local_addr=("127.0.0.1", 0))
            port = transport.get_extra_info("sockname")[1]
            client = DnsClient(concurrency=2, timeout=1.0)
            try:
                await client.lookup("x.foo.com", Type.A,
                                    [f"127.0.0.1:{port}"])
            except UpstreamError as e:
                return str(e)
            finally:
                transport.close()
            return None

        err = asyncio.run(run())
        assert err is not None and "tcp retry" in err

    def test_large_answer_set_resolves_via_tcp(self):
        """End to end: a remote DC whose answer set overflows the 1232-
        byte EDNS ceiling truncates over UDP; the recursion client must
        fetch the full set over TCP and the local binder must serve it."""
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/dc9", {"type": "service",
                                            "service": {"port": 53}})
            store.put_json("/com/foo/dc9/big", {
                "type": "service",
                "service": {"srvce": "_big", "proto": "_tcp", "port": 80},
            })
            for i in range(100):
                store.put_json(f"/com/foo/dc9/big/lb{i}",
                               {"type": "load_balancer",
                                "load_balancer":
                                    {"address": f"10.9.{i // 250}.{i % 250 + 1}"}})
            store.start_session()
            remote = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="dc9",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector())
            await remote.start()
            local, recursion = await start_local(
                {"dc9": [f"127.0.0.1:{remote.udp_port}"]})
            try:
                # sanity: the remote really does truncate this over UDP
                direct = await udp_ask(remote.udp_port, "big.dc9.foo.com",
                                       Type.A)
                assert direct.tc and not direct.answers
                r = await udp_ask(local.udp_port, "big.dc9.foo.com",
                                  Type.A, rd=True, payload=4096)
            finally:
                await local.stop()
                await remote.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert len(r.answers) == 100
        addrs = {a.address for a in r.answers}
        assert len(addrs) == 100


class TestDns0x20:
    """The upstream client randomizes the qname's case and only accepts
    responses echoing the question verbatim — the blind-spoofing
    mitigation that lets the per-upstream socket be shared
    (binder_tpu/recursion/client.py _PortProto)."""

    def _fake_upstream(self, loop, echo_verbatim: bool):
        class Upstream(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                self.transport = transport

            def datagram_received(self, data, addr):
                q = Message.decode(data)
                resp = bytearray(Message(
                    id=q.id, qr=True,
                    questions=list(q.questions),
                    answers=[ARecord(name=q.questions[0].name, ttl=30,
                                     address="10.3.3.3")]).encode())
                if echo_verbatim:
                    qlen = 0
                    off = 12
                    while data[off] != 0:
                        off += 1 + data[off]
                    qlen = off + 5 - 12
                    resp[12:12 + qlen] = data[12:12 + qlen]
                return self.transport.sendto(bytes(resp), addr)

        return loop.create_datagram_endpoint(
            Upstream, local_addr=("127.0.0.1", 0))

    def test_verbatim_echo_accepted(self):
        async def run():
            from binder_tpu.recursion import DnsClient
            loop = asyncio.get_running_loop()
            tr, _ = await self._fake_upstream(loop, echo_verbatim=True)
            port = tr.get_extra_info("sockname")[1]
            client = DnsClient(timeout=1.0)
            try:
                answers = await client.lookup("web.foo.com", Type.A,
                                              [f"127.0.0.1:{port}"])
                return answers
            finally:
                client.close()
                tr.close()

        answers = asyncio.run(run())
        assert answers[0].address == "10.3.3.3"

    def test_case_mangling_upstream_rejected(self):
        """A response that does not echo the exact case mask (a spoofed
        or case-normalizing middlebox answer) is silently dropped, so
        the lookup times out instead of accepting it."""
        async def run():
            from binder_tpu.recursion import DnsClient, UpstreamError
            loop = asyncio.get_running_loop()
            tr, _ = await self._fake_upstream(loop, echo_verbatim=False)
            port = tr.get_extra_info("sockname")[1]
            client = DnsClient(timeout=0.5)
            try:
                await client.lookup("web.foo.com", Type.A,
                                    [f"127.0.0.1:{port}"])
            except UpstreamError:
                return True
            finally:
                client.close()
                tr.close()
            return False

        assert asyncio.run(run())


class TestServerCaseEcho:
    def test_generic_path_echoes_requester_case(self):
        """dns0x20 server side: mixed-case questions come back with the
        exact case mask on every path, including the generic resolver
        (QueryCtx._echo_question_case) — an SRV query cannot take the
        raw lane, so this pins the generic path."""
        async def run():
            server, _ = await start_local({})
            store = server.zk_cache.store
            store.put_json("/com/foo/svc", {
                "type": "service",
                "service": {"srvce": "_pg", "proto": "_tcp", "port": 1}})
            store.put_json("/com/foo/svc/lb0",
                           {"type": "load_balancer",
                            "load_balancer": {"address": "10.0.0.1"}})
            try:
                loop = asyncio.get_running_loop()
                fut = loop.create_future()
                q = bytearray(make_query("_pg._tcp.svc.foo.com",
                                         Type.SRV, qid=9).encode())
                # uppercase some qname letters by hand
                mangled = bytes(q).replace(b"_pg", b"_pG").replace(
                    b"svc", b"sVc").replace(b"foo", b"FoO")

                class P(asyncio.DatagramProtocol):
                    def connection_made(self, t):
                        t.sendto(mangled)

                    def datagram_received(self, d, a):
                        if not fut.done():
                            fut.set_result(d)

                tr, _ = await loop.create_datagram_endpoint(
                    P, remote_addr=("127.0.0.1", server.udp_port))
                raw = await asyncio.wait_for(fut, 5)
                tr.close()
                return mangled, raw
            finally:
                await server.stop()

        mangled, raw = asyncio.run(run())
        qlen = len("_pg._tcp.svc.foo.com") + 2 + 4
        assert raw[12:12 + qlen] == mangled[12:12 + qlen]
        assert Message.decode(raw).rcode == Rcode.NOERROR


from tests.test_zone import udp_ask_raw  # shared raw-ask helper


class TestRawSplice:
    """Round-5 forwarding hot path: the validated upstream wire is
    forwarded with id/RD/question-case patched instead of decode +
    rebuild (reference rebuilds per record type per query,
    lib/recursion.js:299-323).  The differential contract: spliced and
    rebuilt responses are byte-equal modulo the id bytes for every
    shape the splice accepts; shapes it declines take the rebuild path
    unchanged."""

    @staticmethod
    async def _pair(dcs):
        """Two local binders over the same remote map: one in the
        logged posture (want_log_detail forces the rebuild path), one
        log-off (splices)."""
        rebuilt, r1 = await start_local(
            dcs, server_kw={"query_log": True})
        spliced, r2 = await start_local(
            dcs, server_kw={"query_log": False})
        return rebuilt, r1, spliced, r2

    def test_spliced_equals_rebuilt_modulo_id(self):
        async def run():
            remote = await start_remote("east", "10.9.9.9")
            dcs = {"east": [f"127.0.0.1:{remote.udp_port}"]}
            rebuilt, r1, spliced, r2 = await self._pair(dcs)
            try:
                for payload in (1232, None):
                    qa = make_query("web.east.foo.com", Type.A, qid=101,
                                    rd=True, edns_payload=payload).encode()
                    qb = make_query("web.east.foo.com", Type.A, qid=202,
                                    rd=True, edns_payload=payload).encode()
                    ra = await udp_ask_raw(rebuilt.udp_port, qa)
                    rb = await udp_ask_raw(spliced.udp_port, qb)
                    assert ra[:2] == (101).to_bytes(2, "big")
                    assert rb[:2] == (202).to_bytes(2, "big")
                    assert ra[2:] == rb[2:], \
                        f"payload={payload}: spliced != rebuilt"
                    m = Message.decode(rb)
                    assert m.rcode == Rcode.NOERROR
                    assert m.rd            # client's RD echoed
                    assert m.answers[0].address == "10.9.9.9"
                    assert m.answers[0].ttl == 44
                    assert (m.edns is not None) == (payload is not None)
            finally:
                await rebuilt.stop()
                await spliced.stop()
                await r1.close()
                await r2.close()
                await remote.stop()

        asyncio.run(run())

    def test_mixed_case_question_echoed(self):
        async def run():
            remote = await start_remote("east", "10.9.9.10")
            dcs = {"east": [f"127.0.0.1:{remote.udp_port}"]}
            _, r1, spliced, r2 = await self._pair(dcs)
            await _.stop()
            await r1.close()
            try:
                q = bytearray(make_query("web.east.foo.com", Type.A,
                                         qid=7, rd=True).encode())
                # uppercase a few qname bytes (dns0x20 client)
                q[12 + 1] ^= 0x20
                q[12 + 5] ^= 0x20
                resp = await udp_ask_raw(spliced.udp_port, bytes(q))
                # the spliced response must echo the client's exact
                # question bytes, not our upstream query's case mask
                qend = 12
                while resp[qend] != 0:
                    qend += 1 + resp[qend]
                qend += 5
                assert resp[12:qend] == bytes(q[12:qend])
                m = Message.decode(resp)
                assert m.answers[0].address == "10.9.9.10"
            finally:
                await spliced.stop()
                await r2.close()
                await remote.stop()

        asyncio.run(run())

    def test_srv_with_glue_declines_to_rebuild(self):
        """An upstream SRV answer carries A additionals; the rebuild
        path drops them (reference behavior), so the splice must
        decline rather than diverge."""
        async def run():
            remote = await start_remote("east", "10.9.9.11")
            # register a service with members under the east dc
            # (remote fixture only has a host; build our own remote)
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/east", {"type": "service",
                                             "service": {"port": 53}})
            store.put_json("/com/foo/east/svc", {
                "type": "service",
                "service": {"srvce": "_pg", "proto": "_tcp",
                            "port": 5432}})
            store.put_json("/com/foo/east/svc/m0",
                           {"type": "load_balancer",
                            "load_balancer": {"address": "10.9.9.12"}})
            store.start_session()
            remote2 = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                   datacenter_name="east",
                                   host="127.0.0.1", port=0,
                                   collector=MetricsCollector())
            await remote2.start()
            dcs = {"east": [f"127.0.0.1:{remote2.udp_port}"]}
            rebuilt, r1, spliced, r2 = await self._pair(dcs)
            try:
                name = "_pg._tcp.svc.east.foo.com"
                ra = await udp_ask(rebuilt.udp_port, name, Type.SRV)
                rb = await udp_ask(spliced.udp_port, name, Type.SRV)
                for m in (ra, rb):
                    assert m.rcode == Rcode.NOERROR
                    assert m.answers[0].port == 5432
                    # glue dropped on BOTH paths (rebuild semantics)
                    non_opt = [r for r in m.additionals
                               if type(r).__name__ != "OPTRecord"]
                    assert non_opt == []
            finally:
                await rebuilt.stop()
                await spliced.stop()
                await r1.close()
                await r2.close()
                await remote2.stop()
                await remote.stop()

        asyncio.run(run())

    def test_ptr_spliced(self):
        async def run():
            remote = await start_remote("east", "10.9.9.13")
            dcs = {"east": [f"127.0.0.1:{remote.udp_port}"]}
            rebuilt, r1, spliced, r2 = await self._pair(dcs)
            try:
                name = "13.9.9.10.in-addr.arpa"
                qa = make_query(name, Type.PTR, qid=11, rd=True).encode()
                qb = make_query(name, Type.PTR, qid=22, rd=True).encode()
                ra = await udp_ask_raw(rebuilt.udp_port, qa)
                rb = await udp_ask_raw(spliced.udp_port, qb)
                assert ra[2:] == rb[2:]
                m = Message.decode(rb)
                assert m.answers[0].target == "web.east.foo.com"
            finally:
                await rebuilt.stop()
                await spliced.stop()
                await r1.close()
                await r2.close()
                await remote.stop()

        asyncio.run(run())


class TestErrorRenderConformance:
    """Wire-level conformance for recursion-path error responses
    (ISSUE 4 satellite): a SERVFAIL/REFUSED produced on the recursion
    path must carry the query's EDNS posture (the OPT echo survives
    the error path's section reset) and set RA — this binder IS the
    recursive service for the shape it just failed to recurse."""

    RA_BIT = 0x80

    def test_handler_crash_servfail_keeps_edns_and_ra(self):
        async def run():
            server, recursion = await start_local(
                {"east": ["127.0.0.1:9"]})

            async def boom(query):
                raise RuntimeError("injected recursion failure")

            # the coroutine path raises -> engine _on_query_error
            recursion._resolve_slow = boom
            try:
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
                assert raw[3] & 0x0F == Rcode.SERVFAIL
                assert raw[3] & self.RA_BIT, "RA must be set"
                msg = Message.decode(raw)
                assert msg.additionals and \
                    msg.additionals[-1].rtype == Type.OPT, \
                    "SERVFAIL must echo the EDNS OPT"
                # and WITHOUT EDNS on the query: no OPT invented
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A,
                                         payload=None)
                assert raw[3] & 0x0F == Rcode.SERVFAIL
                assert Message.decode(raw).additionals == []
            finally:
                await server.stop()
                await recursion.close()

        asyncio.run(run())

    def test_upstream_failure_refused_keeps_edns_and_ra(self):
        async def run():
            from binder_tpu.recursion import DnsClient
            server, recursion = await start_local(
                {"east": ["127.0.0.1:9"]},
                client=DnsClient(concurrency=2, timeout=0.2))
            try:
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
                assert raw[3] & 0x0F == Rcode.REFUSED
                assert raw[3] & self.RA_BIT, "RA must be set"
                msg = Message.decode(raw)
                assert msg.additionals and \
                    msg.additionals[-1].rtype == Type.OPT
            finally:
                await server.stop()
                await recursion.close()

        asyncio.run(run())

    def test_success_paths_set_ra_spliced_and_rebuilt(self):
        async def run():
            remote = await start_remote("east", "10.77.0.3")
            # query_log=True forces the rebuild path; default splices
            rebuilt_srv, r1 = await start_local(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                server_kw={"query_log": True})
            spliced_srv, r2 = await start_local(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            try:
                for srv in (rebuilt_srv, spliced_srv):
                    raw = await udp_ask_wire(srv.udp_port,
                                             "web.east.foo.com", Type.A)
                    assert raw[3] & 0x0F == Rcode.NOERROR
                    assert raw[3] & self.RA_BIT, "RA must be set"
                # a locally served (non-recursion) answer does NOT
                # advertise recursion
                raw = await udp_ask_wire(remote.udp_port,
                                         "web.east.foo.com", Type.A)
                assert not raw[3] & self.RA_BIT
            finally:
                await rebuilt_srv.stop()
                await spliced_srv.stop()
                await r1.close()
                await r2.close()
                await remote.stop()

        asyncio.run(run())
