"""Tests for the config-template renderer (binder_tpu/config/render.py)
and the binder-config-render CLI — the config-agent/SAPI analog
(reference sapi_manifests/binder/template).
"""
import json
import os
import subprocess
import sys

import pytest

from binder_tpu.config.render import TemplateError, render, render_manifest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEPLOY_MANIFEST = os.path.join(ROOT, "deploy", "config", "manifest.json")
CLI = os.path.join(ROOT, "bin", "binder-config-render")

TRITON_MD = {
    "dns_domain": "dc0.example.com",
    "datacenter_name": "dc0",
    "region_name": "home",
    "ufds_domain": "ufds.dc0.example.com",
    "ufds_ldap_root_dn": "cn=root",
    "ufds_ldap_root_pw": "secret",
    "auto": {"ZONENAME": "zone-1", "SERVER_UUID": "srv-1"},
    "SERVICE_NAME": "binder",
}

MANTA_MD = {
    "DNS_DOMAIN": "manta.example.com",
    "DATACENTER": "dc9",
    "auto": {"ZONENAME": "zone-2", "SERVER_UUID": "srv-2"},
    "SERVICE_NAME": "binder",
}


# -- engine semantics --

def test_interpolation_escaped_and_raw():
    assert render("{{x}}", {"x": "a&b"}) == "a&amp;b"
    assert render("{{{x}}}", {"x": "a&b"}) == "a&b"


def test_missing_key_renders_empty():
    assert render("[{{nope}}]", {}) == "[]"
    assert render("[{{{nope}}}]", {}) == "[]"


def test_comment_dropped_even_multiline():
    assert render("a{{! one\n two }}b", {}) == "ab"


def test_section_truthy_pushes_context():
    out = render("{{#s}}{{name}}{{/s}}", {"s": {"name": "in"}, "name": "out"})
    assert out == "in"


def test_section_falsy_and_inverted():
    md = {"on": False}
    assert render("{{#on}}yes{{/on}}{{^on}}no{{/on}}", md) == "no"
    assert render("{{^absent}}no{{/absent}}", {}) == "no"


def test_section_list_iterates():
    out = render("{{#xs}}{{v}},{{/xs}}", {"xs": [{"v": 1}, {"v": 2}]})
    assert out == "1,2,"


def test_dotted_name():
    assert render("{{a.b.c}}", {"a": {"b": {"c": "deep"}}}) == "deep"


def test_outer_scope_visible_inside_section():
    out = render("{{#s}}{{outer}}{{/s}}", {"s": {}, "outer": "seen"})
    assert out == "seen"


def test_unbalanced_sections_raise():
    with pytest.raises(TemplateError):
        render("{{#a}}", {})
    with pytest.raises(TemplateError):
        render("{{/a}}", {})
    with pytest.raises(TemplateError):
        render("{{#a}}{{/b}}", {})


# -- the shipped template --

def test_triton_branch_has_recursion():
    cfg = json.loads(render_manifest(DEPLOY_MANIFEST, TRITON_MD,
                                     output_path=None))
    assert cfg["dnsDomain"] == "dc0.example.com"
    assert cfg["recursion"]["regionName"] == "home"
    assert cfg["recursion"]["ufds"]["url"] == \
        "ldaps://ufds.dc0.example.com"
    assert cfg["store"]["backend"] == "zookeeper"
    assert cfg["instance_uuid"] == "zone-1"


def test_manta_branch_authoritative_only():
    cfg = json.loads(render_manifest(DEPLOY_MANIFEST, MANTA_MD,
                                     output_path=None))
    assert cfg["dnsDomain"] == "manta.example.com"
    assert cfg["datacenterName"] == "dc9"
    assert "recursion" not in cfg


def test_render_manifest_writes_output(tmp_path):
    dest = tmp_path / "config.json"
    render_manifest(DEPLOY_MANIFEST, MANTA_MD, output_path=str(dest))
    assert json.loads(dest.read_text())["datacenterName"] == "dc9"


# -- the CLI --

def _run_cli(*argv):
    return subprocess.run([sys.executable, CLI, *argv],
                          capture_output=True, text=True,
                          env={**os.environ,
                               "PYTHONPATH": ROOT + os.pathsep
                               + os.environ.get("PYTHONPATH", "")})


def test_cli_stdout(tmp_path):
    md = tmp_path / "md.json"
    md.write_text(json.dumps(TRITON_MD))
    res = _run_cli("-m", str(md), "-o", "-")
    assert res.returncode == 0, res.stderr
    assert json.loads(res.stdout)["recursion"]["datacenterName"] == "dc0"


def test_cli_rejects_invalid_json_output(tmp_path):
    md = tmp_path / "md.json"
    # neither branch's keys present -> "dnsDomain": "", fine; break it
    # with a template that renders non-JSON instead
    md.write_text(json.dumps({}))
    bad_tpl = tmp_path / "template"
    bad_tpl.write_text("{{#x}}not json{{/x}} nope")
    dest = tmp_path / "out.json"
    res = _run_cli("-m", str(md), "-t", str(bad_tpl), "-o", str(dest))
    assert res.returncode == 1
    assert "not valid JSON" in res.stderr
    assert not dest.exists()


def test_cli_on_change_gating(tmp_path):
    """--on-change runs exactly when the rendered content actually
    changed (config-agent's restart-consumers-on-change semantics), and
    an unchanged render never rewrites the file."""
    md = tmp_path / "md.json"
    md.write_text(json.dumps(MANTA_MD))
    dest = tmp_path / "config.json"
    stamp = tmp_path / "restarted"
    hook = f"touch {stamp}"

    # first render: content changed (file absent) -> hook runs
    res = _run_cli("-m", str(md), "-o", str(dest), "-c", hook)
    assert res.returncode == 0, res.stderr
    assert "wrote" in res.stdout
    assert stamp.exists()

    # identical metadata re-push: no rewrite, no hook
    stamp.unlink()
    mtime = dest.stat().st_mtime_ns
    res = _run_cli("-m", str(md), "-o", str(dest), "-c", hook)
    assert res.returncode == 0, res.stderr
    assert "unchanged" in res.stdout
    assert dest.stat().st_mtime_ns == mtime
    assert not stamp.exists()

    # metadata change -> rewrite + hook again
    md.write_text(json.dumps({**MANTA_MD, "DATACENTER": "dc10"}))
    res = _run_cli("-m", str(md), "-o", str(dest), "-c", hook)
    assert res.returncode == 0, res.stderr
    assert stamp.exists()
    assert json.loads(dest.read_text())["datacenterName"] == "dc10"


def test_cli_on_change_hook_failure_surfaces(tmp_path):
    md = tmp_path / "md.json"
    md.write_text(json.dumps(MANTA_MD))
    dest = tmp_path / "config.json"
    res = _run_cli("-m", str(md), "-o", str(dest), "-c", "exit 7")
    assert res.returncode == 7
    assert "on-change command failed" in res.stderr
    # the config itself IS written — only the consumer restart failed
    assert json.loads(dest.read_text())["datacenterName"] == "dc9"
