"""Shared test configuration.

The service tier is pure-Python asyncio; tests run against the in-memory
fake coordination store (the reference's biggest testability gap — it has
integration-only tests against a live ZooKeeper, SURVEY §4).

JAX env pinning (harness requirement): any test that imports jax must see a
CPU platform with a virtual 8-device mesh, never the real TPU tunnel.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _flag
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import logging

import pytest


@pytest.fixture(autouse=True)
def _restore_binder_logger_state():
    """Snapshot/restore handler, level, and propagate state for the
    binder logger tree around every test.

    Several tests (log ring, query log, zlogcat) attach handlers or
    adjust levels on the shared "binder"/"binder.server" loggers; a
    leaked handler changes what LATER tests' servers consider "logging
    armed" (e.g. the TCP fastpath gate's log-ring check), which made
    their behavior depend on test ORDER — green alone, red in the full
    run.  Restoring the exact prior state makes every test see the
    logger tree cold."""
    names = [None] + [n for n in logging.Logger.manager.loggerDict
                      if n == "binder" or n.startswith("binder.")]
    saved = {}
    for name in names:
        logger = logging.getLogger(name)
        saved[name] = (list(logger.handlers), logger.level,
                       logger.propagate, logger.disabled)
    yield
    for name, (handlers, level, propagate, disabled) in saved.items():
        logger = logging.getLogger(name)
        logger.handlers[:] = handlers
        logger.setLevel(level)
        logger.propagate = propagate
        logger.disabled = disabled
    # loggers born mid-test keep their objects (they may be cached by
    # the code under test) but must not keep leaked handlers
    for name in logging.Logger.manager.loggerDict:
        if (name not in saved
                and (name == "binder" or name.startswith("binder."))):
            logger = logging.getLogger(name)
            logger.handlers[:] = []
            logger.setLevel(logging.NOTSET)
            logger.propagate = True
