"""Shared test configuration.

The service tier is pure-Python asyncio; tests run against the in-memory
fake coordination store (the reference's biggest testability gap — it has
integration-only tests against a live ZooKeeper, SURVEY §4).

JAX env pinning (harness requirement): any test that imports jax must see a
CPU platform with a virtual 8-device mesh, never the real TPU tunnel.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flag = "--xla_force_host_platform_device_count=8"
if _flag not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _flag
    ).strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
