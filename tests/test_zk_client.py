"""ZooKeeper wire-protocol client tests against the in-process ZK server.

This is the layer the reference delegates to zkstream and only ever
exercises against a live ZooKeeper (SURVEY §4); here the real jute
protocol runs end-to-end in-process, including session expiry and
reconnect behavior.
"""
import asyncio
import json

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import MirrorCache
from binder_tpu.store.zk_client import ZKClient
from binder_tpu.store.zk_testserver import ZKEnsembleState, ZKTestServer

DOMAIN = "foo.com"


async def wait_for(predicate, timeout=5.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


async def boot(server):
    """ZKClient + MirrorCache against the given test server."""
    client = ZKClient(address="127.0.0.1", port=server.port,
                      session_timeout_ms=2000)
    cache = MirrorCache(client, DOMAIN)
    client.start()
    assert await wait_for(client.is_connected)
    return client, cache


def put_host(writer_client, path, addr):
    return writer_client.mkdirp(
        path, json.dumps({"type": "host",
                          "host": {"address": addr}}).encode())


class TestProtocol:
    def test_session_and_reads(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            client, cache = await boot(server)
            # a second client acts as the registrar writing records
            writer = ZKClient(address="127.0.0.1", port=server.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")
            assert await writer.get_data("/com/foo/web") is not None
            assert await writer.get_children("/com/foo") == ["web"]
            assert await writer.exists("/com/foo/web")
            assert not await writer.exists("/com/foo/nope")
            client.close()
            writer.close()
            await server.stop()

        asyncio.run(run())

    def test_watch_driven_mirror(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            writer = ZKClient(address="127.0.0.1", port=server.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")

            client, cache = await boot(server)
            assert await wait_for(
                lambda: cache.lookup("web.foo.com") is not None)
            node = cache.lookup("web.foo.com")
            assert node.data["host"]["address"] == "10.1.2.3"
            assert cache.reverse_lookup("10.1.2.3") is node

            # live update flows through the data watch
            await writer.set_data("/com/foo/web", json.dumps(
                {"type": "host", "host": {"address": "10.9.9.9"}}).encode())
            assert await wait_for(
                lambda: cache.reverse_lookup("10.9.9.9") is not None)
            assert cache.reverse_lookup("10.1.2.3") is None

            # node added later flows through the children watch
            await put_host(writer, "/com/foo/web2", "10.4.4.4")
            assert await wait_for(
                lambda: cache.lookup("web2.foo.com") is not None)

            # deletion unbinds
            await writer.delete("/com/foo/web2")
            assert await wait_for(
                lambda: cache.lookup("web2.foo.com") is None)

            client.close()
            writer.close()
            await server.stop()

        asyncio.run(run())

    def test_session_expiry_rebuilds(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            writer = ZKClient(address="127.0.0.1", port=server.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")

            client, cache = await boot(server)
            assert await wait_for(
                lambda: cache.lookup("web.foo.com") is not None)

            client_sid = client._session_id
            server.expire_session(client_sid)
            # expired session -> fresh session -> full rebuild; a record
            # written while we were down must appear
            await put_host(writer, "/com/foo/web3", "10.5.5.5")
            assert await wait_for(
                lambda: (client.is_connected()
                         and client._session_id != client_sid), timeout=8)
            assert await wait_for(
                lambda: cache.lookup("web3.foo.com") is not None, timeout=8)

            client.close()
            writer.close()
            await server.stop()

        asyncio.run(run())

    def test_connection_blip_resyncs(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            writer = ZKClient(address="127.0.0.1", port=server.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")

            client, cache = await boot(server)
            assert await wait_for(
                lambda: cache.lookup("web.foo.com") is not None)
            sid = client._session_id

            server.drop_connections()
            # the drop is noticed asynchronously: wait out the down/up cycle
            assert await wait_for(lambda: not writer.is_connected(),
                                  timeout=8)
            assert await wait_for(writer.is_connected, timeout=8)
            await put_host(writer, "/com/foo/web4", "10.6.6.6")
            assert await wait_for(
                lambda: cache.lookup("web4.foo.com") is not None, timeout=8)
            # same session resumed, not a new one
            assert client._session_id == sid

            client.close()
            writer.close()
            await server.stop()

        asyncio.run(run())


class TestFullStackOverZK:
    def test_binder_serves_from_real_zk_protocol(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            writer = ZKClient(address="127.0.0.1", port=server.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")
            await writer.mkdirp("/com/foo/svc", json.dumps({
                "type": "service",
                "service": {"srvce": "_pg", "proto": "_tcp",
                            "port": 5432}}).encode())
            for i in range(2):
                await writer.mkdirp(f"/com/foo/svc/lb{i}", json.dumps({
                    "type": "load_balancer",
                    "load_balancer": {"address": f"10.0.1.{i+1}"}}).encode())

            client, cache = await boot(server)
            binder = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="dc0", host="127.0.0.1",
                                  port=0, collector=MetricsCollector())
            await binder.start()
            assert await wait_for(cache.is_ready)
            assert await wait_for(
                lambda: cache.lookup("svc.foo.com") is not None
                and len(cache.lookup("svc.foo.com").children) == 2)

            loop = asyncio.get_running_loop()
            fut = loop.create_future()

            class P(asyncio.DatagramProtocol):
                def connection_made(self, t):
                    t.sendto(make_query("_pg._tcp.svc.foo.com", Type.SRV,
                                        qid=1).encode())

                def datagram_received(self, d, a):
                    if not fut.done():
                        fut.set_result(d)

            tr, _ = await loop.create_datagram_endpoint(
                P, remote_addr=("127.0.0.1", binder.udp_port))
            r = Message.decode(await asyncio.wait_for(fut, 5))
            tr.close()

            await binder.stop()
            client.close()
            writer.close()
            await server.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert sorted(a.target for a in r.answers) == \
            ["lb0.svc.foo.com", "lb1.svc.foo.com"]


class TestEnsembleFailover:
    """Multi-server connect string (VERDICT r1 item 7): reconnects walk
    the server list, so losing one ensemble member fails over to the
    next (deployment shape: co-located 3-5 node ensemble,
    reference README.md:36-39)."""

    def test_connect_string_parsing(self):
        from binder_tpu.store.zk_client import parse_connect_string
        assert parse_connect_string("10.0.0.1", 2181) == [("10.0.0.1", 2181)]
        assert parse_connect_string("a:2182,b", 2181) == [
            ("a", 2182), ("b", 2181)]
        assert parse_connect_string("[::1]:2190, h2 ", 2181) == [
            ("::1", 2190), ("h2", 2181)]
        # bracketed v6 without a port, and bare v6
        assert parse_connect_string("[2001:db8::1]", 2181) == [
            ("2001:db8::1", 2181)]
        assert parse_connect_string("fd00::7", 2181) == [("fd00::7", 2181)]
        with pytest.raises(ValueError):
            parse_connect_string("", 2181)

    def test_half_alive_member_cannot_stall_rotation(self, monkeypatch):
        """A member that accepts TCP but never answers the ConnectRequest
        must fail within CONNECT_TIMEOUT so rotation advances (r2 advisor
        medium: the handshake read used to have no deadline)."""
        import binder_tpu.store.zk_client as zkmod
        monkeypatch.setattr(zkmod, "CONNECT_TIMEOUT", 0.5)
        monkeypatch.setattr(zkmod, "RECONNECT_DELAY", 0.05)

        async def run():
            # half-alive member: accepts connections, reads, never writes
            async def black_hole(reader, writer):
                try:
                    await reader.read()
                finally:
                    writer.close()

            tarpit = await asyncio.start_server(
                black_hole, "127.0.0.1", 0)
            tarpit_port = tarpit.sockets[0].getsockname()[1]
            live = ZKTestServer()
            await live.start()

            client = ZKClient(
                address=f"127.0.0.1:{tarpit_port},127.0.0.1:{live.port}",
                port=2181, session_timeout_ms=2000)
            client.start()
            # must reach the live member despite the tarpit being first:
            # well under the old failure mode (infinite stall)
            assert await wait_for(client.is_connected, timeout=5.0)
            client.close()
            tarpit.close()
            await live.stop()

        asyncio.run(run())

    def test_session_survives_server_move(self, monkeypatch):
        """The production failover path (VERDICT r2 weak 3): the session
        is replicated ensemble-wide (ZAB), so losing the connected member
        moves the client to a survivor under the SAME session id, watches
        re-arm, and the mirror keeps serving throughout — no SERVFAIL
        window (deployment shape: reference README.md:36-39)."""
        import binder_tpu.store.zk_client as zkmod
        monkeypatch.setattr(zkmod, "RECONNECT_DELAY", 0.05)

        async def run():
            state = ZKEnsembleState()
            s1 = ZKTestServer(state=state)
            s2 = ZKTestServer(state=state)
            await s1.start()
            await s2.start()

            # registrar writes through member 2; the tree is shared
            writer = ZKClient(address="127.0.0.1", port=s2.port)
            writer.start()
            assert await wait_for(writer.is_connected)
            await put_host(writer, "/com/foo/web", "10.1.2.3")

            client = ZKClient(
                address=f"127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                port=2181, session_timeout_ms=2000)
            cache = MirrorCache(client, DOMAIN)
            client.start()
            assert await wait_for(client.is_connected)
            assert await wait_for(
                lambda: cache.lookup("web.foo.com") is not None)
            session_before = client._session_id
            assert session_before != 0

            # lose the member the client is connected to (index 0).
            # While the client reconnects, the mirror must keep serving:
            # is_ready() may never flip false (the resolver would answer
            # SERVFAIL, lib/server.js:186-192 semantics).
            await s1.stop()
            deadline = asyncio.get_running_loop().time() + 10.0
            while not client.is_connected():
                assert asyncio.get_running_loop().time() < deadline, \
                    "client failed to reconnect to the surviving member"
                assert cache.is_ready()
                assert cache.lookup("web.foo.com") is not None
                await asyncio.sleep(0.01)

            # same session resumed on the survivor, not a fresh one
            assert client._session_id == session_before
            # watches re-armed under the moved session: a mutation made
            # through the survivor must reach the mirror
            await put_host(writer, "/com/foo/moved", "10.4.4.4")
            assert await wait_for(
                lambda: cache.lookup("moved.foo.com") is not None)
            assert cache.lookup("web.foo.com") is not None

            client.close()
            writer.close()
            await s2.stop()

        asyncio.run(run())

    def test_mirror_rebuilds_via_surviving_server(self):
        """The *expiry* failover path: with independent (non-replicated)
        members the old session is unknown to the survivor, so the client
        starts a fresh session and fully rebuilds — the lib/zk.js:45-47
        semantics."""
        async def run():
            s1 = ZKTestServer()
            s2 = ZKTestServer()
            await s1.start()
            await s2.start()
            # independent members: seed both with the same records (s2
            # gets the post-failover truth, including one extra record to
            # prove liveness)
            for srv in (s1, s2):
                w = ZKClient(address="127.0.0.1", port=srv.port)
                w.start()
                assert await wait_for(w.is_connected)
                await put_host(w, "/com/foo/web", "10.1.2.3")
                if srv is s2:
                    await put_host(w, "/com/foo/extra", "10.9.9.9")
                w.close()

            client = ZKClient(
                address=f"127.0.0.1:{s1.port},127.0.0.1:{s2.port}",
                port=2181, session_timeout_ms=2000)
            cache = MirrorCache(client, DOMAIN)
            client.start()
            assert await wait_for(client.is_connected)
            assert await wait_for(
                lambda: cache.lookup("web.foo.com") is not None)

            # kill the member we are connected to (index 0)
            await s1.stop()
            # ... the client must fail over to s2, establish a fresh
            # session, and rebuild the mirror from the survivor
            assert await wait_for(
                lambda: cache.lookup("extra.foo.com") is not None,
                timeout=10.0)
            assert cache.lookup("web.foo.com") is not None
            assert client.is_connected()
            client.close()
            await s2.stop()

        asyncio.run(run())
