"""Stream-lane (TCP) fast path: accept fast path, pipelined coalescing,
connection-table hardening (ISSUE 5).

Pins the serving contracts the rewritten lane must keep:

- **byte-for-byte parity** — responses served via the accept fast path
  and via the promoted pipelined path match the UDP lane's wire output
  (modulo ID), and a truncated cached UDP wire is never replayed on TCP
  (TC-decline);
- **RFC 7766 conformance** — out-of-order responses carry the right
  IDs (a slow query never head-of-line-blocks the batch), half-close
  still gets its owed answers, a mid-frame RST never wedges the
  connection table, and the idle deadline still fires under pipelining;
- **bounded resources** — a slow reader is disconnected at
  ``MAX_TCP_WRITE_BUFFER`` with the ``binder_tcp_slow_reader_drops``
  metric advanced, never buffered unboundedly;
- **observability** — the ``binder_tcp_*`` exposition passes
  ``tools/lint.py validate_tcp_metrics`` (this is the family's tier-1
  wiring) and the ``/status`` ``tcp`` section is schema-complete;
- **chaos** — the stream-fault DSL actions drive a live server and the
  table re-converges to empty.
"""
import asyncio
import socket
import struct
import time

from binder_tpu.chaos import ChaosDriver, FaultPlan
from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.server import DnsServer
from binder_tpu.dns.wire import ARecord
from binder_tpu.introspect import Introspector
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from tools.lint import (validate_status_snapshot, validate_tcp_metrics)

DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(40):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(cache, **kw):
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1",
                          port=0, collector=MetricsCollector(), **kw)
    await server.start()
    return server


async def udp_ask_raw(port, wire, timeout=2.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(wire)

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()


async def tcp_oneshot_raw(port, wire):
    """The accept-fast-path client: connect, one query, read, close."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(struct.pack(">H", len(wire)) + wire)
    await writer.drain()
    (ln,) = struct.unpack(">H", await reader.readexactly(2))
    data = await reader.readexactly(ln)
    writer.close()
    await writer.wait_closed()
    return data


async def read_frames(reader, n, timeout=5.0):
    out = []
    for _ in range(n):
        hdr = await asyncio.wait_for(reader.readexactly(2), timeout)
        (ln,) = struct.unpack(">H", hdr)
        out.append(await asyncio.wait_for(reader.readexactly(ln),
                                          timeout))
    return out


def norm_id(wire: bytes) -> bytes:
    return b"\x00\x00" + wire[2:]


async def wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        await asyncio.sleep(0.02)
    return pred()


class TestParity:
    def test_fast_path_and_promoted_match_udp_wire(self):
        """One-shot (accept fast path) and second-burst (promoted
        pipelined) responses are byte-identical to the UDP lane's
        output for the same question, modulo the query ID."""
        shapes = [("web.foo.com", Type.A, 1232),
                  ("web.foo.com", Type.A, None),
                  ("nope.foo.com", Type.A, 1232),
                  ("1.0.168.192.in-addr.arpa", Type.PTR, 1232)]

        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            results = []
            for name, qtype, payload in shapes:
                wire = make_query(name, qtype, qid=11,
                                  edns_payload=payload).encode()
                udp = await udp_ask_raw(server.udp_port, wire)
                one = await tcp_oneshot_raw(server.tcp_port, wire)
                # promoted path: same query in the SECOND burst of a
                # pipelined connection
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", server.tcp_port)
                writer.write(struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                await read_frames(reader, 1)
                writer.write(struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                (piped,) = await read_frames(reader, 1)
                writer.close()
                await writer.wait_closed()
                results.append((name, udp, one, piped))
            assert server.engine.tcp_stats.promotions >= len(shapes)
            await server.stop()
            return results

        for name, udp, one, piped in asyncio.run(run()):
            assert norm_id(one) == norm_id(udp), name
            assert norm_id(piped) == norm_id(udp), name

    def test_tc_decline_for_cached_udp_wire(self):
        """A no-EDNS UDP answer that truncated (and was cached) must
        never be replayed on TCP: the TCP serve re-renders the full
        answer set (the tc=1 retry flow's whole point)."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            wire = make_query("svc.foo.com", Type.A, qid=3,
                              edns_payload=None).encode()
            # twice over UDP: second serve comes from the answer cache
            await udp_ask_raw(server.udp_port, wire)
            udp = Message.decode(
                await udp_ask_raw(server.udp_port, wire))
            tcp = Message.decode(
                await tcp_oneshot_raw(server.tcp_port, wire))
            await server.stop()
            return udp, tcp

        udp, tcp = asyncio.run(run())
        assert udp.tc and not udp.answers
        assert not tcp.tc and len(tcp.answers) == 40


class TestRfc7766:
    def test_out_of_order_responses_with_right_ids(self):
        """A slow (async) query pipelined ahead of fast ones must not
        head-of-line-block them: the fast responses come back first,
        each under its own ID (RFC 7766 §6.2.1.1)."""
        async def run():
            eng = DnsServer()

            def on_query(q):
                if q.name().startswith("slow"):
                    async def later():
                        await asyncio.sleep(0.15)
                        q.response.answers.append(ARecord(
                            name=q.name(), ttl=5, address="10.9.9.9"))
                        q.respond()
                    return later()
                q.response.answers.append(ARecord(
                    name=q.name(), ttl=5, address="10.1.1.1"))
                q.respond()
                return None

            eng.on_query = on_query
            port = await eng.listen_tcp("127.0.0.1", 0, announce=False)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            block = b""
            for qid, name in ((1, "slow.example.com"),
                              (2, "fast1.example.com"),
                              (3, "fast2.example.com")):
                w = make_query(name, Type.A, qid=qid).encode()
                block += struct.pack(">H", len(w)) + w
            writer.write(block)
            await writer.drain()
            frames = await read_frames(reader, 3)
            writer.close()
            await writer.wait_closed()
            await eng.close()
            return [Message.decode(f) for f in frames]

        r1, r2, r3 = asyncio.run(run())
        # fast responses first (out of order vs the request stream),
        # the slow one last — all IDs intact
        assert (r1.id, r2.id, r3.id) == (2, 3, 1)
        assert r3.answers[0].address == "10.9.9.9"

    def test_half_close_still_gets_owed_response(self):
        """send-then-SHUT_WR with an async answer outstanding: the
        response must still be written, then the slot reclaimed."""
        async def run():
            eng = DnsServer()

            def on_query(q):
                async def later():
                    await asyncio.sleep(0.1)
                    q.response.answers.append(ARecord(
                        name=q.name(), ttl=5, address="10.2.2.2"))
                    q.respond()
                return later()

            eng.on_query = on_query
            port = await eng.listen_tcp("127.0.0.1", 0, announce=False)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            w = make_query("x.example.com", Type.A, qid=9).encode()
            writer.write(struct.pack(">H", len(w)) + w)
            await writer.drain()
            writer.write_eof()
            (frame,) = await read_frames(reader, 1)
            eof = await asyncio.wait_for(reader.read(16), 5)
            writer.close()
            await writer.wait_closed()
            stats = eng.tcp_stats
            empty = await wait_until(lambda: not eng._tcp_conns)
            await eng.close()
            return Message.decode(frame), eof, stats, empty

        r, eof, stats, empty = asyncio.run(run())
        assert r.id == 9 and r.answers[0].address == "10.2.2.2"
        assert eof == b""
        assert stats.half_closes >= 1
        assert empty

    def test_mid_frame_rst_never_wedges_table(self):
        """A torn frame followed by RST must shed the connection; the
        server keeps serving and the table re-converges to empty."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            loop = asyncio.get_running_loop()
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setblocking(False)
            await loop.sock_connect(s, ("127.0.0.1", server.tcp_port))
            # header promising 256 bytes, 3 sent: mid-frame
            await loop.sock_sendall(s, b"\x01\x00abc")
            await asyncio.sleep(0.1)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         struct.pack("ii", 1, 0))
            s.close()   # RST
            engine = server.engine
            empty = await wait_until(lambda: not engine._tcp_conns)
            # the lane still serves
            r = Message.decode(await tcp_oneshot_raw(
                server.tcp_port,
                make_query("web.foo.com", Type.A, qid=4).encode()))
            stats = engine.tcp_stats
            await server.stop()
            return empty, r, stats

        empty, r, stats = asyncio.run(run())
        assert empty
        assert r.rcode == Rcode.NOERROR
        assert stats.rst_drops >= 1

    def test_idle_deadline_fires_under_pipelining(self):
        """Frames keep a pipelined connection alive; silence after the
        last frame still trips the idle deadline."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=0.4)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            for qid in range(3):
                w = make_query("web.foo.com", Type.A, qid=qid).encode()
                writer.write(struct.pack(">H", len(w)) + w)
                await writer.drain()
                await read_frames(reader, 1)
                await asyncio.sleep(0.2)   # < deadline per frame
            t0 = asyncio.get_running_loop().time()
            eof = await asyncio.wait_for(reader.read(16), 5)
            elapsed = asyncio.get_running_loop().time() - t0
            stats = server.engine.tcp_stats
            writer.close()
            await server.stop()
            return eof, elapsed, stats

        eof, elapsed, stats = asyncio.run(run())
        assert eof == b""
        assert elapsed < 2.0
        assert stats.idle_timeouts >= 1


class TestWriteBufferCap:
    def test_slow_reader_disconnected_at_cap_with_metric(self):
        """A client that pipelines queries and never reads must be
        disconnected once the server-side backlog passes
        MAX_TCP_WRITE_BUFFER — with the drop recorded in
        binder_tcp_slow_reader_drops, never buffered unboundedly."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=30.0,
                                        max_tcp_write_buffer=4096)
            raw = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            raw.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            raw.setblocking(False)
            loop = asyncio.get_running_loop()
            await loop.sock_connect(raw, ("127.0.0.1", server.tcp_port))
            wire = make_query("svc.foo.com", Type.A, qid=1,
                              edns_payload=4096).encode()
            frame = struct.pack(">H", len(wire)) + wire
            aborted = False
            try:
                # the kernel absorbs up to ~tcp_wmem max before the
                # user-space backlog grows, so pump well past that
                for i in range(20000):
                    await loop.sock_sendall(raw, frame)
                    if i % 64 == 0:
                        await asyncio.sleep(0)
            except (ConnectionResetError, BrokenPipeError, OSError):
                aborted = True
            raw.close()
            stats = server.engine.tcp_stats
            exposed = server.collector.expose()
            # other clients are unaffected
            r = Message.decode(await tcp_oneshot_raw(
                server.tcp_port,
                make_query("web.foo.com", Type.A, qid=2).encode()))
            await server.stop()
            return aborted, stats, exposed, r

        aborted, stats, exposed, r = asyncio.run(run())
        assert aborted
        assert stats.slow_reader_drops >= 1
        assert r.rcode == Rcode.NOERROR
        for line in exposed.splitlines():
            if line.startswith("binder_tcp_slow_reader_drops"):
                assert float(line.split()[-1]) >= 1.0
                break
        else:
            raise AssertionError(
                "binder_tcp_slow_reader_drops not exposed")


class TestAccountingAndCoalescing:
    def test_oneshot_vs_promotion_accounting(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            engine = server.engine
            wire = make_query("web.foo.com", Type.A, qid=1).encode()
            await tcp_oneshot_raw(server.tcp_port, wire)
            stats = engine.tcp_stats
            await wait_until(lambda: stats.oneshot_closes >= 1)
            assert stats.accepts >= 1
            assert stats.fast_serves >= 1
            assert stats.promotions == 0
            assert stats.oneshot_closes >= 1
            # now a client that keeps sending: second burst promotes
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            writer.write(struct.pack(">H", len(wire)) + wire)
            await writer.drain()
            await read_frames(reader, 1)
            writer.write(struct.pack(">H", len(wire)) + wire)
            await writer.drain()
            await read_frames(reader, 1)
            writer.close()
            await writer.wait_closed()
            assert stats.promotions == 1
            await server.stop()

        asyncio.run(run())

    def test_pipelined_burst_coalesces_into_one_write(self):
        """All responses produced while draining one read chunk go out
        as a single vectored write."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            block = b""
            for qid in range(1, 6):
                w = make_query("web.foo.com", Type.A, qid=qid).encode()
                block += struct.pack(">H", len(w)) + w
            writer.write(block)
            await writer.drain()
            frames = await read_frames(reader, 5)
            stats = server.engine.tcp_stats
            writer.close()
            await writer.wait_closed()
            await server.stop()
            return frames, stats

        frames, stats = asyncio.run(run())
        ids = sorted(Message.decode(f).id for f in frames)
        assert ids == [1, 2, 3, 4, 5]
        assert stats.coalesced_writes >= 1
        assert stats.coalesced_frames >= 5


class TestObservability:
    def test_tcp_metrics_family_validates(self):
        """Tier-1 wiring for tools/lint.py validate_tcp_metrics: the
        full binder_tcp_* family is present (right TYPEs, a sample
        each) on a live server's real exposition."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            await tcp_oneshot_raw(
                server.tcp_port,
                make_query("web.foo.com", Type.A, qid=1).encode())
            text = server.collector.expose()
            await server.stop()
            return text

        errs = validate_tcp_metrics(asyncio.run(run()))
        assert errs == []

    def test_status_snapshot_carries_tcp_section(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            await tcp_oneshot_raw(
                server.tcp_port,
                make_query("web.foo.com", Type.A, qid=1).encode())
            intro = Introspector(server=server)
            snap = intro.snapshot()
            await server.stop()
            return snap

        snap = asyncio.run(run())
        assert validate_status_snapshot(snap) == []
        tcp = snap["tcp"]
        assert tcp["accepts"] >= 1
        assert tcp["max_conns"] == DnsServer.MAX_TCP_CONNS
        assert tcp["max_write_buffer"] == DnsServer.MAX_TCP_WRITE_BUFFER


class TestChaosStreamFaults:
    def test_dsl_parses_stream_actions(self):
        plan = FaultPlan.parse("""
            at 0.0 tcp-slow-reader conns=2 queries=64 hold_ms=100
            at 0.1 tcp-half-close queries=2
            at 0.2 tcp-rst conns=1
        """)
        assert [a for _, a, _ in plan.timeline] == [
            "tcp-slow-reader", "tcp-half-close", "tcp-rst"]

    def test_driver_soaks_live_server(self):
        """The scripted stream faults run against a live listener; the
        table re-converges to empty and serving never stops."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            plan = FaultPlan.parse(
                "at 0.0 tcp-slow-reader conns=1 queries=32 hold_ms=100;"
                "at 0.05 tcp-half-close queries=2;"
                "at 0.1 tcp-rst conns=2")
            driver = ChaosDriver(
                plan, store=store,
                tcp_target=("127.0.0.1", server.tcp_port,
                            "web.foo.com"))
            await driver.run()
            await driver.stream_quiesce()
            engine = server.engine
            empty = await wait_until(lambda: not engine._tcp_conns)
            r_tcp = Message.decode(await tcp_oneshot_raw(
                server.tcp_port,
                make_query("web.foo.com", Type.A, qid=7).encode()))
            stats = engine.tcp_stats
            await server.stop()
            return empty, r_tcp, stats

        empty, r_tcp, stats = asyncio.run(run())
        assert empty
        assert r_tcp.rcode == Rcode.NOERROR
        # the torn-frame RSTs were shed, not wedged (the half-close
        # fault is served synchronously here, so nothing is ever owed
        # at EOF — that path is pinned by TestRfc7766 with an async
        # handler)
        assert stats.rst_drops >= 1
        assert stats.accepts >= 3

    def test_driver_without_target_skips(self):
        drv = ChaosDriver(FaultPlan())
        # must not raise (and must not wedge waiting for a loop)
        drv.apply("tcp-rst", {})
        assert ("tcp-rst" in [a for _, a in drv.applied])
