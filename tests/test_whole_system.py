"""Whole-system capstone: every subsystem at once, under faults.

Topology (all real protocols, in one process + the native balancer):

    dig-analog client ──UDP──▶ mbalancer ──unix──▶ 2 binder backends
                                                   │        │
                                         ZK wire (jute)  recursion (DNS)
                                                   │        │
                                     2-member ZK ensemble  remote-DC binder
                                     (shared ZKEnsembleState)

The individual paths each have their own suites; this test pins the
*interactions*: per-name invalidation propagating through the balancer
while recursion traffic flows, a ZK member dying without a SERVFAIL
window (session resumes on the survivor), and a backend dying with the
balancer failing over — queries answering correctly throughout.
"""
import asyncio
import io
import json
import os

import pytest

from binder_tpu.dns import Rcode, Type
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.recursion import DnsClient, Recursion, StaticResolverSource
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.store.zk_client import ZKClient
from binder_tpu.store.zk_testserver import ZKEnsembleState, ZKTestServer
from binder_tpu.utils.jsonlog import make_logger

from tests.test_balancer import (
    BALANCER,
    read_stats,
    start_balancer,
    udp_ask as _udp_ask,
)
from tests.test_full_stack import wait_for

DOMAIN = "foo.com"

pytestmark = pytest.mark.skipif(
    not os.path.exists(BALANCER),
    reason="mbalancer not built (make -C native)")


async def udp_ask(port, name, qtype, qid):
    # the shared helper (already decodes) with RD set — the clients in
    # this scenario are recursion-shaped
    return await _udp_ask(port, name, qtype, qid=qid, rd=True)


# all three serving postures: python-path (query_log=True, plain
# logger) keeps every query in Python; native-path (query_log=False)
# engages the full native stack — raw lane, fastpath cache, zone
# precompilation, serve_wire on the balancer lane; native-logged
# (query_log=True + JSON logger) engages the native stack WITH the
# query-log ring — the reference-parity posture — and the test asserts
# real log records exist for natively served queries.  The SAME fault
# scenario (ZK member death, backend death, churn) runs in each.
@pytest.mark.parametrize("query_log,json_log",
                         [(True, False), (False, False), (True, True)],
                         ids=["python-path", "native-path",
                              "native-logged"])
def test_everything_at_once(tmp_path, query_log, json_log):
    sockdir = str(tmp_path)

    async def run():
        # -- 2-member ZK ensemble over one shared state --
        state = ZKEnsembleState()
        zk1 = ZKTestServer(state=state)
        zk2 = ZKTestServer(state=state)
        await zk1.start()
        await zk2.start()
        connect = f"127.0.0.1:{zk1.port},127.0.0.1:{zk2.port}"

        # registrar seeds the shared tree through member 2
        writer = ZKClient(address="127.0.0.1", port=zk2.port)
        writer.start()
        assert await wait_for(writer.is_connected)
        await writer.mkdirp("/com/foo/web", json.dumps(
            {"type": "host", "host": {"address": "10.1.0.1"}}).encode())
        await writer.mkdirp("/com/foo/api", json.dumps(
            {"type": "host", "host": {"address": "10.1.0.2"}}).encode())

        # -- remote-DC binder for recursion (fake store is fine there) --
        rstore = FakeStore()
        rcache = MirrorCache(rstore, DOMAIN)
        rstore.put_json("/com/foo/east", {"type": "service",
                                          "service": {"port": 53}})
        rstore.put_json("/com/foo/east/db",
                        {"type": "host",
                         "host": {"address": "10.99.0.7"}})
        rstore.start_session()
        log_streams = []

        def posture_log(tag):
            # native-logged posture: a real JSON stream logger (the
            # shape the log ring requires to arm)
            if not json_log:
                return None
            stream = io.StringIO()
            log_streams.append(stream)
            return make_logger(f"capstone-{tag}", stream=stream)

        remote = BinderServer(zk_cache=rcache, dns_domain=DOMAIN,
                              datacenter_name="east", host="127.0.0.1",
                              port=0, collector=MetricsCollector(),
                              query_log=query_log,
                              log=posture_log("remote"))
        await remote.start()

        # -- 2 ZK-backed backends with recursion, behind the balancer --
        backends = []
        for i in range(2):
            client = ZKClient(address=connect, port=2181,
                              session_timeout_ms=2000)
            cache = MirrorCache(client, DOMAIN)
            client.start()
            recursion = Recursion(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="local",
                source=StaticResolverSource(
                    {"east": [f"127.0.0.1:{remote.udp_port}"]}),
                nic_provider=lambda: [],
                client=DnsClient(concurrency=2, timeout=2.0))
            await recursion.wait_ready()
            server = BinderServer(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="local", recursion=recursion,
                host="127.0.0.1", port=0,
                balancer_socket=os.path.join(sockdir, str(i)),
                collector=MetricsCollector(), query_log=query_log,
                log=posture_log(f"backend{i}"))
            await server.start()
            backends.append((client, cache, recursion, server))
        assert await wait_for(lambda: all(
            c.lookup("api.foo.com") is not None
            and c.lookup("api.foo.com").data is not None
            for _cl, c, _r, _s in backends))

        # relay lane (-D): this scenario asserts the balancer's own
        # cache fill/invalidation counters, which direct return
        # bypasses by design (tools/balancer_smoke.py and
        # tests/test_balancer.py cover the direct lane)
        proc, port = await start_balancer(sockdir, direct=False)
        try:
            await asyncio.sleep(0.4)

            # 1. authoritative A through the balancer (fills its cache)
            for qid in (1, 2):
                m = await udp_ask(port, "web.foo.com", Type.A, qid)
                assert m.rcode == Rcode.NOERROR
                assert m.answers[0].address == "10.1.0.1"

            # 2. cross-DC recursion through the balancer (never cached)
            m = await udp_ask(port, "db.east.foo.com", Type.A, 5)
            assert m.rcode == Rcode.NOERROR
            assert m.answers[0].address == "10.99.0.7"

            # 3. churn web over ZK: per-name invalidation must ripple
            # through backend caches AND the balancer, while api stays
            # cached and recursion keeps working
            await udp_ask(port, "api.foo.com", Type.A, 6)
            await writer.set_data("/com/foo/web", json.dumps(
                {"type": "host",
                 "host": {"address": "10.1.0.99"}}).encode())
            assert await wait_for(lambda: all(
                c.lookup("web.foo.com").data["host"]["address"]
                == "10.1.0.99" for _cl, c, _r, _s in backends))
            assert await wait_for(
                lambda: read_stats(sockdir)["cache_invalidations"] >= 1)
            m = await udp_ask(port, "web.foo.com", Type.A, 7)
            assert m.answers[0].address == "10.1.0.99"
            m = await udp_ask(port, "api.foo.com", Type.A, 8)
            assert m.answers[0].address == "10.1.0.2"
            m = await udp_ask(port, "db.east.foo.com", Type.A, 9)
            assert m.answers[0].address == "10.99.0.7"

            # 4. ZK member 1 dies: sessions resume on member 2, mirrors
            # keep serving (no SERVFAIL window), watches re-arm
            sessions_before = [cl._session_id
                               for cl, _c, _r, _s in backends]
            await zk1.stop()
            for qid in range(20, 26):
                m = await udp_ask(port, "web.foo.com", Type.A, qid)
                assert m.rcode == Rcode.NOERROR, f"qid {qid}"
                assert m.answers[0].address == "10.1.0.99"
            assert await wait_for(lambda: all(
                cl.is_connected() for cl, _c, _r, _s in backends))
            assert [cl._session_id
                    for cl, _c, _r, _s in backends] == sessions_before
            # a post-failover mutation still propagates
            await writer.mkdirp("/com/foo/late", json.dumps(
                {"type": "host",
                 "host": {"address": "10.1.0.50"}}).encode())
            assert await wait_for(lambda: all(
                c.lookup("late.foo.com") is not None
                and c.lookup("late.foo.com").data is not None
                for _cl, c, _r, _s in backends))
            m = await udp_ask(port, "late.foo.com", Type.A, 30)
            assert m.answers[0].address == "10.1.0.50"

            # 5. backend 0 dies (SIGTERM unlinks its socket): the
            # balancer fails over and every path keeps answering
            await backends[0][3].stop()
            os_path = os.path.join(sockdir, "0")
            if os.path.exists(os_path):
                os.unlink(os_path)
            await asyncio.sleep(0.5)   # balancer sweep notices
            for qid in range(40, 44):
                m = await udp_ask(port, "web.foo.com", Type.A, qid)
                assert m.answers[0].address == "10.1.0.99"
            m = await udp_ask(port, "db.east.foo.com", Type.A, 50)
            assert m.answers[0].address == "10.99.0.7"

            if json_log:
                # reference-parity posture: the native stack must have
                # served under logging AND produced real log records
                native_lines = 0
                for _cl, _c, _r, s in backends[1:] + backends[:1]:
                    if s._fastpath is None:
                        continue
                    assert s._log_ring, "log ring failed to arm"
                    s._drain_native_log()
                    import binder_tpu.server as _srv
                    stats = _srv._fastio.fastpath_stats(s._fastpath)
                    native_lines += stats["log_lines"]
                assert native_lines > 0, \
                    "no natively-logged serves in the logged posture"
                records = []
                for stream in log_streams:
                    for ln in stream.getvalue().splitlines():
                        rec = json.loads(ln)
                        if rec.get("msg") == "DNS query":
                            records.append(rec)
                # every answered query above must have left a record —
                # at minimum the early web.foo.com serves
                assert any(r.get("query", {}).get("name") ==
                           "web.foo.com" for r in records)
                assert len(records) >= native_lines
        finally:
            proc.kill()
            await proc.wait()
            for client, _c, recursion, server in backends:
                try:
                    await server.stop()
                except Exception:  # noqa: BLE001 — backend 0 already down
                    pass
                await recursion.close()
                client.close()
            writer.close()
            await remote.stop()
            await zk2.stop()

    asyncio.run(run())
