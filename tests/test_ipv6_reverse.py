"""IPv6 reverse-map tests (ISSUE 11 satellite): AAAA/v6-addressed host
records populate ``ip6.arpa`` PTR entries alongside the v4 path.

Layers:
- wire helpers: canonical nibble render/parse round-trip;
- mirror: ``TreeNode.ip`` canonicalizes v6 text so reverse-map keys,
  dependency tags, and PTR lookups agree; upkeep on delete/re-address;
- engine: ``plan_ptr`` serves ip6.arpa alongside in-addr.arpa, REFUSED
  for malformed nibble names;
- raw lane: differential against the generic path (byte-identical);
- end to end: a live server answers the v6 PTR over UDP, including for
  hosts added after start (the mutation path).
"""
import asyncio

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.query import QueryCtx
from binder_tpu.dns.wire import ip_from_reverse_name, reverse_name_for_ip
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.resolver import Resolver
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

from tests.test_raw_lane import ask_raw, new_server

DOMAIN = "foo.com"

V6 = "fd00:1234::42"
V6_REV = reverse_name_for_ip(V6)            # canonical ip6.arpa name
V6_NONCANON = "FD00:1234:0:0:0:0:0:42"      # same address, other text


def make_stack(addr=V6):
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web6",
                   {"type": "host", "host": {"address": addr}})
    store.put_json("/com/foo/web4",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.start_session()
    return store, cache


def ask(resolver, name, qtype):
    sent = []
    q = QueryCtx(make_query(name, qtype, qid=99), ("127.0.0.1", 5353),
                 "udp", sent.append)
    pending = resolver.handle(q)
    if pending is not None:
        asyncio.run(pending)
    assert len(sent) == 1
    return Message.decode(sent[0])


class TestWireHelpers:
    def test_round_trip(self):
        assert V6_REV.endswith(".ip6.arpa")
        assert len(V6_REV.split(".")) == 34  # 32 nibbles + ip6 + arpa
        assert ip_from_reverse_name(V6_REV) == "fd00:1234::42"

    def test_v4_round_trip_unchanged(self):
        assert reverse_name_for_ip("192.168.0.1") == \
            "1.0.168.192.in-addr.arpa"
        assert ip_from_reverse_name("1.0.168.192.in-addr.arpa") == \
            "192.168.0.1"

    def test_malformed_nibble_names_rejected(self):
        assert ip_from_reverse_name("1.2.3.4.ip6.arpa") is None
        assert ip_from_reverse_name(
            "g" + V6_REV[1:]) is None          # non-hex nibble
        assert ip_from_reverse_name(
            "ff." + V6_REV) is None            # 2-char label


class TestMirrorReverseMap:
    def test_v6_reverse_entry_keyed_canonically(self):
        store, cache = make_stack(addr=V6_NONCANON)
        node = cache.reverse_lookup("fd00:1234::42")
        assert node is not None
        assert node.ip == "fd00:1234::42"

    def test_v4_entries_unaffected(self):
        store, cache = make_stack()
        assert cache.reverse_lookup("192.168.0.1") is not None

    def test_delete_removes_v6_entry(self):
        store, cache = make_stack()
        assert cache.reverse_lookup("fd00:1234::42") is not None
        store.delete("/com/foo/web6")
        assert cache.reverse_lookup("fd00:1234::42") is None

    def test_readdress_repoints_entry(self):
        store, cache = make_stack()
        store.put_json("/com/foo/web6",
                       {"type": "host", "host": {"address": "fd00::9"}})
        assert cache.reverse_lookup("fd00:1234::42") is None
        assert cache.reverse_lookup("fd00::9") is not None

    def test_invalid_v6_text_yields_no_entry(self):
        store, cache = make_stack(addr="fd00::zz")
        assert cache.reverse_lookup("fd00::zz") is None


class TestEnginePtr:
    def test_v6_ptr_resolves(self):
        store, cache = make_stack()
        resolver = Resolver(cache, dns_domain=DOMAIN,
                            datacenter_name="coal")
        r = ask(resolver, V6_REV, Type.PTR)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].target == "web6.foo.com"

    def test_v6_ptr_miss_refused(self):
        store, cache = make_stack()
        resolver = Resolver(cache, dns_domain=DOMAIN,
                            datacenter_name="coal")
        miss = reverse_name_for_ip("fd00::dead")
        assert ask(resolver, miss, Type.PTR).rcode == Rcode.REFUSED

    def test_malformed_v6_reverse_refused(self):
        store, cache = make_stack()
        resolver = Resolver(cache, dns_domain=DOMAIN,
                            datacenter_name="coal")
        r = ask(resolver, "1.2.3.4.ip6.arpa", Type.PTR)
        assert r.rcode == Rcode.REFUSED

    def test_v4_ptr_still_resolves(self):
        store, cache = make_stack()
        resolver = Resolver(cache, dns_domain=DOMAIN,
                            datacenter_name="coal")
        r = ask(resolver, "1.0.168.192.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].target == "web4.foo.com"


class TestRawLaneDifferential:
    SHAPES = [
        (V6_REV, 1232),                              # v6 PTR hit, EDNS
        (V6_REV, None),                              # v6 PTR hit, no EDNS
        (reverse_name_for_ip("fd00::dead"), 1232),   # v6 PTR miss
        ("1.2.3.4.ip6.arpa", 1232),                  # malformed v6
        ("1.0.168.192.in-addr.arpa", 1232),          # v4 PTR hit
    ]

    def test_lane_matches_generic_path(self):
        store, cache = make_stack()
        lane = new_server(cache, lane=True)
        generic = new_server(cache, lane=False)
        for name, payload in self.SHAPES:
            wire = make_query(name, Type.PTR, qid=7,
                              edns_payload=payload).encode()
            a = ask_raw(lane, wire)
            b = ask_raw(generic, wire)
            assert a == b, f"lane diverged from generic for {name}"

    def test_lane_serves_v6_hit(self):
        store, cache = make_stack()
        lane = new_server(cache, lane=True)
        wire = make_query(V6_REV, Type.PTR, qid=7).encode()
        m = Message.decode(ask_raw(lane, wire))
        assert m.rcode == Rcode.NOERROR
        assert m.answers[0].target == "web6.foo.com"


class TestEndToEnd:
    def test_live_server_serves_v6_ptr_and_mutations(self):
        from tests.test_zone import udp_ask_raw

        async def run():
            store, cache = make_stack()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector())
            await server.start()
            try:
                hit = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query(V6_REV, Type.PTR, qid=5).encode()))
                # a v6 host added AFTER start rides the mutation path
                store.put_json("/com/foo/late6",
                               {"type": "host",
                                "host": {"address": "fd00::77"}})
                late = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query(reverse_name_for_ip("fd00::77"),
                               Type.PTR, qid=6).encode()))
                v4 = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("1.0.168.192.in-addr.arpa",
                               Type.PTR, qid=8).encode()))
                return hit, late, v4
            finally:
                await server.stop()

        hit, late, v4 = asyncio.run(run())
        assert hit.rcode == Rcode.NOERROR
        assert hit.answers[0].target == "web6.foo.com"
        assert late.rcode == Rcode.NOERROR
        assert late.answers[0].target == "late6.foo.com"
        assert v4.rcode == Rcode.NOERROR
        assert v4.answers[0].target == "web4.foo.com"
