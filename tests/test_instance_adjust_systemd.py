"""End-to-end tests for instance_adjust's systemd backend (-m systemd).

The reconciler drives the shipped deploy/systemd/binder@.service template
units through systemctl (ref: smf_adjust against libscf,
src/smf_adjust.c:866-931).  The container has no booted systemd, so these
tests install tests/fake_systemctl.py on PATH as ``systemctl`` and assert
both the resulting unit state and the exact command protocol: enable/start
on create, drop-in no-op detection, restart-only-when-running on config
change, disable-->wait-->delete on removal, and reset-failed + start as the
maintenance-restore analog (flush_status, src/smfx.c:242-336).
"""
import os
import shutil
import stat
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ADJUST = os.path.join(ROOT, "native", "build", "instance_adjust")
FAKE = os.path.join(ROOT, "tests", "fake_systemctl.py")

pytestmark = pytest.mark.skipif(
    not os.path.exists(ADJUST),
    reason="instance_adjust not built (make -C native)")


@pytest.fixture
def sd(tmp_path):
    """Fake-systemd environment: PATH shim + state/dropin/socket dirs."""
    bindir = tmp_path / "bin"
    bindir.mkdir()
    shim = bindir / "systemctl"
    shutil.copy(FAKE, shim)
    shim.chmod(shim.stat().st_mode | stat.S_IXUSR)

    env = dict(os.environ)
    env["PATH"] = f"{bindir}:{env['PATH']}"
    env["FAKE_SYSTEMD_STATE"] = str(tmp_path / "sysd")
    env["FAKE_SOCKDIR"] = str(tmp_path / "sockets")
    (tmp_path / "sysd").mkdir()

    class Env:
        dropins = tmp_path / "dropins"
        sockets = tmp_path / "sockets"
        state = tmp_path / "sysd"

        def adjust(self, count, base="binder", baseport=5301, extra=None,
                   expect_rc=0):
            cmd = [ADJUST, "-m", "systemd", "-D", str(self.dropins),
                   "-b", base, "-B", str(baseport), "-i", str(count),
                   "-d", str(self.sockets)]
            cmd += extra or []
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=60, env=env)
            assert proc.returncode == expect_rc, (proc.stdout, proc.stderr)
            return proc.stdout.splitlines()

        def log(self):
            try:
                with open(self.state / "log") as f:
                    return f.read().splitlines()
            except FileNotFoundError:
                return []

        def clear_log(self):
            (self.state / "log").write_text("")

        def unit_state(self, unit):
            try:
                with open(self.state / "units" / unit) as f:
                    return dict(line.strip().split("=", 1) for line in f)
            except FileNotFoundError:
                return None

        def set_unit_state(self, unit, state, enabled="1"):
            (self.state / "units").mkdir(exist_ok=True)
            (self.state / "units" / unit).write_text(
                f"state={state}\nenabled={enabled}\n")

        def dropin(self, port, base="binder"):
            path = (self.dropins / f"{base}@{port}.service.d"
                    / "50-instance.conf")
            try:
                return path.read_text()
            except FileNotFoundError:
                return None

    e = Env()
    e.env = env
    e.dropins.mkdir()
    return e


def test_create_enables_and_starts(sd):
    out = sd.adjust(2)
    assert "create binder-5301" in out and "create binder-5302" in out
    assert "start binder-5301" in out and "start binder-5302" in out
    for port in (5301, 5302):
        unit = f"binder@{port}.service"
        st = sd.unit_state(unit)
        assert st == {"state": "active", "enabled": "1"}
        conf = sd.dropin(port)
        assert f"Environment=BINDER_PORT={port}" in conf
        assert (f"Environment=BINDER_SOCKET_PATH={sd.sockets}/{port}"
                in conf)
        # the fake's start created the balancer socket
        assert (sd.sockets / str(port)).exists()
    log = sd.log()
    # drop-in edits must be followed by exactly one daemon-reload, and it
    # must precede the first start
    reloads = [i for i, l in enumerate(log) if l == "daemon-reload"]
    starts = [i for i, l in enumerate(log) if l.startswith("start ")]
    assert len(reloads) == 1 and starts and reloads[0] < starts[0]


def test_converged_run_is_noop(sd):
    sd.adjust(2)
    sd.clear_log()
    out = sd.adjust(2)
    assert "unchanged binder-5301" in out and "unchanged binder-5302" in out
    log = sd.log()
    for verb in ("start", "stop", "restart", "disable", "daemon-reload"):
        assert not any(l.startswith(verb) for l in log), log


def test_scale_down_disables_and_forgets(sd):
    sd.adjust(3)
    sd.clear_log()
    out = sd.adjust(1)
    assert "remove binder-5302" in out and "remove binder-5303" in out
    log = sd.log()
    assert "disable --now binder@5302.service" in log
    assert "disable --now binder@5303.service" in log
    assert "reset-failed binder@5302.service" in log
    for port in (5302, 5303):
        assert sd.dropin(port) is None
        assert sd.unit_state(f"binder@{port}.service") is None  # forgotten
    # survivor untouched
    assert sd.unit_state("binder@5301.service")["state"] == "active"
    assert "unchanged binder-5301" in out


def test_config_change_restarts_only_running(sd):
    sd.adjust(2)
    # stop 5302 behind the reconciler's back
    sd.set_unit_state("binder@5302.service", "inactive")
    sd.clear_log()
    # change the socket dir => every drop-in differs
    sd.sockets = sd.sockets.parent / "sockets2"
    out = sd.adjust(2)
    assert "configure binder-5301" in out and "configure binder-5302" in out
    log = sd.log()
    # running instance: restart (running-snapshot compare,
    # smf_adjust.c:384-448); stopped instance: plain start
    assert "restart binder@5301.service" in log
    assert "start binder@5302.service" in log
    assert "restart binder@5302.service" not in log
    assert "Environment=BINDER_SOCKET_PATH=" + str(sd.sockets) + "/5301" \
        in sd.dropin(5301)


def test_restore_from_failed(sd):
    sd.adjust(1)
    sd.set_unit_state("binder@5301.service", "failed")
    sd.clear_log()
    out = sd.adjust(1)
    assert "restore binder-5301" in out
    log = sd.log()
    ir = log.index("reset-failed binder@5301.service")
    assert any(l == "start binder@5301.service" for l in log[ir:])
    assert sd.unit_state("binder@5301.service")["state"] == "active"


def test_foreign_instance_sets_untouched(sd):
    # same-prefix different base, a non-numeric instance, and a
    # leading-zero tail (its parsed port would name a different unit)
    sd.set_unit_state("binder-blue@6001.service", "active")
    sd.set_unit_state("binder@abc.service", "active")
    sd.set_unit_state("binder@007.service", "active")
    sd.adjust(1)
    assert sd.unit_state("binder-blue@6001.service")["state"] == "active"
    assert sd.unit_state("binder@abc.service")["state"] == "active"
    assert sd.unit_state("binder@007.service")["state"] == "active"
    log = sd.log()
    assert not any("binder-blue@" in l or "binder@abc" in l
                   or "binder@007" in l or "binder@7.service" in l
                   for l in log if not l.startswith("list-"))


def test_wait_online_uses_socket(sd):
    out = sd.adjust(2, extra=["-w"])
    assert "start binder-5301" in out
    # -w returned success only because the sockets appeared
    assert (sd.sockets / "5301").exists() and (sd.sockets / "5302").exists()


def test_wait_online_fails_on_crashed_instance(sd):
    (sd.state / "fail-start").write_text("")
    sd.adjust(1, extra=["-w"], expect_rc=1)
    assert sd.unit_state("binder@5301.service")["state"] == "failed"


def test_refresh_hook_runs_once_on_change_only(sd, tmp_path):
    marker = tmp_path / "refreshed"
    hook = f"date >> {marker}"
    out = sd.adjust(2, extra=["-r", hook])
    assert "refresh-hook" in out
    assert len(marker.read_text().splitlines()) == 1
    out = sd.adjust(2, extra=["-r", hook])
    assert "refresh-hook" not in out
    assert len(marker.read_text().splitlines()) == 1


def test_dry_run_mutates_nothing(sd):
    out = sd.adjust(2, extra=["-n"])
    assert "create binder-5301" in out and "start binder-5301" in out
    assert sd.dropin(5301) is None
    assert sd.unit_state("binder@5301.service") is None
    log = sd.log()
    assert all(l.startswith(("list-", "show")) for l in log), log


def test_hand_started_unit_gets_dropin_and_restart(sd):
    # a unit someone started by hand runs with the unit-file default
    # environment; its first drop-in must restart it, or it keeps serving
    # on the stale socket path
    sd.set_unit_state("binder@5301.service", "active")
    sd.clear_log()
    out = sd.adjust(1)
    assert "create binder-5301" in out
    assert "restart binder@5301.service" in sd.log()


def test_removal_only_converge_still_reloads(sd):
    sd.adjust(2)
    sd.clear_log()
    sd.adjust(1)
    # no start/restart happened for the survivor, but the deleted drop-in
    # must still be flushed from systemd's cache
    log = sd.log()
    assert "daemon-reload" in log
    assert not any(l.startswith(("start ", "restart ")) for l in log)


def test_auto_with_statedir_never_touches_systemd(sd, tmp_path):
    # -m auto with an explicit -s must select the statedir backend even
    # where systemd is running; otherwise binder-topology on a systemd
    # host would reconcile the host's real units
    statedir = tmp_path / "state"
    cmd = [ADJUST, "-s", str(statedir), "-b", "binder", "-B", "5301",
           "-i", "1", "-e", "sleep 300"]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=60,
                          env=sd.env)
    try:
        assert proc.returncode == 0, proc.stderr
        assert (statedir / "binder-5301.props").exists()
        assert sd.log() == []   # no systemctl invocation at all
    finally:
        subprocess.run([ADJUST, "-s", str(statedir), "-b", "binder",
                        "-B", "5301", "-i", "0", "-e", "sleep 300"],
                       timeout=60, env=sd.env)


def test_discovery_via_enabled_units_without_dropin(sd):
    # an instance someone enabled by hand (no drop-in) is still discovered
    # and reconciled away when unplanned
    sd.set_unit_state("binder@5399.service", "active", enabled="1")
    out = sd.adjust(1)
    assert "remove binder-5399" in out
    assert sd.unit_state("binder@5399.service") is None
