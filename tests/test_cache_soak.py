"""Randomized churn soak for the mirror cache (SURVEY §7.3 hard part #1).

The reference's watch-tree diff logic is the piece the survey flags as
"must not leak watchers or serve stale reverse entries across session
resets" — and the piece the reference never tests.  This soak drives a
seeded random mix of creates/updates/deletes/subtree-removals/session
expiries against the fake store and, at checkpoints, asserts full
bidirectional consistency:

- every store node under the domain subtree is mirrored with its data;
- every mirrored node still exists in the store (no ghosts);
- the reverse (PTR) index is *exactly* the set of live host-type nodes
  with addresses (no stale entries, no misses);
- no watcher accumulates duplicate listeners (leak check);
- the mutation generation only moves forward.
"""
import json
import random

from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"
ROOT = "/com/foo"

HOST_TYPES = ["host", "db_host", "load_balancer", "rr_host"]


def record_for(rng, kind):
    if kind == "service":
        return {"type": "service",
                "service": {"srvce": "_s", "proto": "_tcp",
                            "port": rng.randrange(1, 65536)}}
    t = rng.choice(HOST_TYPES)
    return {"type": t,
            t: {"address": f"10.{rng.randrange(256)}.{rng.randrange(256)}"
                           f".{rng.randrange(1, 255)}"}}


def store_tree(store, path=ROOT):
    """(path -> data bytes) for the whole live subtree."""
    out = {}
    kids = store.get_children(path)
    if kids is None:
        return out
    out[path] = store.get_data(path)
    for kid in kids:
        out.update(store_tree(store, f"{path}/{kid}"))
    return out


def path_to_domain(path):
    assert path.startswith("/")
    return ".".join(reversed(path[1:].split("/")))


def assert_consistent(store, cache):
    tree = store_tree(store)

    # store -> mirror: every live node is mirrored with current data
    for path, raw in tree.items():
        domain = path_to_domain(path)
        node = cache.lookup(domain)
        assert node is not None, f"store node {path} not mirrored"
        expect = json.loads(raw.decode()) if raw else None
        # unparseable/scalar data keeps the previous value by design;
        # this soak only writes valid JSON objects, so expect equality
        assert node.data == expect, f"stale data at {path}"

    # mirror -> store: no ghost nodes
    live_domains = {path_to_domain(p) for p in tree}
    for domain in cache.nodes:
        assert domain in live_domains, f"ghost mirror node {domain}"

    # reverse index == exactly the live host-typed nodes
    expected_rev = {}
    for path, raw in tree.items():
        rec = json.loads(raw.decode()) if raw else None
        if not isinstance(rec, dict):
            continue
        rtype = rec.get("type")
        sub = rec.get(rtype) if isinstance(rtype, str) else None
        if rtype in {"db_host", "host", "load_balancer", "moray_host",
                     "redis_host", "ops_host", "rr_host"} \
                and isinstance(sub, dict) and sub.get("address"):
            # last writer wins on address collisions, matching the map
            expected_rev[sub["address"]] = path_to_domain(path)
    for ip, node in cache.rev_lookup.items():
        assert ip in expected_rev, f"stale reverse entry {ip}"
        assert node.domain in live_domains
    for ip in expected_rev:
        # collisions allowed: some live node owns the IP
        assert ip in cache.rev_lookup, f"missing reverse entry {ip}"

    # watcher-leak check: at most one listener per event per path
    for path, w in store._watchers.items():
        for event, listeners in w._listeners.items():
            assert len(listeners) <= 1, \
                f"{len(listeners)} {event} listeners leaked on {path}"


def test_churn_soak():
    rng = random.Random(0xB1DE2)
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.start_session()

    live_paths = []
    last_gen = cache.gen

    def new_path():
        # up to 3 levels below the root; parents auto-created by mkdirp
        depth = rng.randrange(1, 4)
        labels = [f"n{rng.randrange(30)}" for _ in range(depth)]
        return ROOT + "/" + "/".join(labels)

    for step in range(600):
        op = rng.random()
        if op < 0.45 or not live_paths:
            path = new_path()
            store.put_json(path, record_for(rng, rng.choice(
                ["service", "host"])))
            # mkdirp may have created intermediate nodes too
            p = path
            while p != ROOT:
                if p not in live_paths:
                    live_paths.append(p)
                p = p.rsplit("/", 1)[0]
        elif op < 0.70:
            path = rng.choice(live_paths)
            store.put_json(path, record_for(rng, rng.choice(
                ["service", "host"])))
        elif op < 0.85:
            path = rng.choice(live_paths)
            store.rmr(path)
            live_paths = [p for p in live_paths
                          if p != path and not p.startswith(path + "/")]
        elif op < 0.95:
            store.expire_session()
        else:
            # delete a leaf specifically (exercises the non-recursive path)
            leaves = [p for p in live_paths
                      if not any(q.startswith(p + "/") for q in live_paths)]
            if leaves:
                path = rng.choice(leaves)
                store.delete(path)
                live_paths.remove(path)

        assert cache.gen >= last_gen, "generation went backwards"
        last_gen = cache.gen

        if step % 50 == 49:
            assert_consistent(store, cache)

    assert_consistent(store, cache)
    # the root itself must have survived all of it
    assert cache.is_ready()


def test_churn_soak_with_sessions_only():
    """Pure session-churn: expire repeatedly over a static tree and
    confirm listeners/reverse entries stay exact (regression shape for
    the 2^depth rebind and listener-leak hazards)."""
    rng = random.Random(7)
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.start_session()
    for i in range(12):
        store.put_json(f"{ROOT}/svc{i % 4}/h{i}",
                       record_for(rng, "host"))
    for _ in range(25):
        store.expire_session()
        assert_consistent(store, cache)
