"""Tests for the native instance reconciler (native/adjust/instance_adjust).

Covers the reconciliation matrix of the reference's smf_adjust — which has
zero automated tests (SURVEY §4) — against real supervised processes.
Instances run `sleep` via the exec template; a later topology test boots
real binders through it.
"""
import os
import signal
import subprocess
import time

import pytest

ADJUST = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "build", "instance_adjust")

pytestmark = pytest.mark.skipif(
    not os.path.exists(ADJUST),
    reason="instance_adjust not built (make -C native)")


def run_adjust(statedir, count, base="binder", baseport=5301,
               exec_tmpl="sleep 300", sockdir=None, extra=None):
    cmd = [ADJUST, "-s", str(statedir), "-b", base, "-B", str(baseport),
           "-i", str(count), "-e", exec_tmpl]
    if sockdir:
        cmd += ["-d", str(sockdir)]
    cmd += extra or []
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    return proc.returncode, proc.stdout.splitlines(), proc.stderr


def read_pid(statedir, name):
    with open(os.path.join(statedir, f"{name}.pid")) as f:
        return int(f.read().strip())


def alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    try:  # zombies answer kill(0) but are dead
        with open(f"/proc/{pid}/stat") as f:
            return f.read().rsplit(")", 1)[1].split()[0] != "Z"
    except (OSError, IndexError):
        return False


def kill_all(statedir):
    for fn in os.listdir(statedir):
        if fn.endswith(".pid"):
            try:
                pid = int(open(os.path.join(statedir, fn)).read())
                os.kill(pid, signal.SIGKILL)
            except (ValueError, ProcessLookupError, OSError):
                pass


@pytest.fixture()
def statedir(tmp_path):
    d = str(tmp_path / "state")
    yield d
    kill_all(d) if os.path.isdir(d) else None


class TestReconcile:
    def test_scale_up_from_zero(self, statedir):
        rc, out, err = run_adjust(statedir, 3)
        assert rc == 0, err
        assert sorted(l for l in out if l.startswith("create")) == [
            "create binder-5301", "create binder-5302", "create binder-5303"]
        for port in (5301, 5302, 5303):
            pid = read_pid(statedir, f"binder-{port}")
            assert alive(pid)

    def test_idempotent_second_run(self, statedir):
        run_adjust(statedir, 2)
        rc, out, err = run_adjust(statedir, 2)
        assert rc == 0
        # pure no-op: nothing created/configured/started/removed
        assert [l for l in out if not l.startswith("unchanged")] == []
        assert len([l for l in out if l.startswith("unchanged")]) == 2

    def test_no_op_preserves_processes(self, statedir):
        run_adjust(statedir, 2)
        pids = [read_pid(statedir, f"binder-{p}") for p in (5301, 5302)]
        run_adjust(statedir, 2)
        assert [read_pid(statedir, f"binder-{p}")
                for p in (5301, 5302)] == pids

    def test_scale_down_removes_surplus(self, statedir):
        run_adjust(statedir, 3)
        doomed = read_pid(statedir, "binder-5303")
        rc, out, _ = run_adjust(statedir, 1)
        assert rc == 0
        assert "remove binder-5302" in out and "remove binder-5303" in out
        time.sleep(0.2)
        assert not alive(doomed)
        assert not os.path.exists(
            os.path.join(statedir, "binder-5303.props"))
        # survivor untouched
        assert alive(read_pid(statedir, "binder-5301"))

    def test_config_change_restarts(self, statedir):
        run_adjust(statedir, 1)
        old_pid = read_pid(statedir, "binder-5301")
        rc, out, _ = run_adjust(statedir, 1, exec_tmpl="sleep 301")
        assert rc == 0
        assert "configure binder-5301" in out
        new_pid = read_pid(statedir, "binder-5301")
        assert new_pid != old_pid and alive(new_pid)
        time.sleep(0.2)
        assert not alive(old_pid)

    def test_dead_instance_restored(self, statedir):
        run_adjust(statedir, 1)
        pid = read_pid(statedir, "binder-5301")
        os.kill(pid, signal.SIGKILL)
        time.sleep(0.2)
        rc, out, _ = run_adjust(statedir, 1)
        assert rc == 0
        assert "restore binder-5301" in out
        assert alive(read_pid(statedir, "binder-5301"))

    def test_exec_template_substitution(self, statedir, tmp_path):
        sockdir = str(tmp_path / "socks")
        run_adjust(statedir, 1, exec_tmpl="echo port=%P sock=%S name=%N; "
                                          "sleep 300", sockdir=sockdir)
        time.sleep(0.3)
        log = open(os.path.join(statedir, "binder-5301.log")).read()
        assert f"port=5301 sock={sockdir}/5301 name=binder-5301" in log

    def test_refresh_hook_runs_on_change_only(self, statedir, tmp_path):
        marker = str(tmp_path / "marker")
        hook = f"touch {marker}"
        run_adjust(statedir, 1, extra=["-r", hook])
        assert os.path.exists(marker)
        os.unlink(marker)
        run_adjust(statedir, 1, extra=["-r", hook])  # no-op run
        assert not os.path.exists(marker)

    def test_dry_run_touches_nothing(self, statedir):
        rc, out, _ = run_adjust(statedir, 2, extra=["-n"])
        assert rc == 0
        assert "create binder-5301" in out
        assert not os.path.exists(
            os.path.join(statedir, "binder-5301.props"))

    def test_count_cap(self, statedir):
        rc, _, err = run_adjust(statedir, 33)
        assert rc == 2 and "count > 32" in err

    def test_wait_online_with_socket(self, statedir, tmp_path):
        sockdir = str(tmp_path / "socks")
        # instance that creates its socket after a moment, like a real
        # binder bringing up its balancer listener
        tmpl = ("sh -c 'sleep 0.5; python3 -c \"import socket; "
                "s=socket.socket(socket.AF_UNIX); s.bind(\\\"%S\\\"); "
                "import time; time.sleep(300)\"'")
        rc, out, _ = run_adjust(statedir, 1, exec_tmpl=tmpl,
                                sockdir=sockdir, extra=["-w"])
        assert rc == 0
        assert os.path.exists(os.path.join(sockdir, "5301"))

    def test_wait_online_fails_for_crashing_instance(self, statedir):
        rc, out, err = run_adjust(statedir, 1, exec_tmpl="false",
                                  extra=["-w"])
        assert rc == 1
        assert "did not come online" in err

    def test_prefixed_base_not_claimed(self, statedir):
        """binder must not tear down binder-blue's instances."""
        # create a foreign instance set sharing the prefix
        run_adjust(statedir, 1, base="binder-blue", baseport=6301)
        blue_pid = read_pid(statedir, "binder-blue-6301")
        rc, out, _ = run_adjust(statedir, 1)  # base=binder
        assert rc == 0
        assert not any("binder-blue" in l for l in out)
        assert alive(blue_pid)
