"""Independent-implementation conformance tests (VERDICT r2 missing 2).

Every other protocol test uses this repo's codec on both ends, so a
symmetric encode/decode bug could pass the whole suite.  The reference
broke that symmetry by scraping real dig(1) against a real ZooKeeper
(reference test/dig.js:109-134, test/helper.js:53-61).  This module does
it four ways, each independent of our codec to a different degree:

1. **RFC golden byte-vectors** (always run): wire bytes hand-assembled
   from RFC 1035/2782/6891 — encode must produce them exactly, decode
   must read them exactly, including a compression-pointer answer our
   encoder would lay out differently.
2. **dig(1)** against a live server (skipped when dig is absent —
   this image ships none; lights up wherever bind-utils exists).
3. **glibc stub resolver** (`getent hosts`) against a live server on
   127.0.0.1:53 — opt-in via BINDER_LIBC_CONFORMANCE=1 because it
   rewrites /etc/resolv.conf (restored afterwards) and binds port 53.
   `make ci` sets the flag automatically when running as root, so the
   gated pipeline always exercises an independent DNS client; plain
   `make test` leaves it opt-in.
4. **Real ZooKeeper** for the store client when ZK_HOST is set (the
   reference's own test precondition, README.md:63-65).
"""
import asyncio
import errno
import ipaddress
import os
import shutil
import subprocess
import sys

import pytest

from binder_tpu.dns import (
    ARecord,
    Message,
    OPTRecord,
    PTRRecord,
    Rcode,
    SOARecord,
    SRVRecord,
    Type,
    make_query,
)
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


# ---------------------------------------------------------------------------
# 1. RFC golden byte-vectors


class TestGoldenVectors:
    """Wire bytes written by hand from the RFCs, never produced by the
    code under test."""

    # RFC 1035 §4.1: standard query, id 0x1234, RD, QDCOUNT 1,
    # QNAME example.com, QTYPE A, QCLASS IN
    QUERY_A = bytes.fromhex(
        "1234"              # id
        "0100"              # flags: RD
        "0001" "0000" "0000" "0000"
        "07" "6578616d706c65" "03" "636f6d" "00"   # 7example3com0
        "0001" "0001"       # A IN
    )

    def test_query_encode_matches_rfc_bytes(self):
        got = make_query("example.com", Type.A, qid=0x1234, rd=True,
                         edns_payload=None).encode()
        assert got == self.QUERY_A

    def test_query_decode_matches_rfc_fields(self):
        m = Message.decode(self.QUERY_A)
        assert m.id == 0x1234
        assert m.qr is False and m.rd is True and m.opcode == 0
        assert len(m.questions) == 1
        q = m.questions[0]
        assert (q.name, q.qtype, q.qclass) == ("example.com", 1, 1)
        assert not m.answers and not m.authorities and not m.additionals

    def test_mixed_case_query_normalizes_on_encode(self):
        # RFC 1035 §2.3.3 case-insensitivity: our encoder lowercases
        assert make_query("ExAmPlE.CoM", Type.A, qid=0x1234, rd=True,
                          edns_payload=None).encode() == self.QUERY_A

    # RFC 1035 §4.1.4 compression: response whose answer name is a
    # pointer to offset 12 (0xC00C) — our encoder also compresses, but
    # decode here is driven purely by the hand bytes
    RESPONSE_A = bytes.fromhex(
        "1234"              # id
        "8580"              # QR AA RD RA, rcode 0
        "0001" "0001" "0000" "0000"
        "07" "6578616d706c65" "03" "636f6d" "00" "0001" "0001"
        "c00c"              # answer name = pointer to QNAME
        "0001" "0001"       # A IN
        "0000012c"          # TTL 300
        "0004" "5db8d822"   # rdlen 4, 93.184.216.34
    )

    def test_response_decode_with_compression_pointer(self):
        m = Message.decode(self.RESPONSE_A)
        assert m.qr is True and m.aa is True and m.ra is True
        assert m.rcode == Rcode.NOERROR
        (a,) = m.answers
        assert isinstance(a, ARecord)
        assert a.name == "example.com"
        assert a.ttl == 300
        assert a.address == "93.184.216.34"

    def test_response_reencode_roundtrip(self):
        # not byte-identical (compression layout is the encoder's), but
        # a second decode must reproduce identical structures
        m = Message.decode(self.RESPONSE_A)
        again = Message.decode(m.encode())
        assert again.answers == m.answers
        assert again.questions == m.questions
        assert (again.id, again.rcode, again.aa) == (m.id, m.rcode, m.aa)

    # RFC 2782 SRV: _pg._tcp.svc.foo.com SRV 10 20 5432 lb0.svc.foo.com
    # — target written UNcompressed per the RFC's erratum guidance
    RESPONSE_SRV = bytes.fromhex(
        "0007" "8400"
        "0001" "0001" "0000" "0000"
        "035f7067" "045f746370" "03737663" "03666f6f" "03636f6d" "00"
        "0021" "0001"                       # SRV IN
        "c00c"                              # answer name -> question
        "0021" "0001" "0000001e"            # SRV IN TTL 30
        "0017"                              # rdlen 23
        "000a" "0014" "1538"                # prio 10 weight 20 port 5432
        "036c6230" "03737663" "03666f6f" "03636f6d" "00"
    )

    def test_srv_decode_rfc2782(self):
        m = Message.decode(self.RESPONSE_SRV)
        (srv,) = m.answers
        assert isinstance(srv, SRVRecord)
        assert srv.name == "_pg._tcp.svc.foo.com"
        assert (srv.priority, srv.weight, srv.port) == (10, 20, 5432)
        assert srv.target == "lb0.svc.foo.com"
        assert srv.ttl == 30

    def test_srv_encode_target_uncompressed(self):
        # RFC 2782: the target must not be compressed even when the
        # suffix already appeared; assert on raw bytes
        m = Message(id=7, qr=True, aa=True)
        m.questions = list(Message.decode(self.RESPONSE_SRV).questions)
        m.answers = [SRVRecord(name="_pg._tcp.svc.foo.com", ttl=30,
                               priority=10, weight=20, port=5432,
                               target="lb0.svc.foo.com")]
        wire = m.encode()
        assert bytes.fromhex("036c62300373766303666f6f03636f6d00") in wire

    # RFC 1035 §3.3.12/§3.5: PTR response for 10.1.2.3
    RESPONSE_PTR = bytes.fromhex(
        "0009" "8400"
        "0001" "0001" "0000" "0000"
        "0133" "0132" "0131" "023130"       # 3.2.1.10
        "07696e2d61646472" "046172706100"   # in-addr.arpa
        "000c" "0001"
        "c00c" "000c" "0001" "0000001e"
        "000d"                              # rdlen 13
        "0377656203666f6f03636f6d00"        # web.foo.com
    )

    def test_ptr_decode(self):
        m = Message.decode(self.RESPONSE_PTR)
        (ptr,) = m.answers
        assert isinstance(ptr, PTRRecord)
        assert ptr.name == "3.2.1.10.in-addr.arpa"
        assert ptr.target == "web.foo.com"

    # RFC 1035 §3.3.13 SOA (as the reference serves for NODATA/negative
    # answers) — rdata with two names then five 32-bit fields
    RESPONSE_SOA = bytes.fromhex(
        "000b" "8400"
        "0001" "0000" "0001" "0000"
        "03666f6f03636f6d00" "0001" "0001"
        "c00c" "0006" "0001" "00000e10"
        "0029"                              # rdlen 41
        "026e7303666f6f03636f6d00"          # mname ns.foo.com (12)
        "07616461646d696e00"                # rname adadmin. (9)
        "78512ec6" "00000e10" "00000384" "00093a80" "0000003c"
    )

    def test_soa_decode(self):
        m = Message.decode(self.RESPONSE_SOA)
        (soa,) = m.authorities
        assert isinstance(soa, SOARecord)
        assert soa.mname == "ns.foo.com"
        assert soa.rname == "adadmin"
        assert soa.serial == 0x78512EC6
        assert (soa.refresh, soa.retry) == (3600, 900)
        assert (soa.expire, soa.minimum) == (604800, 60)

    # RFC 6891 EDNS0 OPT: root name, type 41, class = payload 1232,
    # ttl = ext-rcode/version/flags zero, rdlen 0
    QUERY_EDNS = bytes.fromhex(
        "0042" "0000"
        "0001" "0000" "0000" "0001"
        "0377656203666f6f03636f6d00" "0001" "0001"
        "00" "0029" "04d0" "00000000" "0000"
    )

    def test_edns_query_encode(self):
        got = make_query("web.foo.com", Type.A, qid=0x42,
                         edns_payload=1232).encode()
        assert got == self.QUERY_EDNS

    def test_edns_query_decode(self):
        m = Message.decode(self.QUERY_EDNS)
        (opt,) = m.additionals
        assert isinstance(opt, OPTRecord)
        assert opt.udp_payload_size == 1232
        assert opt.version == 0 and not opt.dnssec_ok
        assert not opt.has_options


# ---------------------------------------------------------------------------
# live-server fixtures shared by the dig and libc tiers


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "10.7.7.7"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    store.put_json("/com/foo/svc/lb0",
                   {"type": "load_balancer",
                    "load_balancer": {"address": "10.0.1.1"}})
    store.start_session()
    return store, cache


async def serve(coro_fn, *, port=0, host="127.0.0.1"):
    """Boot a BinderServer on the fake store and run coro_fn(server)."""
    _, cache = fixture_store()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host=host, port=port,
                          collector=MetricsCollector())
    await server.start()
    try:
        return await coro_fn(server)
    finally:
        await server.stop()


# ---------------------------------------------------------------------------
# 2. dig(1) — the reference's own conformance client


DIG = shutil.which("dig")


@pytest.mark.skipif(DIG is None, reason="dig(1) not installed")
class TestDigConformance:
    def test_exchanges(self):
        async def run(server):
            port = server.udp_port
            loop = asyncio.get_running_loop()

            def digq(*args):
                return subprocess.run(
                    [DIG, "@127.0.0.1", "-p", str(port), "+time=3",
                     "+tries=1", *args],
                    capture_output=True, text=True, timeout=15).stdout

            out = await loop.run_in_executor(None, digq, "web.foo.com", "A")
            assert "status: NOERROR" in out and "10.7.7.7" in out
            out = await loop.run_in_executor(
                None, digq, "_pg._tcp.svc.foo.com", "SRV")
            assert "status: NOERROR" in out and "5432" in out \
                and "lb0.svc.foo.com" in out
            out = await loop.run_in_executor(None, digq, "-x", "10.7.7.7")
            assert "web.foo.com" in out
            out = await loop.run_in_executor(
                None, digq, "other.example", "A")
            assert "status: REFUSED" in out
            out = await loop.run_in_executor(
                None, digq, "+tcp", "web.foo.com", "A")
            assert "status: NOERROR" in out and "10.7.7.7" in out

        asyncio.run(serve(run))


# ---------------------------------------------------------------------------
# 3. glibc stub resolver (getent) — opt-in, rewrites /etc/resolv.conf


LIBC_GATE = os.environ.get("BINDER_LIBC_CONFORMANCE") == "1" \
    and os.geteuid() == 0


class resolv_override:
    """Crash-safe /etc/resolv.conf override for the libc-backed tiers:
    if a previous run was SIGKILLed between the rewrite and the
    restore, the ``.binder-backup`` beside it holds the true original
    and is the source of truth, never re-snapshotted over."""

    RESOLV = "/etc/resolv.conf"
    BACKUP = RESOLV + ".binder-backup"

    def __init__(self, content: str) -> None:
        self.content = content
        self.saved = None

    def __enter__(self) -> "resolv_override":
        if os.path.exists(self.BACKUP):
            self.saved = open(self.BACKUP).read()
            with open(self.RESOLV, "w") as f:
                f.write(self.saved)
        else:
            self.saved = open(self.RESOLV).read()
            with open(self.BACKUP, "w") as f:
                f.write(self.saved)
        with open(self.RESOLV, "w") as f:
            f.write(self.content)
        return self

    def __exit__(self, *exc) -> None:
        with open(self.RESOLV, "w") as f:
            f.write(self.saved)
        os.unlink(self.BACKUP)


@pytest.mark.skipif(
    not LIBC_GATE,
    reason="set BINDER_LIBC_CONFORMANCE=1 (requires root; rewrites "
           "/etc/resolv.conf and binds 127.0.0.1:53)")
class TestLibcConformance:
    def test_getent_a_and_ptr(self):
        async def run(server):
            loop = asyncio.get_running_loop()

            def getent(*args):
                return subprocess.run(["getent", *args],
                                      capture_output=True, text=True,
                                      timeout=15)

            # forward A through gethostbyname/getaddrinfo
            out = await loop.run_in_executor(
                None, getent, "ahostsv4", "web.foo.com")
            assert "10.7.7.7" in out.stdout, out
            # reverse PTR through gethostbyaddr
            out = await loop.run_in_executor(
                None, getent, "hosts", "10.7.7.7")
            assert "web.foo.com" in out.stdout, out

        try:
            with resolv_override("nameserver 127.0.0.1\n"
                                 "options timeout:2 attempts:1\n"):
                asyncio.run(serve(run, port=53))
        except OSError as e:
            if e.errno == errno.EADDRINUSE:
                pytest.skip("127.0.0.1:53 already bound on this host")
            raise


@pytest.mark.skipif(
    not LIBC_GATE,
    reason="set BINDER_LIBC_CONFORMANCE=1 (requires root; rewrites "
           "/etc/resolv.conf and binds 127.0.0.1:53)")
class TestLibresolvConformance:
    """glibc's res_query + ns_parserr (tools/libresolv_probe.py) as the
    independent client for the record types getent cannot reach: SRV
    answer content (target/port/priority), SRV additionals, and the
    EDNS OPT echo — the coverage the reference got from dig
    (reference test/dig.js:109-134, test/service.test.js:162-177)."""

    @staticmethod
    def _probe(name, qtype):
        out = subprocess.run(
            [sys.executable, os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "libresolv_probe.py"), name, qtype],
            capture_output=True, text=True, timeout=20)
        assert out.returncode == 0, (out.stdout, out.stderr)
        import json as _json
        return _json.loads(out.stdout)

    def test_srv_a_ptr_and_edns_echo(self):
        async def run(server):
            loop = asyncio.get_running_loop()
            probe = self._probe

            # SRV: answer content parsed by glibc, not our codec
            r = await loop.run_in_executor(
                None, probe, "_pg._tcp.svc.foo.com", "SRV")
            assert r["ancount"] == 1, r
            srv = r["answers"][0]
            assert srv["type"] == 33
            assert srv["port"] == 5432
            assert srv["priority"] == 0
            assert srv["target"] == "lb0.svc.foo.com"
            # the SRV additional carries the target's A record
            adds = [a for a in r["additional"] if a["type"] == 1]
            assert adds and adds[0]["name"] == "lb0.svc.foo.com"
            assert adds[0]["address"] == "10.0.1.1"
            # glibc sent EDNS (options edns0): the OPT must be echoed
            # with our payload ceiling
            assert r["opt"] == {"payload": 1232}, r

            # A and PTR through the same independent parser
            r = await loop.run_in_executor(None, probe,
                                           "web.foo.com", "A")
            assert [a["address"] for a in r["answers"]] == ["10.7.7.7"]
            assert r["answers"][0]["ttl"] == 30
            assert r["opt"] == {"payload": 1232}
            r = await loop.run_in_executor(
                None, probe, "7.7.7.10.in-addr.arpa", "PTR")
            assert [a["target"] for a in r["answers"]] == ["web.foo.com"]

        try:
            with resolv_override("nameserver 127.0.0.1\n"
                                 "options timeout:2 attempts:1 edns0\n"):
                asyncio.run(serve(run, port=53))
        except OSError as e:
            if e.errno == errno.EADDRINUSE:
                pytest.skip("127.0.0.1:53 already bound on this host")
            raise


# ---------------------------------------------------------------------------
# 4. real ZooKeeper for the store client


ZK_HOST = os.environ.get("ZK_HOST")


@pytest.mark.skipif(ZK_HOST is None,
                    reason="set ZK_HOST to run against a real ZooKeeper "
                           "(the reference's test precondition, "
                           "README.md:63-65)")
class TestRealZooKeeper:
    def test_session_reads_writes_watches(self):
        from binder_tpu.store.zk_client import ZKClient

        async def run():
            port = int(os.environ.get("ZK_PORT", "2181"))
            client = ZKClient(address=ZK_HOST, port=port,
                              session_timeout_ms=10000)
            client.start()
            deadline = asyncio.get_running_loop().time() + 10
            while not client.is_connected():
                assert asyncio.get_running_loop().time() < deadline, \
                    f"no ZK session to {ZK_HOST}:{port}"
                await asyncio.sleep(0.05)

            base = "/binder-conformance"
            await client.mkdirp(base + "/web", b'{"type":"host"}')
            assert await client.get_data(base + "/web") == \
                b'{"type":"host"}'
            kids = await client.get_children(base)
            assert "web" in kids

            # a watched read must see a real server's notification
            ev = asyncio.Event()
            w = client.watcher(base)
            w.on("children", lambda kids: ev.set())
            await client.create(base + "/second", b"x")
            await asyncio.wait_for(ev.wait(), 10)

            await client.delete(base + "/second")
            await client.delete(base + "/web")
            await client.delete(base)
            client.close()

        asyncio.run(run())


def test_ip_vectors_sanity():
    # guard the golden hex: the A rdata above really is 93.184.216.34
    assert ipaddress.ip_address(bytes.fromhex("5db8d822")).exploded == \
        "93.184.216.34"
