"""Shipped systemd unit files must parse under systemd's own verifier.

Separate from test_instance_adjust_systemd.py so the check runs even
where the native reconciler binary is not built — the unit files are
deploy artifacts, not native-build outputs.
"""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SYSTEMD_ANALYZE = shutil.which("systemd-analyze")


# override the module-level ADJUST skip: this test only needs the unit
# files and systemd-analyze, not the native binary
@pytest.mark.skipif(SYSTEMD_ANALYZE is None,
                    reason="systemd-analyze not installed")
def test_shipped_units_verify():
    """The shipped unit files must parse cleanly under systemd's own
    verifier.  The only accepted diagnostic is the User=nobody warning —
    deliberate reference parity (method_credential user=nobody,
    smf/manifests/multi-binder.xml.in)."""
    deploy = os.path.join(ROOT, "deploy", "systemd")
    units = sorted(fn for fn in os.listdir(deploy)
                   if fn.endswith((".service", ".path", ".target")))
    assert units, deploy
    proc = subprocess.run(
        [SYSTEMD_ANALYZE, "verify"]
        + [os.path.join(deploy, u) for u in units],
        capture_output=True, text=True, timeout=60)
    bad = [line for line in (proc.stdout + proc.stderr).splitlines()
           if line.strip()
           and "Special user nobody configured" not in line
           # ExecStart paths live under /opt/binder, which only exists
           # on an installed host — their absence here is environmental;
           # any OTHER missing command (a typo'd path) must still fail
           and not ("is not executable: No such file" in line
                    and "/opt/binder/" in line)]
    assert not bad, bad
