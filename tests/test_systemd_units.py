"""Shipped systemd unit files must parse under systemd's own verifier.

Separate from test_instance_adjust_systemd.py so the check runs even
where the native reconciler binary is not built — the unit files are
deploy artifacts, not native-build outputs.
"""
import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SYSTEMD_ANALYZE = shutil.which("systemd-analyze")


# override the module-level ADJUST skip: this test only needs the unit
# files and systemd-analyze, not the native binary
@pytest.mark.skipif(SYSTEMD_ANALYZE is None,
                    reason="systemd-analyze not installed")
def test_shipped_units_verify():
    """The shipped unit files must parse cleanly under systemd's own
    verifier.  The only accepted diagnostic is the User=nobody warning —
    deliberate reference parity (method_credential user=nobody,
    smf/manifests/multi-binder.xml.in)."""
    deploy = os.path.join(ROOT, "deploy", "systemd")
    units = sorted(fn for fn in os.listdir(deploy)
                   if fn.endswith((".service", ".path", ".target")))
    assert units, deploy
    proc = subprocess.run(
        [SYSTEMD_ANALYZE, "verify"]
        + [os.path.join(deploy, u) for u in units],
        capture_output=True, text=True, timeout=60)
    bad = [line for line in (proc.stdout + proc.stderr).splitlines()
           if line.strip()
           and "Special user nobody configured" not in line
           # ExecStart paths live under /opt/binder, which only exists
           # on an installed host — their absence here is environmental;
           # any OTHER missing command (a typo'd path) must still fail
           and not ("is not executable: No such file" in line
                    and "/opt/binder/" in line)]
    assert not bad, bad


def _unit(name: str) -> str:
    with open(os.path.join(ROOT, "deploy", "systemd", name)) as f:
        return f.read()


def test_config_bootstrap_wiring():
    """The config-agent-analog flow must be wired end to end (reference:
    config-agent renders sapi_manifests/binder at zone setup and on
    metadata change, then restarts the consuming service):

      metadata.json --(binder-config.service, oneshot, pre-instance)-->
      etc/config.json --(binder@ ordered After it)--> running instance,
      with binder-config.path re-rendering on metadata change.
    """
    cfg = _unit("binder-config.service")
    # renders through the one shipped renderer, gated on metadata
    assert "binder-config-render" in cfg
    assert "ConditionPathExists=/opt/binder/etc/metadata.json" in cfg
    assert "Type=oneshot" in cfg
    # an active oneshot swallows path-unit triggers: the unit must
    # return to inactive after each render so PathChanged re-fires
    assert "RemainAfterExit=" not in cfg
    # config-agent restarts consumers only on rendered-content change —
    # the restart must ride the renderer's change-gated hook, not an
    # unconditional ExecStartPost
    assert "-c 'systemctl try-restart \"binder@*.service\"'" in cfg
    assert "ExecStartPost" not in cfg

    # instances start only after the bootstrap had its chance; Wants
    # (not Requires) so hand-written-config hosts still start
    inst = _unit("binder@.service")
    assert "Wants=binder-config.service" in inst
    assert "After=binder-config.service" in inst

    # metadata change re-triggers the render
    path = _unit("binder-config.path")
    assert "PathChanged=/opt/binder/etc/metadata.json" in path
    assert "Unit=binder-config.service" in path


def test_rsync_to_helper():
    """Dev-deploy helper parity (reference tools/rsync-to): push the
    working copy, then clear-or-restart the service instances."""
    p = os.path.join(ROOT, "tools", "rsync-to")
    assert os.access(p, os.X_OK), "tools/rsync-to must be executable"
    with open(p) as f:
        body = f.read()
    # maintenance-clear analog precedes the restart, and only running
    # instances restart (the reference's svcadm clear-vs-restart split)
    assert body.index("reset-failed") < body.index("try-restart")
    # never ship local secrets/config over a dev sync
    assert "--exclude /etc/config.json" in body
    # bash syntax must hold (the helper is untestable end-to-end here)
    proc = subprocess.run(["bash", "-n", p], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
