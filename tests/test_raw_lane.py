"""Differential tests for the raw resolve lane (BinderServer._raw_lane).

The lane re-implements the single-question A/IN resolve by direct wire
assembly; these tests prove it cannot diverge from the generic path:

- every query shape is driven through BOTH paths over the same store
  fixture and the response wires must be byte-identical (the request
  wires here are lowercase, so the lane's case-preserving question echo
  matches the generic encoder's output exactly);
- answer-cache entries created by one path must be served by the other
  (key-layout parity both directions);
- shapes the lane must decline (other qtypes, EDNS options, compressed
  qnames, service/database records, recursion handoffs, garbage) fall
  back and still produce the generic path's answer.
"""
import random

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.query import QueryCtx
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


def make_fixture():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/ttl1",
                   {"type": "host", "ttl": 120,
                    "host": {"address": "10.0.0.1"}})
    store.put_json("/com/foo/ttl2",
                   {"type": "host", "ttl": 120,
                    "host": {"address": "10.0.0.2", "ttl": 77}})
    store.put_json("/com/foo/badaddr",
                   {"type": "host", "host": {"address": "not-an-ip"}})
    store.put_json("/com/foo/short",
                   {"type": "host", "host": {"address": "10.1"}})
    store.put_json("/com/foo/noaddr", {"type": "host", "host": {}})
    store.put_json("/com/foo/badrec", {"type": "host"})
    store.put_json("/com/foo/db", {
        "type": "database",
        "database": {"primary": "tcp://pg.example.com:5432/x"},
    })
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(3):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


def new_server(cache, lane: bool, **kw):
    srv = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                       datacenter_name="coal",
                       collector=MetricsCollector(), query_log=False, **kw)
    # deterministic shuffle so both servers' service answers rotate
    # identically (the differential compares exact bytes)
    srv.resolver.rng = random.Random(42)
    if not lane:
        srv.engine.raw_lane = None
    return srv


def ask_raw(server, wire: bytes, protocol: str = "udp",
            client_transport=None):
    """Push one request wire through the engine; return the response."""
    out = []
    server.engine._handle_raw(wire, ("192.0.2.9", 1234), protocol,
                              out.append, client_transport=client_transport)
    assert len(out) == 1, f"expected one response, got {len(out)}"
    return out[0]


QUERY_SHAPES = [
    # (name, qtype, rd, edns_payload)
    ("web.foo.com", Type.A, False, 1232),        # host hit, EDNS
    ("web.foo.com", Type.A, True, 1232),         # RD set
    ("web.foo.com", Type.A, False, None),        # no EDNS
    ("web.foo.com", Type.A, False, 4097),        # payload clamped to 4096
    ("web.foo.com", Type.A, False, 100),         # payload below 512 floor
    ("ttl1.foo.com", Type.A, False, 1232),       # record-level TTL
    ("ttl2.foo.com", Type.A, False, 1232),       # sub-record TTL wins
    ("nope.foo.com", Type.A, False, 1232),       # miss -> REFUSED
    ("web.example.org", Type.A, False, 1232),    # outside suffix -> REFUSED
    ("foo.com", Type.A, False, 1232),            # bare domain -> REFUSED
    ("web.foo.com.foo.com", Type.A, False, 1232),      # doubled suffix
    ("web.foo.com.coal.foo.com", Type.A, False, 1232),  # dc-doubled suffix
    ("badaddr.foo.com", Type.A, False, 1232),    # invalid address
    ("short.foo.com", Type.A, False, 1232),      # non-canonical address
    ("noaddr.foo.com", Type.A, False, 1232),     # record without address
    ("badrec.foo.com", Type.A, False, 1232),     # invalid record shape
    ("db.foo.com", Type.A, False, 1232),         # database type (declined)
    ("svc.foo.com", Type.A, False, 1232),        # service A (declined)
    ("_pg._tcp.svc.foo.com", Type.SRV, False, 1232),   # SRV (declined)
    ("1.0.168.192.in-addr.arpa", Type.PTR, False, 1232),  # PTR hit
    ("1.0.168.192.in-addr.arpa", Type.PTR, False, None),  # PTR, no EDNS
    ("1.0.168.192.in-addr.arpa", Type.PTR, True, 1232),   # PTR, RD set
    ("2.0.0.10.in-addr.arpa", Type.PTR, False, 1232),  # PTR sub-TTL wins
    ("9.9.9.9.in-addr.arpa", Type.PTR, False, 1232),   # PTR miss REFUSED
    ("web.foo.com", Type.PTR, False, 1232),      # not a reverse name
    ("1.2.3.4.ip6.arpa", Type.PTR, False, 1232),  # v6 reverse REFUSED
    ("5.1.0.168.192.in-addr.arpa", Type.PTR, False, 1232),  # 5 octets
    ("192.in-addr.arpa", Type.PTR, False, 1232),  # partial reverse
    ("web.foo.com", Type.AAAA, False, 1232),     # unsupported qtype
]


class TestDifferential:
    def test_wire_identical_across_paths(self):
        """Every shape must produce byte-identical responses from the
        lane-enabled and generic-only servers (ids patched equal)."""
        for name, qtype, rd, payload in QUERY_SHAPES:
            _, cache_a = make_fixture()
            _, cache_b = make_fixture()
            # fresh servers per shape: no cross-shape cache pollution
            srv_lane = new_server(cache_a, lane=True)
            srv_gen = new_server(cache_b, lane=False)
            wire = make_query(name, qtype, qid=77, rd=rd,
                              edns_payload=payload).encode()
            got_lane = ask_raw(srv_lane, wire)
            got_gen = ask_raw(srv_gen, wire)
            assert got_lane == got_gen, (
                f"{name}/{Type.name(qtype)} rd={rd} edns={payload}: "
                f"lane={got_lane.hex()} generic={got_gen.hex()}")

    def test_store_down_servfail_identical(self):
        for lane in (True, False):
            # no session ever established: the mirror never becomes
            # ready, so resolution must SERVFAIL on both paths
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            srv = new_server(cache, lane=lane)
            wire = make_query("web.foo.com", Type.A, qid=5).encode()
            resp = Message.decode(ask_raw(srv, wire))
            assert resp.rcode == Rcode.SERVFAIL

    def test_cache_key_parity_lane_fills_generic_hits(self):
        """A lane-resolved entry must be a generic-path cache hit — for
        every EDNS payload edge (none, below floor, typical, above
        clamp), so a drifting floor/clamp copy splits the cache and
        fails here."""
        for payload in (None, 100, 511, 512, 1232, 4096, 4097):
            _, cache = make_fixture()
            srv = new_server(cache, lane=True)
            wire = make_query("web.foo.com", Type.A, qid=9,
                              edns_payload=payload).encode()
            first = ask_raw(srv, wire)
            # disable the lane; the generic path must hit the same entry
            srv.engine.raw_lane = None
            hits_before = srv.answer_cache.hits
            second = ask_raw(srv, wire)
            assert srv.answer_cache.hits == hits_before + 1, payload
            assert first == second, payload

    def test_cache_key_parity_generic_fills_lane_hits(self):
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        srv.engine.raw_lane = None
        wire = make_query("web.foo.com", Type.A, qid=9,
                          edns_payload=1232).encode()
        first = ask_raw(srv, wire)
        srv.engine.raw_lane = srv._raw_lane
        hits_before = srv.answer_cache.hits
        second = ask_raw(srv, wire)
        assert srv.answer_cache.hits == hits_before + 1
        assert first == second

    def test_fastpath_key_parity(self):
        """The lane's inline C-cache key must equal _fastpath_key's."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        for name, qtype, rd, payload in QUERY_SHAPES:
            if qtype != Type.A:
                continue
            wire = make_query(name, qtype, qid=3, rd=rd,
                              edns_payload=payload).encode()
            req = Message.decode(wire)
            q = QueryCtx(req, ("192.0.2.9", 1), "udp", lambda b: None,
                         raw=wire)
            expect = srv._fastpath_key(q)
            # the lane builds through the same shared builder; prove the
            # component path equals the Message path
            from binder_tpu.server import _fastpath_key_parts
            off = 12
            while wire[off]:
                off += 1 + wire[off]
            off += 1
            lane_key = _fastpath_key_parts(
                req.rd, req.edns is not None, req.max_udp_payload(),
                1, 1, wire[12:off].lower())
            assert lane_key == expect, name


class TestLaneBehavior:
    def test_case_preserving_question_echo(self):
        """dns0x20: the lane echoes the question with the request's
        original case (an improvement over the generic lowercase echo)."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        q = make_query("WeB.FoO.cOm", Type.A, qid=2).encode()
        # make_query normalizes, so craft mixed case directly in the wire
        q = q.replace(b"web", b"WeB").replace(b"foo", b"FoO")
        resp = ask_raw(srv, q)
        assert b"WeB" in resp and b"FoO" in resp
        msg = Message.decode(resp)
        assert msg.rcode == Rcode.NOERROR
        assert str(msg.answers[0].address) == "192.168.0.1"

    def test_each_requester_gets_its_own_case_back(self):
        """A mixed-case fill must not leak its case into other clients'
        responses (cache stores the question lowercased; hits splice the
        requester's own bytes back in)."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        mixed = make_query("web.foo.com", Type.A, qid=2).encode() \
            .replace(b"web", b"WeB").replace(b"foo", b"FoO")
        lower = make_query("web.foo.com", Type.A, qid=3).encode()
        first = ask_raw(srv, mixed)           # fills the cache
        assert b"WeB" in first
        second = ask_raw(srv, lower)          # cache hit
        assert b"WeB" not in second and b"web" in second
        third = ask_raw(srv, mixed)           # hit, case restored
        assert b"WeB" in third
        # all three carry the same answer
        for r in (first, second, third):
            m = Message.decode(r)
            assert str(m.answers[0].address) == "192.168.0.1"

    def test_lane_declines_to_generic_on_edns_options(self):
        """An OPT with options (a DNS cookie) must take the generic
        path and still be answered."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        wire = make_query("web.foo.com", Type.A, qid=4,
                          edns_payload=1232).encode()
        # splice a COOKIE option into the OPT RDATA
        cookie = b"\x00\x0a\x00\x08" + b"\x01" * 8
        assert wire.endswith(b"\x00\x00")   # RDLEN 0
        wire = wire[:-2] + len(cookie).to_bytes(2, "big") + cookie
        resp = Message.decode(ask_raw(srv, wire))
        assert resp.rcode == Rcode.NOERROR
        assert str(resp.answers[0].address) == "192.168.0.1"

    def test_lane_declines_compressed_qname(self):
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        # header + qname containing a (self-referential, invalid)
        # compression pointer: both paths must refuse gracefully —
        # generic drops it as malformed (FORMERR)
        wire = (b"\x00\x07\x00\x00\x00\x01\x00\x00\x00\x00\x00\x00"
                + b"\xc0\x0c\x00\x01\x00\x01")
        out = []
        srv.engine._handle_raw(wire, ("192.0.2.9", 1), "udp", out.append)
        if out:   # FORMERR response is acceptable; silence is too
            assert Message.decode(out[0]).rcode == Rcode.FORMERR

    def test_mutation_invalidates_lane_cache(self):
        """Generation bump: a store mutation must stop the lane serving
        the stale cached answer."""
        store, cache = make_fixture()
        srv = new_server(cache, lane=True)
        wire = make_query("web.foo.com", Type.A, qid=11).encode()
        first = Message.decode(ask_raw(srv, wire))
        assert str(first.answers[0].address) == "192.168.0.1"
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "192.168.0.2"}})
        second = Message.decode(ask_raw(srv, wire))
        assert str(second.answers[0].address) == "192.168.0.2"

    def test_lane_serves_rotating_service_hits(self):
        """Once the generic path completes a rotatable service-A entry,
        lane hits must rotate through the variants like respond_raw."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        wire = make_query("svc.foo.com", Type.A, qid=1).encode()
        seen = set()
        # 8 variants must be collected by the generic path first, then
        # hits rotate; drive enough queries to see rotation
        for _ in range(24):
            msg = Message.decode(ask_raw(srv, wire))
            assert msg.rcode == Rcode.NOERROR
            seen.add(tuple(str(a.address) for a in msg.answers))
        assert len(seen) > 1, "no rotation observed"

    def test_metrics_recorded_for_lane_queries(self):
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        wire = make_query("web.foo.com", Type.A, qid=6).encode()
        ask_raw(srv, wire)
        ask_raw(srv, wire)   # second one is a lane cache hit
        text = srv.collector.expose()
        assert 'binder_requests_completed{type="A"} 2' in text
        assert "binder_answer_cache_hits 1" in text

    def test_balancer_protocol_lane(self):
        """Lane handles balancer-framed queries; TCP client transport
        keys separately from UDP (truncation semantics) in the PYTHON
        answer cache.  The native wire-serve entry would intercept the
        repeat before it reaches the lane (correct — fitting responses
        are transport-identical; tests/test_zone.py covers that lane),
        so it is detached here to exercise the Python keying."""
        _, cache = make_fixture()
        srv = new_server(cache, lane=True)
        srv.engine.fastpath = None
        wire = make_query("web.foo.com", Type.A, qid=8).encode()
        u = ask_raw(srv, wire, protocol="balancer", client_transport="udp")
        t = ask_raw(srv, wire, protocol="balancer", client_transport="tcp")
        assert Message.decode(u).answers and Message.decode(t).answers
        # distinct cache keys: one entry per transport semantics
        assert len(srv.answer_cache._entries) == 2
