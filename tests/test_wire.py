"""Unit tests for the DNS wire codec (binder_tpu/dns/wire.py).

The reference has no tests at this layer (it trusts the mname npm package);
these tests are the protocol-level replacement for its dig(1) text-scraping
(reference test/dig.js:109-134, SURVEY §4).
"""
import struct

import pytest

from binder_tpu.dns import (
    AAAARecord,
    ARecord,
    CNAMERecord,
    Message,
    OPTRecord,
    PTRRecord,
    Question,
    RawRecord,
    Rcode,
    SOARecord,
    SRVRecord,
    TXTRecord,
    Type,
    WireError,
    ip_from_reverse_name,
    make_query,
    normalize_name,
    reverse_name_for_ip,
)
from binder_tpu.dns.wire import decode_name, encode_name


def roundtrip(msg: Message) -> Message:
    return Message.decode(msg.encode())


class TestNames:
    def test_encode_decode_simple(self):
        buf = bytearray()
        encode_name("foo.example.com", buf)
        name, off = decode_name(bytes(buf), 0)
        assert name == "foo.example.com"
        assert off == len(buf)

    def test_normalization(self):
        assert normalize_name("FoO.CoM.") == "foo.com"

    def test_root_name(self):
        buf = bytearray()
        encode_name("", buf)
        assert bytes(buf) == b"\x00"
        assert decode_name(b"\x00", 0) == ("", 1)

    def test_compression_shrinks_repeats(self):
        offsets = {}
        buf = bytearray(b"\x00" * 12)  # fake header
        encode_name("a.foo.com", buf, offsets)
        size_first = len(buf)
        encode_name("b.foo.com", buf, offsets)
        # second name should be label 'b' + 2-byte pointer = 1+1+2
        assert len(buf) - size_first == 4
        name, _ = decode_name(bytes(buf), size_first)
        assert name == "b.foo.com"

    def test_pointer_loop_rejected(self):
        # pointer at offset 0 pointing to itself is a forward/self pointer
        data = b"\xc0\x00"
        with pytest.raises(WireError):
            decode_name(data, 0)

    def test_forward_pointer_rejected(self):
        data = b"\xc0\x04\x00\x00\x01a\x00"
        with pytest.raises(WireError):
            decode_name(data, 0)

    def test_label_too_long(self):
        buf = bytearray()
        with pytest.raises(WireError):
            encode_name("a" * 64 + ".com", buf)

    def test_name_too_long(self):
        buf = bytearray()
        with pytest.raises(WireError):
            encode_name(".".join(["abcdefgh"] * 40), buf)

    def test_truncated_label(self):
        with pytest.raises(WireError):
            decode_name(b"\x05ab", 0)


class TestRecords:
    def test_a_roundtrip(self):
        msg = Message(id=7, qr=True, aa=True)
        msg.questions.append(Question("host.foo.com", Type.A))
        msg.answers.append(ARecord(name="host.foo.com", ttl=30,
                                   address="10.0.0.1"))
        out = roundtrip(msg)
        assert out.id == 7 and out.qr and out.aa
        assert out.answers[0].address == "10.0.0.1"
        assert out.answers[0].ttl == 30
        assert out.answers[0].name == "host.foo.com"

    def test_aaaa_roundtrip(self):
        msg = Message()
        msg.answers.append(AAAARecord(name="h.foo.com", ttl=60,
                                      address="fd00::1"))
        out = roundtrip(msg)
        assert out.answers[0].address == "fd00::1"

    def test_srv_roundtrip(self):
        msg = Message()
        msg.answers.append(SRVRecord(name="_http._tcp.svc.foo.com", ttl=60,
                                     priority=0, weight=10, port=8080,
                                     target="h1.svc.foo.com"))
        out = roundtrip(msg)
        srv = out.answers[0]
        assert (srv.priority, srv.weight, srv.port) == (0, 10, 8080)
        assert srv.target == "h1.svc.foo.com"

    def test_ptr_roundtrip(self):
        msg = Message()
        msg.answers.append(PTRRecord(name="1.0.0.10.in-addr.arpa", ttl=30,
                                     target="host.foo.com"))
        out = roundtrip(msg)
        assert out.answers[0].target == "host.foo.com"

    def test_soa_roundtrip(self):
        msg = Message()
        msg.authorities.append(SOARecord(
            name="foo.com", ttl=60, mname="ns.foo.com",
            rname="hostmaster.foo.com", serial=12, refresh=3600,
            retry=600, expire=86400, minimum=60))
        out = roundtrip(msg)
        soa = out.authorities[0]
        assert soa.mname == "ns.foo.com" and soa.serial == 12
        assert soa.minimum == 60

    def test_txt_roundtrip(self):
        msg = Message()
        msg.answers.append(TXTRecord(name="t.foo.com", ttl=5,
                                     texts=("hello", "world")))
        out = roundtrip(msg)
        assert out.answers[0].texts == ("hello", "world")

    def test_cname_roundtrip(self):
        msg = Message()
        msg.answers.append(CNAMERecord(name="www.foo.com", ttl=60,
                                       target="host.foo.com"))
        out = roundtrip(msg)
        assert out.answers[0].target == "host.foo.com"

    def test_unknown_type_kept_raw(self):
        msg = Message()
        msg.answers.append(RawRecord(name="x.foo.com", ttl=1,
                                     rtype_code=99, rdata=b"\x01\x02"))
        out = roundtrip(msg)
        rec = out.answers[0]
        assert isinstance(rec, RawRecord)
        assert rec.rtype_code == 99 and rec.rdata == b"\x01\x02"

    def test_multi_answer_compression(self):
        """Round-robin responses repeat the qname — compression must engage."""
        msg = Message(qr=True)
        msg.questions.append(Question("svc.foo.com", Type.A))
        for i in range(8):
            msg.answers.append(ARecord(name="svc.foo.com", ttl=30,
                                       address=f"10.0.0.{i + 1}"))
        wire = msg.encode()
        # uncompressed: each answer name alone would be 13 bytes; pointer is 2
        assert len(wire) < 12 + 17 + 8 * (2 + 10 + 4) + 20
        out = Message.decode(wire)
        assert len(out.answers) == 8
        assert {a.address for a in out.answers} == {
            f"10.0.0.{i + 1}" for i in range(8)}


class TestMessage:
    def test_query_flags(self):
        q = make_query("a.foo.com", Type.A, qid=1234, rd=True)
        out = roundtrip(q)
        assert out.id == 1234 and out.rd and not out.qr
        assert out.questions[0].name == "a.foo.com"
        assert out.questions[0].qtype == Type.A

    def test_edns_payload(self):
        q = make_query("a.foo.com", Type.A, edns_payload=1400)
        out = roundtrip(q)
        assert out.edns is not None
        assert out.edns.udp_payload_size == 1400
        assert out.max_udp_payload() == 1400

    def test_no_edns_default_512(self):
        q = make_query("a.foo.com", Type.A, edns_payload=None)
        assert q.max_udp_payload() == 512

    def test_rcode_roundtrip(self):
        msg = Message(qr=True, rcode=Rcode.REFUSED)
        out = roundtrip(msg)
        assert out.rcode == Rcode.REFUSED

    def test_truncation_sets_tc(self):
        msg = Message(qr=True)
        msg.questions.append(Question("svc.foo.com", Type.A))
        for i in range(100):
            msg.answers.append(ARecord(name="svc.foo.com", ttl=30,
                                       address=f"10.0.{i // 250}.{i % 250}"))
        wire = msg.encode(max_size=512)
        assert len(wire) <= 512
        out = Message.decode(wire)
        assert out.tc and len(out.answers) == 0

    def test_short_message_rejected(self):
        with pytest.raises(WireError):
            Message.decode(b"\x00\x01")

    def test_garbage_counts_rejected(self):
        hdr = struct.pack(">HHHHHH", 1, 0, 50, 0, 0, 0)
        with pytest.raises(WireError):
            Message.decode(hdr)


class TestReverseNames:
    def test_ipv4_reverse(self):
        assert reverse_name_for_ip("10.1.2.3") == "3.2.1.10.in-addr.arpa"
        assert ip_from_reverse_name("3.2.1.10.in-addr.arpa") == "10.1.2.3"

    def test_ipv6_reverse_roundtrip(self):
        name = reverse_name_for_ip("fd00::1")
        assert name.endswith(".ip6.arpa")
        assert ip_from_reverse_name(name) == "fd00::1"

    def test_invalid_reverse_names(self):
        # mirrors reference REFUSED cases (test/host.test.js:133-167)
        assert ip_from_reverse_name("777.1.2.3.in-addr.arpa") is None
        assert ip_from_reverse_name("2.3.4.in-addr.arpa") is None
        assert ip_from_reverse_name("a.b.c.d.in-addr.arpa") is None
        assert ip_from_reverse_name("host.foo.com") is None


class TestReviewRegressions:
    """Regressions from the first code-review pass."""

    def test_ip6_arpa_multi_char_nibble_rejected(self):
        name = "ab." + ".".join(["0"] * 31) + ".ip6.arpa"
        assert ip_from_reverse_name(name) is None

    def test_srv_target_past_rdlen_rejected(self):
        msg = Message()
        msg.answers.append(SRVRecord(name="s.foo.com", ttl=1, priority=0,
                                     weight=0, port=1, target="t.foo.com"))
        wire = bytearray(msg.encode())
        # find the rdlen field and shrink it so the target overflows rdata
        # header(12) + name + type/class/ttl(8) + rdlen(2)
        name_len = len(b"\x01s\x03foo\x03com\x00")
        rdlen_at = 12 + name_len + 8
        struct.pack_into(">H", wire, rdlen_at, 7)
        with pytest.raises(WireError):
            Message.decode(bytes(wire))

    def test_truncation_keeps_opt(self):
        msg = Message(qr=True)
        msg.questions.append(Question("svc.foo.com", Type.A))
        msg.additionals.append(OPTRecord(name="", ttl=0,
                                         udp_payload_size=1232))
        for i in range(100):
            msg.answers.append(ARecord(name="svc.foo.com", ttl=30,
                                       address=f"10.0.0.{i % 250}"))
        out = Message.decode(msg.encode(max_size=512))
        assert out.tc and out.edns is not None
        assert out.edns.udp_payload_size == 1232

    def test_trailing_garbage_rejected(self):
        wire = make_query("a.foo.com", Type.A, qid=1).encode()
        with pytest.raises(WireError):
            Message.decode(wire + b"\xde\xad\xbe\xef")

    def test_short_form_address_rejected(self):
        msg = Message()
        msg.answers.append(ARecord(name="h.foo.com", ttl=1, address="10.1"))
        with pytest.raises(WireError):
            msg.encode()
