"""Live introspection & health layer (binder_tpu/introspect).

What this pins down end to end:

- the status snapshot is schema-complete under the fake store (every
  section and key the validator requires, live over HTTP) and stays
  consistent while the mirror churns under it;
- the store session state machine distinguishes never-connected from
  session-lost, with measured (not inferred) disconnected_seconds —
  for both FakeStore and the real ZK wire client;
- the flight recorder is bounded, ordered, and dumps on SIGUSR2 with
  multiple distinct event types;
- the loop-lag watchdog observes real stalls into
  binder_loop_lag_seconds and fires loop-stall events;
- the in-flight query table exposes a live query's trace ID and
  current phase, and bin/bstat renders all of it from the endpoint;
- the balancer stats fold re-exports stage_cycles monotonically,
  including across a balancer restart.
"""
import asyncio
import contextlib
import importlib.machinery
import importlib.util
import io
import json
import os
import signal
import socket
import threading
import time
import urllib.request

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.introspect import (BalancerStatsFold, FlightRecorder,
                                   Introspector, LoopLagWatchdog)
from binder_tpu.metrics.collector import MetricsCollector, MetricsServer
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.store.zk_client import ZKClient
from binder_tpu.store.zk_testserver import ZKTestServer
from tools.lint import validate_exposition, validate_status_snapshot

DOMAIN = "foo.com"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_fixture(recorder=None, collector=None):
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "10.0.0.1"}})
    store.start_session()
    return store, cache


async def start_server(recorder=None, collector=None, **kw):
    store, cache = make_fixture(recorder=recorder, collector=collector)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1",
                          port=0, collector=collector or MetricsCollector(),
                          query_log=False, flight_recorder=recorder,
                          **kw)
    await server.start()
    return server, store


async def udp_ask(port, name, qtype, qid=1, timeout=5.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return Message.decode(data)


def via_generic_path(server):
    """Force every query through the generic Python resolve path: the
    raw lane and native fast path would otherwise answer simple A/IN
    shapes before the (test-instrumented) resolver ever runs."""
    server.engine.raw_lane = None
    server.engine.fastpath = None


def hold_resolver(server):
    """Replace the resolver's handle with one that parks the query
    until the returned event is set — a real, observable in-flight
    query with a phase stamp."""
    release = asyncio.Event()

    def slow_handle(query):
        query.stamp("store-lookup")

        async def wait():
            await asyncio.wait_for(release.wait(), 10)
            query.set_error(Rcode.REFUSED)
            query.respond()

        return wait()

    server.resolver.handle = slow_handle
    return release


class TestSnapshotSchema:
    def test_schema_complete_under_fake_store(self):
        async def run():
            recorder = FlightRecorder()
            collector = MetricsCollector()
            server, _store = await start_server(recorder=recorder,
                                                collector=collector)
            watchdog = LoopLagWatchdog(collector=collector,
                                       recorder=recorder, interval=0.01)
            watchdog.start()
            intro = Introspector(server=server, recorder=recorder,
                                 watchdog=watchdog, collector=collector)
            await udp_ask(server.udp_port, f"web.{DOMAIN}", Type.A)
            await asyncio.sleep(0.05)
            snap = intro.snapshot()
            assert validate_status_snapshot(snap) == []
            assert snap["store"]["state"] == "connected"
            assert snap["store"]["disconnected_seconds"] == 0.0
            assert snap["mirror"]["ready"] is True
            assert snap["mirror"]["nodes"] == 2          # root + web
            assert snap["mirror"]["reverse_entries"] == 1
            assert snap["mirror"]["staleness_seconds"] is not None
            assert snap["loop"]["samples"] >= 1
            # JSON round trip (what the HTTP route serves)
            assert validate_status_snapshot(
                json.loads(json.dumps(snap, default=str))) == []
            watchdog.stop()
            await server.stop()
        asyncio.run(run())

    def test_never_connected_vs_lost(self):
        # the distinction is_connected() alone could not express
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        intro = Introspector(zk_cache=cache, store=store)
        snap = intro.snapshot()
        assert snap["store"]["state"] == "never-connected"
        assert snap["store"]["disconnected_seconds"] is None
        assert snap["mirror"]["staleness_seconds"] is None

        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.1"}})
        store.start_session()
        assert intro.snapshot()["store"]["state"] == "connected"

        store.lose_session()
        time.sleep(0.02)
        snap = intro.snapshot()
        assert snap["store"]["state"] == "degraded"
        # exact measured loss age, and the mirror keeps serving (aging)
        assert 0.0 < snap["store"]["disconnected_seconds"] < 5.0
        assert snap["mirror"]["ready"] is True
        assert snap["mirror"]["staleness_seconds"] > 0.0
        edges = [(t["from"], t["to"]) for t in snap["store"]["transitions"]]
        assert ("never-connected", "connected") in edges
        assert ("connected", "degraded") in edges

    def test_recursion_peer_section(self):
        async def run():
            from binder_tpu.recursion import Recursion
            _store, cache = make_fixture()
            rec = Recursion(zk_cache=cache, dns_domain=DOMAIN,
                            datacenter_name="dc0",
                            ufds={"dcs": {"dc1": ["10.9.9.9"]}})
            await rec.wait_ready()
            intro = Introspector(zk_cache=cache, recursion=rec)
            snap = intro.snapshot()
            assert validate_status_snapshot(snap) == []
            r = snap["recursion"]
            assert r["ready"] is True
            assert r["datacenters"] == {"dc1": ["10.9.9.9"]}
            assert r["peer_count"] == 1
            assert r["last_refresh_age_seconds"] is not None
            assert r["case_mismatch_drops"] == 0
            await rec.close()
        asyncio.run(run())

    def test_consistent_under_concurrent_mutation(self):
        async def run():
            collector = MetricsCollector()
            server, store = await start_server(collector=collector)
            intro = Introspector(server=server, collector=collector)
            intro.set_loop(asyncio.get_running_loop())

            stop = threading.Event()
            failures = []

            def scrape():
                # foreign thread: every snapshot must route through the
                # loop and come back schema-valid, never torn/raising
                while not stop.is_set():
                    try:
                        errs = validate_status_snapshot(intro.snapshot())
                        if errs:
                            failures.append(errs)
                            return
                    except Exception as e:  # noqa: BLE001
                        failures.append(e)
                        return

            t = threading.Thread(target=scrape)
            t.start()
            try:
                for i in range(300):
                    store.put_json(
                        f"/com/foo/n{i % 20}",
                        {"type": "host",
                         "host": {"address": f"10.1.0.{i % 250 + 1}"}})
                    if i % 25 == 0:
                        store.expire_session()   # full rebuild mid-scrape
                        await asyncio.sleep(0)
            finally:
                stop.set()
                t.join(5)
            assert not failures, failures[:1]
            await server.stop()
        asyncio.run(run())


class TestSessionFlapSoak:
    """Rapid connected -> degraded -> connected cycling (ISSUE 4
    satellite): the mirror generation and epoch must be MONOTONIC
    across every flap (a regression would re-validate stale cached
    answers), the transition history stays bounded, and the snapshot
    stays schema-valid throughout."""

    def test_mirror_generation_monotonic_under_flapping(self):
        store, cache = make_fixture()
        intro = Introspector(zk_cache=cache, store=store)
        gens, epochs = [cache.gen], [cache.epoch]
        for cycle in range(25):
            store.lose_session()
            gens.append(cache.gen)
            epochs.append(cache.epoch)
            store.put_json(
                "/com/foo/web",
                {"type": "host",
                 "host": {"address": f"10.0.0.{cycle % 250 + 2}"}})
            store.start_session()    # full rebind (watch storm shape)
            gens.append(cache.gen)
            epochs.append(cache.epoch)
            snap = intro.snapshot()
            assert validate_status_snapshot(snap) == []
            assert snap["store"]["state"] == "connected"
        assert gens == sorted(gens), "mirror gen must be monotonic"
        assert epochs == sorted(epochs), "epoch must be monotonic"
        # every reconnect was a distinct establishment + rebuild epoch
        assert store.session_establishments == 26
        assert cache.epoch >= 26
        # bounded history: 25 flap cycles over a 64-edge deque
        assert len(store.session_transitions()) <= 64
        # and the mirror converged on the final write
        node = cache.lookup(f"web.{DOMAIN}")
        assert node.data["host"]["address"] == "10.0.0.26"

    def test_flapping_with_policy_keeps_degraded_state_fresh(self):
        """The degradation state machine rides the flaps without
        sticking: after the last reconnect it reads fresh and the
        one-hot session metric agrees."""
        from binder_tpu.policy import DegradationPolicy
        collector = MetricsCollector()
        store, cache = make_fixture(collector=collector)
        pol = DegradationPolicy(store=store, zk_cache=cache,
                                max_staleness_s=60.0,
                                collector=collector)
        for _ in range(10):
            store.lose_session()
            assert pol.mode() == "stale-serving"
            store.start_session()
            assert pol.mode() == "fresh"
        assert collector.get("binder_degraded_state").value() == 0.0
        assert collector.get("binder_zk_session_state") is None or True
        snap = pol.introspect()
        assert snap["state"] == "fresh"
        # 20 edges recorded, bounded by the history deque
        assert len(snap["transitions"]) <= 64


class TestZKSessionStates:
    def test_never_connected_without_ensemble(self):
        async def run():
            # nothing listening: the client keeps retrying but never
            # had a session — not the same thing as having lost one
            probe = socket.socket()
            probe.bind(("127.0.0.1", 0))
            free_port = probe.getsockname()[1]
            probe.close()
            client = ZKClient(address="127.0.0.1", port=free_port,
                              session_timeout_ms=2000)
            client.start()
            await asyncio.sleep(0.3)
            assert not client.is_connected()
            assert client.session_state() == "never-connected"
            assert client.disconnected_seconds() is None
            client.close()
            assert client.session_state() == "closed"
            await asyncio.sleep(0)
        asyncio.run(run())

    def test_lost_session_is_degraded_with_measured_age(self):
        async def run():
            server = ZKTestServer()
            await server.start()
            recorder = FlightRecorder()
            client = ZKClient(address="127.0.0.1", port=server.port,
                              session_timeout_ms=2000, recorder=recorder)
            client.start()
            deadline = asyncio.get_running_loop().time() + 5
            while not client.is_connected():
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert client.session_state() == "connected"
            assert client.disconnected_seconds() == 0.0
            assert client.session_establishments == 1

            await server.stop()          # the ensemble goes away
            t0 = time.monotonic()
            deadline = asyncio.get_running_loop().time() + 10
            while client.session_state() != "degraded":
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.02)
            assert not client.is_connected()
            disc = client.disconnected_seconds()
            assert disc is not None
            assert disc <= time.monotonic() - t0 + 1.0
            types = {e["type"] for e in recorder.events()}
            assert "session-transition" in types
            client.close()
            await asyncio.sleep(0)
        asyncio.run(run())


class TestFlightRecorder:
    def test_bounded_and_ordered(self):
        rec = FlightRecorder(capacity=16)
        for i in range(50):
            rec.record("slow-query", n=i)
        evs = rec.events()
        assert len(evs) == 16
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs) and seqs[-1] == 50
        assert evs[0]["n"] == 34          # oldest rotated out
        assert rec.recorded == 50 and rec.dropped == 34
        assert rec.stats()["by_type"] == {"slow-query": 50}
        assert rec.events(last=4) == evs[-4:]

    def test_dump_file(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        rec.record("loop-stall", lag_s=0.5)
        path = rec.dump(str(tmp_path / "flight.json"))
        with open(path) as f:
            payload = json.load(f)
        assert payload["pid"] == os.getpid()
        assert payload["events"][0]["type"] == "loop-stall"
        # the dump itself is recorded (postmortem shows who dumped)
        assert rec.events()[-1]["type"] == "dump"

    def test_sigusr2_dump_replays_event_types(self, tmp_path):
        async def run():
            path = str(tmp_path / "sig.json")
            recorder = FlightRecorder()
            loop = asyncio.get_running_loop()
            recorder.install_sigusr2(loop, path=path)
            try:
                # drive ≥3 distinct event types through real wiring
                store, cache = make_fixture(recorder=recorder)
                store.expire_session()           # session-transition +
                await asyncio.sleep(0)           # mirror-rebuild
                watchdog = LoopLagWatchdog(recorder=recorder,
                                           interval=0.01,
                                           stall_threshold=0.05)
                watchdog._observe(0.2, time.monotonic())  # loop-stall
                os.kill(os.getpid(), signal.SIGUSR2)
                deadline = loop.time() + 5
                while not os.path.exists(path):
                    assert loop.time() < deadline
                    await asyncio.sleep(0.02)
                with open(path) as f:
                    payload = json.load(f)
                types = {e["type"] for e in payload["events"]}
                assert {"session-transition", "mirror-rebuild",
                        "loop-stall"} <= types
                seqs = [e["seq"] for e in payload["events"]]
                assert seqs == sorted(seqs)
            finally:
                loop.remove_signal_handler(signal.SIGUSR2)
        asyncio.run(run())

    def test_watch_storm_event(self, monkeypatch):
        monkeypatch.setattr(MirrorCache, "STORM_THRESHOLD", 10)
        recorder = FlightRecorder()
        store, _cache = make_fixture(recorder=recorder)
        for i in range(30):
            store.put_json("/com/foo/web",
                           {"type": "host",
                            "host": {"address": f"10.0.0.{i + 1}"}})
        storms = [e for e in recorder.events() if e["type"] == "watch-storm"]
        assert storms and storms[0]["events"] >= 10


class TestWatchdog:
    def test_stall_observed_and_recorded(self):
        async def run():
            recorder = FlightRecorder()
            collector = MetricsCollector()
            watchdog = LoopLagWatchdog(collector=collector,
                                       recorder=recorder, interval=0.01,
                                       stall_threshold=0.05)
            watchdog.start()
            await asyncio.sleep(0.05)
            time.sleep(0.15)             # block the loop: a real stall
            await asyncio.sleep(0.05)
            watchdog.stop()
            assert watchdog.samples >= 2
            assert watchdog.max_lag >= 0.05
            assert watchdog.stalls >= 1
            stalls = [e for e in recorder.events()
                      if e["type"] == "loop-stall"]
            assert stalls and stalls[0]["lag_s"] >= 0.05
            text = collector.expose()
            assert "binder_loop_lag_seconds_bucket" in text
            assert validate_exposition(text) == []
        asyncio.run(run())


class TestInflightAndBstat:
    def test_inflight_table_and_bstat_output(self):
        async def run():
            recorder = FlightRecorder()
            collector = MetricsCollector()
            server, _store = await start_server(recorder=recorder,
                                                collector=collector)
            watchdog = LoopLagWatchdog(collector=collector,
                                       recorder=recorder, interval=0.02)
            watchdog.start()
            intro = Introspector(server=server, recorder=recorder,
                                 watchdog=watchdog, collector=collector)
            intro.set_loop(asyncio.get_running_loop())
            metrics = MetricsServer(collector, address="127.0.0.1",
                                    port=0)
            metrics.status_source = intro.snapshot
            metrics.start()

            via_generic_path(server)
            release = hold_resolver(server)
            ask = asyncio.ensure_future(
                udp_ask(server.udp_port, f"held.{DOMAIN}", Type.A))
            deadline = asyncio.get_running_loop().time() + 5
            while not server.engine.inflight:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.01)

            snap = intro.snapshot()
            assert validate_status_snapshot(snap) == []
            assert snap["inflight"]["count"] == 1
            q = snap["inflight"]["queries"][0]
            assert q["trace"] and q["name"] == f"held.{DOMAIN}"
            assert q["phase"] == "store-lookup"
            assert q["age_ms"] >= 0.0
            # the gauge sees it too
            assert "binder_inflight_queries" in collector.expose()
            assert collector.get(
                "binder_inflight_queries").value() == 1.0

            # live-endpoint check: fetch + schema validator (the tier-1
            # wiring the CI satellite asks for), then bstat against it
            url = f"http://127.0.0.1:{metrics.port}"
            raw = await asyncio.to_thread(lambda: urllib.request.urlopen(
                f"{url}/status", timeout=5).read())
            assert validate_status_snapshot(json.loads(raw)) == []
            kang = await asyncio.to_thread(lambda: urllib.request.urlopen(
                f"{url}/kang/snapshot", timeout=5).read())
            assert validate_status_snapshot(json.loads(kang)) == []

            loader = importlib.machinery.SourceFileLoader(
                "bstat", os.path.join(REPO, "bin", "bstat"))
            spec = importlib.util.spec_from_loader("bstat", loader)
            bstat = importlib.util.module_from_spec(spec)
            loader.exec_module(bstat)
            out = io.StringIO()

            def run_bstat():
                with contextlib.redirect_stdout(out):
                    return bstat.main([f"127.0.0.1:{metrics.port}"])

            assert await asyncio.to_thread(run_bstat) == 0
            text = out.getvalue()
            assert "CONNECTED" in text            # ZK session state
            assert "last change" in text          # mirror staleness age
            assert q["trace"] in text             # in-flight trace ID
            assert "phase=store-lookup" in text   # current phase

            release.set()
            reply = await ask
            assert reply.rcode == Rcode.REFUSED
            await asyncio.sleep(0.05)
            assert not server.engine.inflight
            watchdog.stop()
            await server.stop()
            metrics.stop()
        asyncio.run(run())

    def test_slow_query_event(self, monkeypatch):
        async def run():
            import binder_tpu.server as server_mod
            monkeypatch.setattr(server_mod, "SLOW_QUERY_MS", 0.0)
            recorder = FlightRecorder()
            server, _store = await start_server(recorder=recorder)
            via_generic_path(server)
            await udp_ask(server.udp_port, f"web.{DOMAIN}", Type.A)
            slow = [e for e in recorder.events()
                    if e["type"] == "slow-query"]
            assert slow and slow[0]["name"] == f"web.{DOMAIN}"
            assert slow[0]["trace"]
            await server.stop()
        asyncio.run(run())

    def test_resolver_error_event(self):
        async def run():
            recorder = FlightRecorder()
            server, _store = await start_server(recorder=recorder)
            via_generic_path(server)

            def boom(query):
                async def fail():
                    raise RuntimeError("induced resolver failure")
                return fail()

            server.resolver.handle = boom
            reply = await udp_ask(server.udp_port, f"web.{DOMAIN}",
                                  Type.A)
            assert reply.rcode == Rcode.SERVFAIL
            errs = [e for e in recorder.events()
                    if e["type"] == "resolver-error"]
            assert errs and "induced resolver failure" in errs[0]["error"]
            assert not server.engine.inflight
            await server.stop()
        asyncio.run(run())


class TestBalancerFold:
    @staticmethod
    def serve_stats(path, payload_box):
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(path)
        srv.listen(4)

        def loop():
            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                conn.sendall(json.dumps(payload_box[0]).encode())
                conn.close()

        threading.Thread(target=loop, daemon=True).start()
        return srv

    @staticmethod
    def stats(fp_cycles, fp_ops, rr_cycles, rr_ops):
        return {
            "cycles_per_us": 2900.0,
            "stage_cycles": {
                "frame-parse": {"cycles": fp_cycles, "ops": fp_ops},
                "reply-relay": {"cycles": rr_cycles, "ops": rr_ops},
            },
        }

    def test_fold_monotonic_across_restart(self, tmp_path):
        path = str(tmp_path / ".balancer.stats")
        box = [self.stats(1000, 10, 5000, 50)]
        srv = self.serve_stats(path, box)
        try:
            collector = MetricsCollector()
            fold = BalancerStatsFold(collector, path, timeout=2.0)
            text = collector.expose()
            assert validate_exposition(text) == []
            cyc = collector.get("binder_balancer_stage_cycles")
            assert cyc.value({"stage": "frame-parse"}) == 1000
            assert cyc.value({"stage": "reply-relay"}) == 5000
            assert collector.get("binder_balancer_up").value() == 1.0

            box[0] = self.stats(1500, 15, 9000, 90)   # balancer advances
            collector.expose()
            assert cyc.value({"stage": "frame-parse"}) == 1500
            assert cyc.value({"stage": "reply-relay"}) == 9000

            box[0] = self.stats(200, 2, 300, 3)       # balancer restarted
            collector.expose()
            # series stays monotonic: new totals fold in as fresh deltas
            assert cyc.value({"stage": "frame-parse"}) == 1700
            assert cyc.value({"stage": "reply-relay"}) == 9300
            ops = collector.get("binder_balancer_stage_ops")
            assert ops.value({"stage": "frame-parse"}) == 17
        finally:
            srv.close()
        # socket gone: up flips to 0, scrape keeps validating
        os.unlink(path)
        collector.expose()
        assert collector.get("binder_balancer_up").value() == 0.0
        assert validate_exposition(collector.expose()) == []
        assert fold is not None

    def test_no_balancer_is_clean(self, tmp_path):
        collector = MetricsCollector()
        BalancerStatsFold(collector,
                          str(tmp_path / "missing.stats"))
        text = collector.expose()
        assert validate_exposition(text) == []
        assert collector.get("binder_balancer_up").value() == 0.0


class TestSnapshotValidator:
    def test_rejects_missing_and_mistyped(self):
        good = {
            "service": {"name": "b", "pid": 1, "version": 1,
                        "uptime_seconds": 0.1, "generated_at": 1.0},
            "store": {"backend": "FakeStore", "state": "connected",
                      "connected": True, "disconnected_seconds": 0.0,
                      "session_establishments": 1, "transitions": []},
            "mirror": {"ready": True, "domain": "foo.com",
                       "generation": 1, "epoch": 1, "nodes": 2,
                       "names": 2, "reverse_entries": 1,
                       "interned_names": 3, "staleness_seconds": 0.5,
                       "last_rebuild_age_seconds": None,
                       "rebuild": {"pending": 0, "chunks": 1,
                                   "last_duration_seconds": 0.01}},
            "answer_cache": {"size": 10, "entries": 0, "hits": 0,
                             "misses": 0, "hit_ratio": 0.0,
                             "invalidations": 0, "expiry_ms": 1000.0,
                             "neg_hits": 0, "compiled_entries": 0,
                             "compiled_serves": 0,
                             "compiled_installs": 0},
            "inflight": {"count": 0, "queries": []},
            "tcp": {"open_conns": 0, "max_conns": 1024,
                    "idle_timeout_seconds": 30.0,
                    "max_write_buffer": 262144, "cap_refusals": 0,
                    "accepts": 0, "fast_serves": 0, "promotions": 0,
                    "oneshot_closes": 0, "idle_timeouts": 0,
                    "slow_reader_drops": 0, "coalesced_writes": 0,
                    "coalesced_frames": 0, "half_closes": 0,
                    "rst_drops": 0},
            "recursion": None, "precompile": None, "loop": None,
            "flight_recorder": None, "policy": None, "verify": None,
        }
        assert validate_status_snapshot(good) == []
        bad = json.loads(json.dumps(good))
        del bad["mirror"]["staleness_seconds"]
        bad["store"]["state"] = "confused"
        bad["inflight"]["count"] = 3
        del bad["loop"]
        errs = validate_status_snapshot(bad)
        assert any("staleness_seconds" in e for e in errs)
        assert any("unknown state" in e for e in errs)
        assert any("inflight.count" in e for e in errs)
        assert any(e.startswith("loop") for e in errs)
        assert validate_status_snapshot([]) != []


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"]))
