"""Property-based tests for the DNS wire codec (hypothesis).

The golden byte-vectors (test_conformance.py) pin specific RFC shapes;
these properties cover the whole input space the codec claims:

- encode→decode round-trips every representable message structurally
  (names normalize to lowercase on encode, so compare normalized);
- arbitrary bytes fed to Message.decode either raise WireError or
  produce a Message — never any other exception (the transport layers
  rely on this contract to treat malformed packets as protocol noise);
- truncation: encode(max_size) output never exceeds max_size for
  EDNS-less messages, sets TC exactly when content was dropped, and a
  truncated response still decodes;
- a decoded message re-encodes to bytes that decode to the same
  structure (idempotence through the compression layer).
"""
import ipaddress
import string

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from binder_tpu.dns.wire import (
    AAAARecord,
    ARecord,
    CNAMERecord,
    Message,
    OPTRecord,
    PTRRecord,
    Question,
    SOARecord,
    SRVRecord,
    TXTRecord,
    WireError,
)

LABEL_CHARS = string.ascii_lowercase + string.digits + "-_"

# labels up to the codec's 63-char bound, names filtered to the 253-char
# presentation bound so boundary-length names are actually generated
labels = st.text(LABEL_CHARS, min_size=1, max_size=63)
names = st.builds(".".join,
                  st.lists(labels, min_size=1, max_size=8).filter(
                      lambda ls: sum(len(x) + 1 for x in ls) <= 253))
ttls = st.integers(min_value=0, max_value=2**31 - 1)
u16 = st.integers(min_value=0, max_value=0xFFFF)
v4 = st.builds("{}.{}.{}.{}".format,
               *([st.integers(0, 255)] * 4))
# canonical form: the codec normalizes v6 text on decode (AAAA rdata is
# 16 raw bytes), so round-trip comparison needs canonical inputs
v6 = st.builds(
    lambda a, b: str(ipaddress.IPv6Address(f"2001:db8::{a:x}:{b:x}")),
    u16, u16)


def a_records(name_s=names):
    return st.builds(lambda n, t, addr: ARecord(name=n, ttl=t,
                                                address=addr),
                     name_s, ttls, v4)


records = st.one_of(
    a_records(),
    st.builds(lambda n, t, addr: AAAARecord(name=n, ttl=t, address=addr),
              names, ttls, v6),
    st.builds(lambda n, t, tgt: PTRRecord(name=n, ttl=t, target=tgt),
              names, ttls, names),
    st.builds(lambda n, t, tgt: CNAMERecord(name=n, ttl=t, target=tgt),
              names, ttls, names),
    st.builds(lambda n, t, p, w, port, tgt: SRVRecord(
        name=n, ttl=t, priority=p, weight=w, port=port, target=tgt),
        names, ttls, u16, u16, u16, names),
    st.builds(lambda n, t, mn, rn, serial: SOARecord(
        name=n, ttl=t, mname=mn, rname=rn, serial=serial,
        refresh=3600, retry=900, expire=604800, minimum=60),
        names, ttls, names, names, ttls),
    st.builds(lambda n, t, texts: TXTRecord(name=n, ttl=t,
                                            texts=tuple(texts)),
              names, ttls,
              st.lists(st.text(LABEL_CHARS, max_size=50), min_size=0,
                       max_size=3)),
)

messages = st.builds(
    lambda mid, qr, aa, tc, rd, ra, rcode, qs, ans, auth: Message(
        id=mid, qr=qr, aa=aa, tc=tc, rd=rd, ra=ra, rcode=rcode,
        questions=qs, answers=ans, authorities=auth),
    u16, st.booleans(), st.booleans(), st.booleans(), st.booleans(),
    st.booleans(), st.integers(0, 15),
    st.lists(st.builds(lambda n, t: Question(name=n, qtype=t),
                       names, st.integers(1, 255)),
             min_size=1, max_size=1),
    st.lists(records, max_size=4),
    st.lists(records, max_size=2),
)


@settings(max_examples=300, deadline=None)
@given(messages)
def test_encode_decode_round_trip(msg):
    wire = msg.encode()
    back = Message.decode(wire)
    assert back.id == msg.id
    assert (back.qr, back.aa, back.tc, back.rd, back.ra) == \
        (msg.qr, msg.aa, msg.tc, msg.rd, msg.ra)
    assert back.rcode == msg.rcode
    assert back.questions == msg.questions
    assert back.answers == msg.answers
    assert back.authorities == msg.authorities


@settings(max_examples=300, deadline=None)
@given(messages)
def test_reencode_idempotent(msg):
    once = Message.decode(msg.encode())
    twice = Message.decode(once.encode())
    assert twice == once


@settings(max_examples=1000, deadline=None)
@given(st.binary(max_size=600))
def test_decode_never_raises_anything_but_wireerror(data):
    try:
        Message.decode(data)
    except WireError:
        pass


@settings(max_examples=1000, deadline=None)
@given(st.binary(min_size=12, max_size=600), st.integers(0, 11),
       st.binary(max_size=4))
def test_decode_mutated_valid_prefix(data, pos, junk):
    """Splice junk into an otherwise plausible header region — the
    decoder must still only ever raise WireError."""
    buf = bytearray(data)
    buf[pos:pos + len(junk)] = junk
    try:
        Message.decode(bytes(buf))
    except WireError:
        pass


@settings(max_examples=200, deadline=None)
@given(messages, st.integers(min_value=64, max_value=512))
def test_truncation_bound_and_tc(msg, max_size):
    # EDNS-less messages only: the OPT record is deliberately retained
    # in TC responses (RFC 6891) and is exercised separately
    wire = msg.encode(max_size=max_size)
    full = msg.encode()
    if len(full) <= max_size:
        assert wire == full
    else:
        # truncation cannot drop the question section; its size is the
        # floor (a real question is <= 271 bytes, under every real UDP
        # ceiling, so the floor only binds for artificial max_size)
        floor = len(Message(id=msg.id,
                            questions=list(msg.questions)).encode())
        assert len(wire) <= max(max_size, floor)
        back = Message.decode(wire)
        assert back.tc is True
        assert back.answers == [] and back.authorities == []


@settings(max_examples=200, deadline=None)
@given(st.lists(a_records(), min_size=1, max_size=30), u16)
def test_truncated_with_edns_keeps_opt(answers, payload):
    msg = Message(id=1, qr=True,
                  questions=[Question(name="q.example", qtype=1)],
                  answers=answers,
                  additionals=[OPTRecord(name="", ttl=0,
                                         udp_payload_size=1232)])
    wire = msg.encode(max_size=100)
    back = Message.decode(wire)
    if back.tc:
        # RFC 6891: the OPT pseudo-record survives truncation
        assert any(isinstance(r, OPTRecord) for r in back.additionals)
        assert back.answers == []
