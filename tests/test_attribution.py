"""Per-stage attribution layer: trace IDs, phase stamps, stage metrics.

The observability surface this pins down end to end:

- every query gets a process-unique trace ID, carried by the
  op-req-start/op-req-done probes and the query log, stable under
  concurrent allocation (threads and overlapping in-flight queries);
- the QueryCtx phase stamps decompose a query's latency into
  non-negative phases whose names are complete for each serve path
  (answer-cache hit, store miss, recursion fast path, TCP);
- the `binder_query_stage_seconds` histogram agrees with
  `binder_requests_completed` (every after-hook observation lands in
  both), and the whole scrape text passes the Prometheus-exposition
  validator (tools/lint.py) — malformed exposition fails tier-1 here;
- the balancer's stats-socket `stage_cycles` counters are present,
  consistent with its own query counters and with the backend's
  `binder_requests_completed`.
"""
import asyncio
import json
import os
import socket
import struct
import threading

import pytest

from binder_tpu.dns import Message, Type, make_query
from binder_tpu.dns.query import next_trace_id
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import (
    METRIC_REQUEST_COUNTER,
    METRIC_STAGE_HISTOGRAM,
    BinderServer,
)
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.utils.probes import ProbeProvider
from tools.lint import validate_exposition

DOMAIN = "foo.com"
BALANCER = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "mbalancer")


def make_fixture():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "10.0.0.1"}})
    store.start_session()
    return cache


async def start_server(**kw):
    """In-process server with a subscribed probe sink; returns
    (server, events) where events collects (probe name, args)."""
    provider = ProbeProvider("binder", backend="off")
    events = []
    provider.subscribe(lambda name, args: events.append((name, args)))
    server = BinderServer(zk_cache=kw.pop("zk_cache", None) or
                          make_fixture(),
                          dns_domain=DOMAIN, datacenter_name="dc0",
                          host="127.0.0.1", port=0,
                          collector=MetricsCollector(),
                          probes=provider, **kw)
    await server.start()
    return server, events


async def udp_ask(port, name, qtype, qid=1, rd=False, timeout=5.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid,
                                        rd=rd).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return Message.decode(data)


async def tcp_ask(port, name, qtype, qid=2):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    wire = make_query(name, qtype, qid=qid).encode()
    writer.write(struct.pack(">H", len(wire)) + wire)
    await writer.drain()
    (ln,) = struct.unpack(">H", await asyncio.wait_for(
        reader.readexactly(2), 5))
    data = await reader.readexactly(ln)
    writer.close()
    await writer.wait_closed()
    return Message.decode(data)


def done_events(events):
    return [args for name, args in events if name == "op-req-done"]


class TestTraceIds:
    def test_thread_concurrent_allocation_unique(self):
        """8 threads allocating 2000 IDs each never collide (the
        counter is a single C call; no lock needed or taken)."""
        per_thread = 2000
        out = [None] * 8

        def alloc(i):
            out[i] = [next_trace_id() for _ in range(per_thread)]

        threads = [threading.Thread(target=alloc, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        all_ids = [tid for ids in out for tid in ids]
        assert len(set(all_ids)) == len(all_ids)
        # format: "<pid hex>-<seq hex>", distinguishable across the
        # deployment unit's processes
        pid_hex = format(os.getpid(), "x")
        assert all(tid.startswith(pid_hex + "-") for tid in all_ids)

    def test_concurrent_queries_unique_trace_ids(self):
        """Overlapping in-flight queries each get their own trace ID,
        and start/done probe events correlate by it."""
        n = 50

        async def run():
            server, events = await start_server(query_log=False)
            try:
                await asyncio.gather(*[
                    udp_ask(server.udp_port, "web.foo.com", Type.A,
                            qid=i + 1) for i in range(n)])
            finally:
                await server.stop()
            return events

        events = asyncio.run(run())
        starts = [a for nm, a in events if nm == "op-req-start"]
        dones = done_events(events)
        assert len(starts) == n and len(dones) == n
        start_traces = {a["trace"] for a in starts}
        done_traces = {a["trace"] for a in dones}
        assert len(start_traces) == n
        assert start_traces == done_traces


class TestPhaseStamps:
    def assert_stages(self, stages, required):
        """Required stage names present; every recorded phase >= 0 (the
        monotonic-clock cursor can never produce a negative delta)."""
        missing = required - set(stages)
        assert not missing, f"missing stages {missing} in {stages}"
        negative = {k: v for k, v in stages.items() if v < 0}
        assert not negative, f"negative phase durations: {negative}"

    def test_miss_then_hit_stamps(self):
        async def run():
            server, events = await start_server(query_log=False)
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A,
                              qid=1)
                await udp_ask(server.udp_port, "web.foo.com", Type.A,
                              qid=2)
            finally:
                await server.stop()
            return events

        dones = done_events(asyncio.run(run()))
        assert len(dones) == 2
        # first query: full resolve path through the store
        self.assert_stages(dones[0]["stages"],
                           {"store-lookup", "log-after"})
        # repeat: whole-hit stamp from the answer cache
        self.assert_stages(dones[1]["stages"],
                           {"cache-hit", "log-after"})
        assert "store-lookup" not in dones[1]["stages"]

    def test_tcp_stamps(self):
        async def run():
            server, events = await start_server(query_log=False)
            try:
                r = await tcp_ask(server.tcp_port, "web.foo.com", Type.A)
                assert r.answers
            finally:
                await server.stop()
            return events

        dones = done_events(asyncio.run(run()))
        assert len(dones) == 1
        self.assert_stages(dones[0]["stages"],
                           {"store-lookup", "log-after"})

    def test_recursion_fast_path_stamps(self):
        """The cross-DC forward decomposes into dispatch / upstream RTT
        / event-loop wait / splice — the split that makes the recursion
        p50 attributable (the whole await window is also recorded and
        must cover its two overlay phases)."""
        from binder_tpu.recursion import Recursion, StaticResolverSource

        async def run():
            remote_store = FakeStore()
            remote_cache = MirrorCache(remote_store, DOMAIN)
            remote_store.put_json("/com/foo/east",
                                  {"type": "service",
                                   "service": {"port": 53}})
            remote_store.put_json(
                "/com/foo/east/web",
                {"type": "host", "host": {"address": "10.77.0.1",
                                          "ttl": 44}})
            remote_store.start_session()
            remote = BinderServer(zk_cache=remote_cache,
                                  dns_domain=DOMAIN,
                                  datacenter_name="east",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector())
            await remote.start()

            local_store = FakeStore()
            local_cache = MirrorCache(local_store, DOMAIN)
            local_store.start_session()
            recursion = Recursion(
                zk_cache=local_cache, dns_domain=DOMAIN,
                datacenter_name="local",
                source=StaticResolverSource(
                    {"east": [f"127.0.0.1:{remote.udp_port}"]}),
                nic_provider=lambda: [])
            await recursion.wait_ready()
            server, events = await start_server(
                zk_cache=local_cache, recursion=recursion,
                query_log=False)
            try:
                # first query cold-starts the pooled upstream port via
                # the slow coroutine path ("upstream" stamp); the
                # repeat takes the zero-coroutine fast path whose wait
                # is split into upstream-rtt + loop-wait
                for qid in (1, 2):
                    r = await udp_ask(server.udp_port,
                                      "web.east.foo.com", Type.A,
                                      rd=True, qid=qid)
                    assert r.answers
            finally:
                await server.stop()
                await remote.stop()
            return events

        dones = done_events(asyncio.run(run()))
        assert len(dones) == 2
        self.assert_stages(dones[0]["stages"],
                           {"store-lookup", "dispatch", "upstream",
                            "log-after"})
        stages = dones[1]["stages"]
        self.assert_stages(stages, {"store-lookup", "dispatch", "await",
                                    "upstream-rtt", "loop-wait",
                                    "log-after"})
        # the response was spliced or rebuilt; either way the local
        # post-arrival work carries its own stamp
        assert "splice" in stages or "rebuild" in stages


class TestStageMetrics:
    def test_stage_counts_match_requests_completed(self):
        """Every after-hook observation lands in BOTH
        binder_requests_completed and the log-after stage cell, so the
        two totals must agree exactly for Python-served queries."""
        n = 7

        async def run():
            server, _ = await start_server(query_log=False)
            try:
                for i in range(n):
                    # distinct unknown names: no answer-cache reuse, no
                    # native serving — each traverses the after hook
                    await udp_ask(server.udp_port, f"m{i}.foo.com",
                                  Type.A, qid=i + 1)
            finally:
                await server.stop()
            return server

        server = asyncio.run(run())
        counter = server.collector.get(METRIC_REQUEST_COUNTER)
        completed = sum(counter._values.values())
        hist = server.collector.get(METRIC_STAGE_HISTOGRAM)
        assert completed == n
        assert hist.count({"stage": "log-after"}) == n
        # no stage can have observed more queries than completed
        for key in hist._counts:
            assert sum(hist._counts[key]) <= completed

    def test_exposition_validates(self):
        """The full scrape text — counters, gauges, latency/size
        histograms, and the new per-stage histogram — passes the
        Prometheus text-format validator (tools/lint.py), so a
        malformed exposition fails tier-1 here."""
        async def run():
            server, _ = await start_server(query_log=False)
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A)
                await udp_ask(server.udp_port, "web.foo.com", Type.A,
                              qid=2)
                await tcp_ask(server.tcp_port, "web.foo.com", Type.SRV)
            finally:
                await server.stop()
            return server.collector.expose()

        text = asyncio.run(run())
        assert METRIC_STAGE_HISTOGRAM + "_bucket" in text
        errors = validate_exposition(text)
        assert not errors, "\n".join(errors)

    def test_validator_rejects_malformed(self):
        """The validator itself catches the failure shapes a hand-rolled
        exposition can produce (guards against a vacuous gate)."""
        cases = {
            "no TYPE": 'orphan_metric{a="b"} 1\n',
            "bad label": '# TYPE m counter\nm{9bad="x"} 1\n',
            "unquoted": '# TYPE m counter\nm{a=b} 1\n',
            "bad value": '# TYPE m counter\nm{a="b"} zork\n',
            "negative counter": '# TYPE m counter\nm -4\n',
            "duplicate sample": '# TYPE m gauge\nm 1\nm 2\n',
            "count mismatch": (
                '# TYPE h histogram\n'
                'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 5\n'
                'h_sum 1\nh_count 9\n'),
            "shrinking buckets": (
                '# TYPE h histogram\n'
                'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\n'
                'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n'),
            "missing +Inf": (
                '# TYPE h histogram\n'
                'h_bucket{le="1"} 5\nh_sum 1\nh_count 5\n'),
            "no final newline": '# TYPE m gauge\nm 1',
        }
        for what, text in cases.items():
            assert validate_exposition(text), f"validator missed: {what}"
        # and a known-good document yields no findings
        good = ('# HELP m things\n# TYPE m counter\nm{a="b"} 3\n'
                '# TYPE h histogram\n'
                'h_bucket{le="0.5"} 2\nh_bucket{le="+Inf"} 4\n'
                'h_sum 1.25\nh_count 4\n')
        assert validate_exposition(good) == []


@pytest.mark.skipif(not os.path.exists(BALANCER),
                    reason="mbalancer not built (make -C native)")
class TestBalancerStageCounters:
    def test_stats_socket_stage_cycles_consistent(self):
        """The stats dump carries the four stage cells, the calibrated
        TSC rate, and counts consistent with both the balancer's own
        query counters and the backend's binder_requests_completed."""
        import tempfile
        n = 20

        async def run(sockdir):
            backend = BinderServer(
                zk_cache=make_fixture(), dns_domain=DOMAIN,
                datacenter_name="dc0", host="127.0.0.1", port=0,
                balancer_socket=os.path.join(sockdir, "0"),
                collector=MetricsCollector(), query_log=False)
            await backend.start()
            # -D pins the compat relay lane: this test asserts the
            # probe/relay stage counters, which direct return bypasses
            proc = await asyncio.create_subprocess_exec(
                BALANCER, "-d", sockdir, "-p", "0", "-b", "127.0.0.1",
                "-s", "150", "-c", "60000", "-D",
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.DEVNULL)
            try:
                line = await asyncio.wait_for(proc.stdout.readline(), 30)
                assert line.startswith(b"PORT "), line
                port = int(line.split()[1])
                await asyncio.sleep(0.5)   # backend scan + connect
                for i in range(n):
                    # distinct names: every query is a balancer-cache
                    # miss, probed and forwarded to the backend
                    await udp_ask(port, f"c{i}.foo.com", Type.A,
                                  qid=i + 1)
                stats = read_stats(sockdir)
            finally:
                proc.terminate()
                await proc.wait()
                await backend.stop()
            # scrape AFTER the queries: expose() folds any natively
            # accumulated backend counts into the collectors
            backend.collector.expose()
            counter = backend.collector.get(METRIC_REQUEST_COUNTER)
            return stats, sum(counter._values.values())

        with tempfile.TemporaryDirectory() as sockdir:
            stats, backend_completed = asyncio.run(run(sockdir))

        assert stats["udp_queries"] == n
        # cache on + all misses: every query forwarded, every one
        # served exactly once by the backend
        assert backend_completed == n
        cells = stats["stage_cycles"]
        assert set(cells) == {"frame-parse", "cache-probe",
                              "backend-write", "reply-relay"}
        for name, cell in cells.items():
            assert cell["cycles"] >= 0 and cell["ops"] >= 0, name
        # one probe per query (plus response harvests), one write per
        # forward, one relay per response — none can undercount n
        assert cells["cache-probe"]["ops"] >= n
        assert cells["backend-write"]["ops"] >= n
        assert cells["reply-relay"]["ops"] >= n
        assert stats["cycles_per_us"] > 0
        served = stats.get("cache_hits", 0) + \
            stats.get("cache_misses", 0) + stats.get("uncacheable", 0)
        assert served == n


def read_stats(sockdir):
    c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    c.settimeout(2)
    c.connect(os.path.join(sockdir, ".balancer.stats"))
    buf = b""
    while True:
        chunk = c.recv(4096)
        if not chunk:
            break
        buf += chunk
    c.close()
    return json.loads(buf)
