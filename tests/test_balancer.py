"""Integration tests for the native C++ load balancer (native/balancer).

Spawns the real mbalancer binary in front of real Python backend servers
connected via the balancer protocol — the multi-process topology the
reference runs in production but never tests (SURVEY §4: "the balancer …
zero automated tests").
"""
import asyncio
import json
import os
import socket
import struct

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"
# BINDER_BALANCER overrides the binary under test (e.g. the sanitizer
# build: `make -C native asan` then BINDER_BALANCER=native/build/mbalancer.asan)
BALANCER = os.environ.get("BINDER_BALANCER") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native", "build", "mbalancer")

pytestmark = pytest.mark.skipif(
    not os.path.exists(BALANCER),
    reason="mbalancer not built (make -C native)")


def make_fixture(tag):
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": f"10.42.0.{tag}"}})
    store.start_session()
    return cache


async def start_backend(sockdir, instance, tag):
    server = BinderServer(zk_cache=make_fixture(tag), dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1", port=0,
                          balancer_socket=os.path.join(sockdir,
                                                       str(instance)),
                          collector=MetricsCollector())
    await server.start()
    return server


async def start_balancer(sockdir, scan_ms=150, cache_ms=60000,
                         bind="127.0.0.1", direct=True):
    # direct=False pins the compat relay lane (-D): tests asserting the
    # balancer's own answer-cache behavior need replies to flow back
    # through it, which direct return bypasses by design
    args = [BALANCER, "-d", sockdir, "-p", "0", "-b", bind,
            "-s", str(scan_ms), "-c", str(cache_ms)]
    if not direct:
        args.append("-D")
    proc = await asyncio.create_subprocess_exec(
        *args,
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.DEVNULL)
    # generous deadline: on a loaded single-core box (bench processes,
    # parallel suites) 5s was observed to flake the whole-suite gate
    line = await asyncio.wait_for(proc.stdout.readline(), 30)
    assert line.startswith(b"PORT "), line
    return proc, int(line.split()[1])


async def udp_ask(port, name, qtype, qid=1, timeout=5.0, sock=None,
                  host="127.0.0.1", rd=False):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid,
                                        rd=rd).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=(host, port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return Message.decode(data)


async def tcp_ask(port, name, qtype, qid=2):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    wire = make_query(name, qtype, qid=qid).encode()
    writer.write(struct.pack(">H", len(wire)) + wire)
    await writer.drain()
    (ln,) = struct.unpack(">H", await asyncio.wait_for(
        reader.readexactly(2), 5))
    data = await reader.readexactly(ln)
    writer.close()
    await writer.wait_closed()
    return Message.decode(data)


def read_stats(sockdir):
    import socket as s
    c = s.socket(s.AF_UNIX, s.SOCK_STREAM)
    c.settimeout(2)
    c.connect(os.path.join(sockdir, ".balancer.stats"))
    buf = b""
    while True:
        chunk = c.recv(4096)
        if not chunk:
            break
        buf += chunk
    c.close()
    return json.loads(buf)


class TestBalancer:
    def test_udp_and_tcp_through_balancer(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            b2 = await start_backend(sockdir, 5302, 2)
            proc, port = await start_balancer(sockdir)
            try:
                await asyncio.sleep(0.4)  # let the scan connect backends
                udp_r = await udp_ask(port, "web.foo.com", Type.A)
                tcp_r = await tcp_ask(port, "web.foo.com", Type.A)
                stats = read_stats(sockdir)
            finally:
                proc.kill()
                await proc.wait()
                await b1.stop()
                await b2.stop()
            return udp_r, tcp_r, stats

        udp_r, tcp_r, stats = asyncio.run(run())
        assert udp_r.rcode == Rcode.NOERROR
        assert udp_r.answers[0].address.startswith("10.42.0.")
        assert tcp_r.rcode == Rcode.NOERROR
        assert stats["udp_queries"] == 1 and stats["tcp_queries"] == 1
        assert len(stats["backends"]) == 2
        assert all(be["healthy"] for be in stats["backends"])

    def test_failover_when_backend_leaves(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            b2 = await start_backend(sockdir, 5302, 2)
            proc, port = await start_balancer(sockdir)
            try:
                await asyncio.sleep(0.4)
                first = await udp_ask(port, "web.foo.com", Type.A, qid=1)
                served_by = first.answers[0].address

                # the backend that answered leaves: SIGTERM semantics =
                # unlink socket + stop serving (main.js:181-193)
                leaving = b1 if served_by.endswith(".1") else b2
                path = leaving.balancer_socket
                await leaving.stop()
                os.unlink(path)
                await asyncio.sleep(0.5)  # rescan notices

                second = await udp_ask(port, "web.foo.com", Type.A, qid=2)
                stats = read_stats(sockdir)
            finally:
                proc.kill()
                await proc.wait()
                for b in (b1, b2):
                    try:
                        await b.stop()
                    except Exception:
                        pass
            return served_by, second, stats

        served_by, second, stats = asyncio.run(run())
        # affinity must be re-pointed to the surviving backend
        assert second.rcode == Rcode.NOERROR
        assert second.answers[0].address != served_by
        healthy = [be for be in stats["backends"] if be["healthy"]]
        assert len(healthy) == 1

    def test_affinity_sticks_to_one_backend(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            b2 = await start_backend(sockdir, 5302, 2)
            # cache off: this test counts forwards to prove affinity
            proc, port = await start_balancer(sockdir, cache_ms=0)
            try:
                await asyncio.sleep(0.4)
                addrs = set()
                for i in range(6):
                    r = await udp_ask(port, "web.foo.com", Type.A, qid=i)
                    addrs.add(r.answers[0].address)
                stats = read_stats(sockdir)
            finally:
                proc.kill()
                await proc.wait()
                await b1.stop()
                await b2.stop()
            return addrs, stats

        addrs, stats = asyncio.run(run())
        # same client IP -> same backend every time
        assert len(addrs) == 1
        counts = sorted(be["forwarded"] for be in stats["backends"])
        assert counts == [0, 6]

    def test_late_joining_backend_discovered(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            proc, port = await start_balancer(sockdir)
            try:
                await asyncio.sleep(0.3)
                stats_before = read_stats(sockdir)
                backend = await start_backend(sockdir, 5301, 1)
                await asyncio.sleep(0.4)  # next scan picks it up
                r = await udp_ask(port, "web.foo.com", Type.A)
                stats_after = read_stats(sockdir)
                await backend.stop()
            finally:
                proc.kill()
                await proc.wait()
            return stats_before, r, stats_after

        before, r, after = asyncio.run(run())
        assert before["backends"] == []
        assert r.rcode == Rcode.NOERROR
        assert len(after["backends"]) == 1


class TestBalancerCache:
    """The balancer's answer cache (mbalancer -c): repeat single-answer
    UDP queries are served without a forward, invalidated by the
    backend's generation control frames on store mutation."""

    def test_repeat_queries_cached_and_invalidated(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/web",
                           {"type": "host",
                            "host": {"address": "10.42.0.7"}})
            store.start_session()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="dc0", host="127.0.0.1",
                                  port=0,
                                  balancer_socket=os.path.join(sockdir,
                                                               "0"),
                                  collector=MetricsCollector())
            await server.start()
            proc, port = await start_balancer(sockdir, direct=False)
            try:
                await asyncio.sleep(0.4)
                for i in range(5):
                    r = await udp_ask(port, "web.foo.com", Type.A,
                                      qid=i + 1)
                    assert r.id == i + 1
                    assert r.answers[0].address == "10.42.0.7"
                stats = read_stats(sockdir)
                assert stats["cache_hits"] == 4, stats
                assert stats["cache_entries"] == 1
                assert stats["backends"][0]["forwarded"] == 1
                assert stats["backends"][0]["gen_known"] is True
                # per-stage attribution cells: the one forwarded miss
                # produced one matched round trip, and the histogram
                # holds exactly that observation
                assert stats["cache_misses"] == 1, stats
                assert stats["fwd_rtt_count"] == 1
                assert stats["fwd_rtt_sum_s"] > 0
                assert sum(stats["fwd_rtt_us_cells"]) == 1
                assert stats["backend_wq_peak"] > 0

                # store mutation -> gen frame -> cached entry is stale
                store.put_json("/com/foo/web",
                               {"type": "host",
                                "host": {"address": "10.42.0.99"}})
                await asyncio.sleep(0.2)   # frame delivery
                r = await udp_ask(port, "web.foo.com", Type.A, qid=99)
                assert r.answers[0].address == "10.42.0.99"
                stats = read_stats(sockdir)
                assert stats["backends"][0]["forwarded"] == 2
                # and the fresh answer is cached again
                r = await udp_ask(port, "web.foo.com", Type.A, qid=100)
                assert r.answers[0].address == "10.42.0.99"
                stats = read_stats(sockdir)
                assert stats["backends"][0]["forwarded"] == 2
            finally:
                proc.kill()
                await proc.wait()
                await server.stop()

        asyncio.run(run())

    def test_multi_answer_collects_variants_then_rotates(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/svc", {
                "type": "service",
                "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
            })
            for i in range(4):
                store.put_json(f"/com/foo/svc/lb{i}",
                               {"type": "load_balancer",
                                "load_balancer":
                                    {"address": f"10.0.1.{i + 1}"}})
            store.start_session()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="dc0", host="127.0.0.1",
                                  port=0,
                                  balancer_socket=os.path.join(sockdir,
                                                               "0"),
                                  collector=MetricsCollector())
            await server.start()
            proc, port = await start_balancer(sockdir, direct=False)
            try:
                await asyncio.sleep(0.4)
                orderings = []
                for i in range(24):
                    r = await udp_ask(port, "svc.foo.com", Type.A,
                                      qid=i + 1)
                    assert len(r.answers) == 4
                    orderings.append(tuple(a.address for a in r.answers))
                stats = read_stats(sockdir)
                # collect-then-serve: the first 8 responses fill the
                # variant set (all forwarded), everything after is a
                # cache hit cycling through the collected shuffles
                assert stats["backends"][0]["forwarded"] == 8, stats
                assert stats["cache_hits"] == 16
                # rotation stays visible through the cache
                assert len(set(orderings)) > 1
                assert len(set(orderings[8:])) > 1
            finally:
                proc.kill()
                await proc.wait()
                await server.stop()

        asyncio.run(run())

    def test_qid_reuse_cannot_poison_cache(self, tmp_path):
        """Two in-flight queries under one (client, qid) for different
        names: the response for name A must not be cached under name
        B's key (the fill verifies the response's echoed question)."""
        sockdir = str(tmp_path)

        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/aaa",
                           {"type": "host",
                            "host": {"address": "10.42.1.1"}})
            store.put_json("/com/foo/bbb",
                           {"type": "host",
                            "host": {"address": "10.42.2.2"}})
            store.start_session()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="dc0", host="127.0.0.1",
                                  port=0,
                                  balancer_socket=os.path.join(sockdir,
                                                               "0"),
                                  collector=MetricsCollector())
            await server.start()
            proc, port = await start_balancer(sockdir, direct=False)
            try:
                await asyncio.sleep(0.4)
                loop = asyncio.get_running_loop()
                got = []
                done = loop.create_future()

                class Proto(asyncio.DatagramProtocol):
                    def connection_made(self, transport):
                        # same qid, two names, back-to-back: the second
                        # overwrites the pending-fill slot before the
                        # first response returns
                        transport.sendto(make_query(
                            "aaa.foo.com", Type.A, qid=7).encode())
                        transport.sendto(make_query(
                            "bbb.foo.com", Type.A, qid=7).encode())

                    def datagram_received(self, data, addr):
                        got.append(Message.decode(data))
                        if len(got) == 2 and not done.done():
                            done.set_result(None)

                transport, _ = await loop.create_datagram_endpoint(
                    Proto, remote_addr=("127.0.0.1", port))
                await asyncio.wait_for(done, 5)
                transport.close()

                # now bbb must resolve to bbb's address, repeatedly
                # (cached or not) — a poisoned cache would serve aaa's
                for i in range(4):
                    r = await udp_ask(port, "bbb.foo.com", Type.A,
                                      qid=100 + i)
                    assert r.answers[0].address == "10.42.2.2", \
                        [str(a.address) for a in r.answers]
                r = await udp_ask(port, "aaa.foo.com", Type.A, qid=200)
                assert r.answers[0].address == "10.42.1.1"
            finally:
                proc.kill()
                await proc.wait()
                await server.stop()

        asyncio.run(run())


class TestBalancerV6:
    def test_ipv6_front(self, tmp_path):
        """-b with a ':' binds an IPv6 (dual-stack-capable) front; the
        frame protocol already carries family-6 client addresses."""
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            proc, port = await start_balancer(sockdir, bind="::1")
            try:
                await asyncio.sleep(0.4)
                for qid in (5, 6):   # second ask is a balancer-cache hit
                    m = await udp_ask(port, "web.foo.com", Type.A,
                                      qid=qid, host="::1")
                    assert m.id == qid
                    assert m.answers[0].address == "10.42.0.1"

                # TCP over v6 through the same front
                reader, writer = await asyncio.open_connection("::1", port)
                wire = make_query("web.foo.com", Type.A, qid=9).encode()
                writer.write(struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                (ln,) = struct.unpack(">H", await asyncio.wait_for(
                    reader.readexactly(2), 5))
                m = Message.decode(await reader.readexactly(ln))
                assert m.id == 9
                writer.close()
            finally:
                proc.kill()
                await proc.wait()
                await b1.stop()

        asyncio.run(run())

    def test_cache_time_expiry_reforwards(self, tmp_path):
        """Entries lapse after -c ms even with no store mutation."""
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            proc, port = await start_balancer(sockdir, cache_ms=150,
                                               direct=False)
            try:
                await asyncio.sleep(0.4)
                for qid in (1, 2):
                    await udp_ask(port, "web.foo.com", Type.A, qid=qid)
                stats = read_stats(sockdir)
                assert stats["cache_hits"] == 1
                assert stats["backends"][0]["forwarded"] == 1
                await asyncio.sleep(0.3)   # past expiry
                await udp_ask(port, "web.foo.com", Type.A, qid=3)
                stats = read_stats(sockdir)
                assert stats["backends"][0]["forwarded"] == 2
            finally:
                proc.kill()
                await proc.wait()
                await b1.stop()

        asyncio.run(run())


class TestBalancerBounds:
    """Resource bounds (VERDICT r1): write queues are capped, stalled
    backends get marked down, idle/flooding TCP clients are evicted —
    one slow peer must never OOM or fd-starve the front end."""

    @staticmethod
    async def start_bounded_balancer(sockdir, *, scan_ms=100, extra=(),
                                     env_caps=None):
        env = dict(os.environ)
        for k, v in (env_caps or {}).items():
            env[k] = str(v)
        proc = await asyncio.create_subprocess_exec(
            BALANCER, "-d", sockdir, "-p", "0", "-b", "127.0.0.1",
            "-s", str(scan_ms), *extra,
            env=env,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL)
        line = await asyncio.wait_for(proc.stdout.readline(), 5)
        assert line.startswith(b"PORT ")
        return proc, int(line.split()[1])

    def test_stalled_backend_marked_down(self, tmp_path):
        """A backend that connects but never reads: the balancer's write
        queue must stay bounded (frames shed past the cap) and the
        backend must be marked down by the stall sweep."""
        sockdir = str(tmp_path)

        async def run():
            import socket as s
            # fake backend: accepts the balancer's connection, never reads
            lsock = s.socket(s.AF_UNIX, s.SOCK_STREAM)
            lsock.bind(os.path.join(sockdir, "0"))
            lsock.listen(1)
            lsock.setblocking(False)
            loop = asyncio.get_running_loop()
            proc, port = await self.start_bounded_balancer(
                sockdir, env_caps={"MBALANCER_MAX_BACKEND_WQ": 4096})
            try:
                conn, _ = await asyncio.wait_for(loop.sock_accept(lsock), 5)
                # flood queries; the unix kernel buffer absorbs the first
                # ~200 KB, then the user-space queue hits its 4 KB cap
                q = make_query("web.foo.com", Type.A, qid=1).encode()
                us = s.socket(s.AF_INET, s.SOCK_DGRAM)
                # paced so the balancer's UDP rcvbuf doesn't shed the
                # flood before it reaches the backend write queue
                for i in range(12000):
                    us.sendto(q, ("127.0.0.1", port))
                    if i % 500 == 0:
                        await asyncio.sleep(0.005)
                await asyncio.sleep(0.8)   # > kBackendStallTicks * scan_ms
                stats = read_stats(sockdir)
                us.close()
                conn.close()
            finally:
                proc.kill()
                await proc.wait()
                lsock.close()
            return stats

        stats = asyncio.run(run())
        assert stats["wq_overflows"] > 0, stats
        assert stats["backend_stalls"] >= 1, stats
        # memory is bounded: the dead connection's queue was shed on
        # mark-down; whatever the post-reconnect stream holds is within
        # the cap (the balancer recovers via rescan by design, so the
        # backend may legitimately be "healthy" again here)
        assert all(b["wq_bytes"] <= 4096 for b in stats["backends"]), stats

    def test_idle_tcp_client_evicted(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            proc, port = await self.start_bounded_balancer(
                sockdir, extra=("-T", "200"))
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                got = await asyncio.wait_for(reader.read(16), 5)
                stats = read_stats(sockdir)
                writer.close()
            finally:
                proc.kill()
                await proc.wait()
            return got, stats

        got, stats = asyncio.run(run())
        assert got == b""              # peer closed us
        assert stats["idle_closes"] >= 1
        assert stats["tcp_clients"] == 0

    def test_tcp_client_cap_evicts_idlest(self, tmp_path):
        sockdir = str(tmp_path)

        async def run():
            b1 = await start_backend(sockdir, 5301, 1)
            proc, port = await self.start_bounded_balancer(
                sockdir, extra=("-m", "2"))
            try:
                await asyncio.sleep(0.3)
                r1, w1 = await asyncio.open_connection("127.0.0.1", port)
                await asyncio.sleep(0.1)   # r1 is oldest
                r2, w2 = await asyncio.open_connection("127.0.0.1", port)
                # a newcomer while both are fresh is REFUSED (a connect
                # flood must not displace established clients)
                r0, w0 = await asyncio.open_connection("127.0.0.1", port)
                refused = await asyncio.wait_for(r0.read(16), 5)
                assert refused == b""
                w0.close()
                # keep c2 active so c1 is strictly idlest, and let c1
                # pass the eviction idle floor (1 s)
                await asyncio.sleep(1.1)
                wire = make_query("web.foo.com", Type.A, qid=5).encode()
                w2.write(struct.pack(">H", len(wire)) + wire)
                await w2.drain()
                await asyncio.wait_for(r2.readexactly(2), 5)

                r3, w3 = await asyncio.open_connection("127.0.0.1", port)
                evicted = await asyncio.wait_for(r1.read(16), 5)
                # the newcomer is serviceable
                w3.write(struct.pack(">H", len(wire)) + wire)
                await w3.drain()
                (ln,) = struct.unpack(">H", await asyncio.wait_for(
                    r3.readexactly(2), 5))
                reply = Message.decode(await r3.readexactly(ln))
                stats = read_stats(sockdir)
                for w in (w1, w2, w3):
                    w.close()
            finally:
                proc.kill()
                await proc.wait()
                await b1.stop()
            return evicted, reply, stats

        evicted, reply, stats = asyncio.run(run())
        assert evicted == b""
        assert reply.rcode == Rcode.NOERROR
        assert stats["client_evictions"] == 1
        assert stats["tcp_clients"] == 2

    def test_flooded_tcp_client_disconnected(self, tmp_path):
        """A TCP client that asks but never reads: a misbehaving backend
        blasting responses must fill the client's bounded queue and get
        it disconnected, with memory shed, not grown."""
        sockdir = str(tmp_path)

        async def run():
            import socket as s
            lsock = s.socket(s.AF_UNIX, s.SOCK_STREAM)
            lsock.bind(os.path.join(sockdir, "0"))
            lsock.listen(1)
            lsock.setblocking(False)
            loop = asyncio.get_running_loop()
            proc, port = await self.start_bounded_balancer(
                sockdir, env_caps={"MBALANCER_MAX_CLIENT_WQ": 65536})
            try:
                conn, _ = await asyncio.wait_for(loop.sock_accept(lsock), 5)
                conn.setblocking(False)
                # client sends one TCP query and never reads the answers
                raw = s.socket(s.AF_INET, s.SOCK_STREAM)
                raw.setsockopt(s.SOL_SOCKET, s.SO_RCVBUF, 4096)
                raw.setblocking(False)
                await loop.sock_connect(raw, ("127.0.0.1", port))
                wire = make_query("web.foo.com", Type.A, qid=9).encode()
                await loop.sock_sendall(
                    raw, struct.pack(">H", len(wire)) + wire)
                # fake backend reads the forwarded frame to learn the
                # client's address key...
                hdr = await asyncio.wait_for(
                    loop.sock_recv(conn, 4), 5)
                (flen,) = struct.unpack(">I", hdr)
                frame = b""
                while len(frame) < flen:
                    frame += await loop.sock_recv(conn, flen - len(frame))
                key = frame[:21]   # ver+family+transport+addr+port
                # ...then blasts ~24 MB of response frames at that key;
                # the kernel absorbs a few MB, the 64 KB queue cap must
                # absorb NONE of the rest
                payload = b"\xab" * 4096
                resp = struct.pack(">I", 21 + len(payload)) + key + payload
                sent = 0
                try:
                    for _ in range(6000):
                        await loop.sock_sendall(conn, resp)
                        sent += 1
                except (BrokenPipeError, ConnectionResetError):
                    pass
                deadline = loop.time() + 5
                stats = read_stats(sockdir)
                while (stats["tcp_clients"] != 0
                       and loop.time() < deadline):
                    await asyncio.sleep(0.1)
                    stats = read_stats(sockdir)
                raw.close()
                conn.close()
            finally:
                proc.kill()
                await proc.wait()
                lsock.close()
            return stats, sent

        stats, sent = asyncio.run(run())
        assert stats["wq_overflows"] >= 1, stats
        assert stats["tcp_clients"] == 0, stats


@pytest.mark.skipif(not os.path.exists(BALANCER),
                    reason="mbalancer not built")
def test_ephemeral_pair_bind_survives_tcp_squatters(tmp_path):
    """mbalancer -p 0 binds UDP first and rebinds TCP to that number —
    which any unrelated socket may hold (observed in a full-bench run:
    'bind tcp: Address already in use' startup death). With a big slice
    of the ephemeral range squatted on TCP, repeated starts must always
    come up and answer on the advertised UDP port (the pair-bind retry
    redraws instead of dying)."""
    async def run():
        squatters = []
        try:
            for _ in range(1500):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                try:
                    s.bind(("127.0.0.1", 0))
                    s.listen(1)
                except OSError:
                    s.close()
                    break
                squatters.append(s)
            for i in range(30):
                proc, port = await start_balancer(str(tmp_path))
                try:
                    # advertised port must actually be HELD on UDP:
                    # binding it ourselves must fail (a UDP connect()
                    # would succeed even against a dead port)
                    probe = socket.socket(socket.AF_INET,
                                          socket.SOCK_DGRAM)
                    try:
                        probe.bind(("127.0.0.1", port))
                        raise AssertionError(
                            f"advertised UDP port {port} not held")
                    except OSError:
                        pass
                    finally:
                        probe.close()
                finally:
                    proc.kill()
                    await proc.wait()
        finally:
            for s in squatters:
                s.close()

    asyncio.run(run())


class TestFrontedByteParity:
    """ISSUE 18: answers through the balancer must be byte-identical
    to direct serving — on BOTH fronted lanes.  UDP rides direct
    return (the backend answers on the balancer's passed socket), TCP
    rides the relay (the client's TCP connection terminates inside the
    balancer), and neither transformation may touch the DNS payload:
    same truncation decision (TC=1 at the classic 512 limit — the
    frame's transport byte carries UDP semantics to the backend), same
    flags, same records.  Queries use identical qids on both paths so
    "modulo ID" reduces to exact equality."""

    @staticmethod
    def _fat_fixture(tag):
        # web = single deterministic answer (exact-bytes compare);
        # svc = 40 lb addresses, >512b without EDNS -> TC=1 on UDP
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        store.put_json("/com/foo/web",
                       {"type": "host",
                        "host": {"address": f"10.42.0.{tag}"}})
        store.put_json("/com/foo/svc", {
            "type": "service",
            "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432}})
        for i in range(40):
            store.put_json(f"/com/foo/svc/lb{i}",
                           {"type": "load_balancer",
                            "load_balancer": {"address": f"10.77.0.{i + 1}"}})
        store.start_session()
        return cache

    async def _start_fat_backend(self, sockdir, instance, tag):
        server = BinderServer(
            zk_cache=self._fat_fixture(tag), dns_domain=DOMAIN,
            datacenter_name="dc0", host="127.0.0.1", port=0,
            balancer_socket=os.path.join(sockdir, str(instance)),
            collector=MetricsCollector())
        await server.start()
        return server

    @staticmethod
    async def _raw_udp_ask(port, wire, timeout=5.0):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        class Proto(asyncio.DatagramProtocol):
            def connection_made(self, transport):
                transport.sendto(wire)

            def datagram_received(self, data, addr):
                if not fut.done():
                    fut.set_result(data)

        transport, _ = await loop.create_datagram_endpoint(
            Proto, remote_addr=("127.0.0.1", port))
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            transport.close()

    @staticmethod
    async def _raw_tcp_ask(port, wire, timeout=5.0):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            writer.write(struct.pack(">H", len(wire)) + wire)
            await writer.drain()
            (ln,) = struct.unpack(">H", await asyncio.wait_for(
                reader.readexactly(2), timeout))
            return await reader.readexactly(ln)
        finally:
            writer.close()
            await writer.wait_closed()

    @staticmethod
    async def _wait_direct(sockdir, timeout=10.0):
        # parity through the direct lane is only meaningful once the
        # fd pass has actually happened — otherwise the ask would ride
        # the relay and the test would vacuously pass
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            try:
                stats = read_stats(sockdir)
                if any(b.get("direct") for b in stats.get("backends", [])):
                    return stats
            except (OSError, ValueError):
                pass
            if asyncio.get_running_loop().time() > deadline:
                raise AssertionError("fd pass never completed")
            await asyncio.sleep(0.1)

    def test_fronted_lanes_byte_identical_to_direct(self, tmp_path):
        async def run():
            sockdir = str(tmp_path)
            backend = await self._start_fat_backend(sockdir, 5301, 7)
            proc, fport = await start_balancer(sockdir)
            try:
                await self._wait_direct(sockdir)

                # -- UDP, single answer (direct-return lane) --
                q = make_query("web.foo.com", Type.A, qid=41).encode()
                via_bal = await self._raw_udp_ask(fport, q)
                direct = await self._raw_udp_ask(backend.udp_port, q)
                assert via_bal == direct
                m = Message.decode(via_bal)
                assert not m.tc and len(m.answers) == 1

                # the answer really came over the passed socket, not
                # the relay fallback
                stats = read_stats(sockdir)
                assert stats["direct_forwards"] >= 1
                assert stats["fd_passes"] >= 1

                # -- UDP, no EDNS, >512b answer: TC=1 both ways --
                q = make_query("svc.foo.com", Type.A, qid=42,
                               edns_payload=None).encode()
                via_bal = await self._raw_udp_ask(fport, q)
                direct = await self._raw_udp_ask(backend.udp_port, q)
                assert via_bal == direct
                m = Message.decode(via_bal)
                assert m.tc and not m.answers

                # -- TCP (relay lane): full-size answers --
                q = make_query("web.foo.com", Type.A, qid=43).encode()
                via_bal = await self._raw_tcp_ask(fport, q)
                direct = await self._raw_tcp_ask(backend.tcp_port, q)
                assert via_bal == direct
                m = Message.decode(via_bal)
                assert not m.tc and len(m.answers) == 1

                # TCP svc: no truncation on the stream lane; answer
                # sets match (order-insensitive — multi-answer
                # responses rotate independently per query)
                q = make_query("svc.foo.com", Type.A, qid=44).encode()
                via_bal = await self._raw_tcp_ask(fport, q)
                direct = await self._raw_tcp_ask(backend.tcp_port, q)
                mb, md = Message.decode(via_bal), Message.decode(direct)
                assert not mb.tc and not md.tc
                assert len(mb.answers) == 40 and len(md.answers) == 40
                def rdatas(msg):
                    out = []
                    for r in msg.answers:
                        buf = bytearray()
                        r.encode_rdata(buf, {})
                        out.append(bytes(buf))
                    return sorted(out)

                assert rdatas(mb) == rdatas(md)
            finally:
                proc.kill()
                await proc.wait()
                await backend.stop()

        asyncio.run(run())
