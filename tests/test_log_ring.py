"""Native query-log ring: the fast path serving under per-query logging.

The reference logs every query unconditionally (lib/server.js:537-591);
before round 5 that posture forced the rebuild's native tier to stand
down entirely.  These tests pin the round-5 contract:

- with a JSON logger attached and queryLog on, the native path serves
  (zone + answer-cache) AND every serve produces a complete bunyan-style
  log line on the same stream the Python logger writes to;
- the line shape matches the Python path's for the same event class
  (cached hits log ``cached: true`` + rcode + summaries; zone serves log
  the resolve-shape ``query`` object);
- lanes without a C drain (TCP) log through the same ring;
- without a JSON stream logger the old stand-down gating is unchanged.
"""
import asyncio
import io
import json
import logging

import pytest

from binder_tpu.dns import Rcode, Type
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.utils.jsonlog import make_logger

try:
    from binder_tpu import _binderfastio as fastio
except ImportError:
    fastio = None

pytestmark = pytest.mark.skipif(
    fastio is None or not hasattr(fastio, "fastpath_log_enable"),
    reason="native extension with log ring not built")

DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(3):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_logged_server(cache, stream, **kw):
    log = make_logger("binder-logring-test", stream=stream)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1",
                          port=0, collector=MetricsCollector(),
                          log=log, query_log=True, **kw)
    await server.start()
    return server


from tests.test_server import tcp_ask  # shared DNS-ask helpers
from tests.test_server import udp_ask as _server_udp_ask


async def udp_ask(port, name, qtype, qid=4242, payload=1232):
    return await _server_udp_ask(port, name, qtype, payload=payload,
                                 qid=qid)


def log_lines(server, stream):
    server._drain_native_log()
    return [json.loads(ln) for ln in stream.getvalue().splitlines()]


class TestLogRing:
    def test_ring_armed_with_json_logger(self):
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            server = await start_logged_server(cache, stream)
            try:
                assert server._log_ring
                assert server._fastpath_active()
            finally:
                await server.stop()

        asyncio.run(run())

    def test_zone_serve_logs_resolve_shape(self):
        """Cold A query in the logged posture: served natively from the
        precompiled zone AND logged with the resolve-shape line."""
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            server = await start_logged_server(cache, stream)
            try:
                r1 = await udp_ask(server.udp_port, "web.foo.com",
                                   Type.A, qid=100)
                r2 = await udp_ask(server.udp_port, "web.foo.com",
                                   Type.A, qid=101)
                assert r1.rcode == r2.rcode == Rcode.NOERROR
                assert r1.answers[0].address == "192.168.0.1"
                stats = fastio.fastpath_stats(server._fastpath)
                assert stats["zone_hits"] >= 2      # served natively
                assert stats["log_lines"] >= 2      # ...and logged
                lines = log_lines(server, stream)
                qlines = [l for l in lines if l.get("msg") == "DNS query"]
                assert len(qlines) == 2
                for ln, qid in zip(qlines, (100, 101)):
                    assert ln["req_id"] == qid
                    assert ln["client"] == "127.0.0.1"
                    assert ln["port"].endswith("/udp")
                    assert ln["edns"] is True
                    assert ln["rcode"] == "NOERROR"
                    assert ln["query"] == {"srv": None,
                                           "name": "web.foo.com",
                                           "type": "A"}
                    assert ln["answers"] == ["web... A 192.168.0.1"]
                    assert ln["additional"] == []
                    assert ln["level"] == 30
                    assert ln["name"] == "binder-logring-test"
                    assert isinstance(ln["latency"], float)
                    assert "T" in ln["time"] and ln["time"].endswith("Z")
            finally:
                await server.stop()

        asyncio.run(run())

    def test_cached_hit_logs_cached_shape(self):
        """A shape the zone can't serve (out-of-suffix REFUSED): first
        query logs through Python, repeats serve natively from the
        answer cache and log the Python hit-path shape (cached: true)."""
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            server = await start_logged_server(cache, stream)
            try:
                r1 = await udp_ask(server.udp_port, "x.example.com",
                                   Type.A, qid=200)
                # first repeat promotes (r5 promote-on-first-hit); the
                # next repeat is the native one
                await udp_ask(server.udp_port, "x.example.com",
                              Type.A, qid=205)
                r2 = await udp_ask(server.udp_port, "x.example.com",
                                   Type.A, qid=201)
                assert r1.rcode == r2.rcode == Rcode.REFUSED
                stats = fastio.fastpath_stats(server._fastpath)
                assert stats["hits"] >= 1           # native cache hit
                lines = log_lines(server, stream)
                by_id = {l["req_id"]: l for l in lines
                         if l.get("msg") == "DNS query"}
                # first: Python resolve line (has the reason field)
                assert by_id[200]["rcode"] == "REFUSED"
                assert by_id[200]["reason"] == \
                    "not within dns domain suffix"
                # repeat: native line with the hit-path shape
                assert by_id[201]["rcode"] == "REFUSED"
                assert by_id[201]["cached"] is True
                assert by_id[201]["answers"] == []
            finally:
                await server.stop()

        asyncio.run(run())

    def test_tcp_serve_logs_through_ring(self):
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            server = await start_logged_server(cache, stream)
            try:
                r = await tcp_ask(server.tcp_port, "web.foo.com", Type.A,
                                  qid=300, edns_payload=None)
                assert r.rcode == Rcode.NOERROR
                stats = fastio.fastpath_stats(server._fastpath)
                assert stats["zone_hits"] >= 1
                lines = log_lines(server, stream)
                tcp_lines = [l for l in lines
                             if l.get("req_id") == 300]
                assert len(tcp_lines) == 1
                assert tcp_lines[0]["port"].endswith("/tcp")
                assert tcp_lines[0]["edns"] is False
                assert tcp_lines[0]["answers"] == ["web... A 192.168.0.1"]
            finally:
                await server.stop()

        asyncio.run(run())

    def test_srv_zone_serve_logs_rotating_answers(self):
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            server = await start_logged_server(cache, stream)
            try:
                r = await udp_ask(server.udp_port,
                                  "_pg._tcp.svc.foo.com", Type.SRV,
                                  qid=400)
                assert r.rcode == Rcode.NOERROR
                assert len(r.answers) == 3
                lines = log_lines(server, stream)
                srv = [l for l in lines if l.get("req_id") == 400]
                assert len(srv) == 1
                assert srv[0]["query"]["srv"] == "_pg._tcp"
                assert srv[0]["query"]["type"] == "SRV"
                # logged answers must be the exact served rotation
                served = [f"SRV {a.target.split('.')[0]}.svc...:{a.port}"
                          for a in r.answers]
                assert srv[0]["answers"] == served
                assert len(srv[0]["additional"]) == 3
            finally:
                await server.stop()

        asyncio.run(run())

    def test_no_json_logger_keeps_stand_down(self):
        """queryLog on with a non-JSON logger: ring unavailable, the
        fast path stands down exactly as before round 5."""
        async def run():
            store, cache = fixture_store()
            plain = logging.getLogger("binder-logring-plain")
            plain.setLevel(logging.INFO)
            plain.propagate = False
            plain.handlers = [logging.NullHandler()]
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector(),
                                  log=plain, query_log=True)
            await server.start()
            try:
                assert not server._log_ring
                assert not server._fastpath_active()
                r1 = await udp_ask(server.udp_port, "web.foo.com", Type.A)
                r2 = await udp_ask(server.udp_port, "web.foo.com", Type.A)
                assert r1.rcode == r2.rcode == Rcode.NOERROR
                stats = fastio.fastpath_stats(server._fastpath)
                assert stats["zone_hits"] == 0
                assert stats["hits"] == 0
            finally:
                await server.stop()

        asyncio.run(run())

    def test_logged_matches_unlogged_wire(self):
        """Differential: the logged posture must serve byte-identical
        answers to the log-off posture (modulo id) for the same store."""
        async def run():
            store, cache = fixture_store()
            stream = io.StringIO()
            logged = await start_logged_server(cache, stream)
            store2, cache2 = fixture_store()
            quiet = BinderServer(zk_cache=cache2, dns_domain=DOMAIN,
                                 datacenter_name="coal",
                                 host="127.0.0.1", port=0,
                                 collector=MetricsCollector(),
                                 query_log=False)
            await quiet.start()
            try:
                for name, qt in (("web.foo.com", Type.A),
                                 ("svc.foo.com", Type.A),
                                 ("_pg._tcp.svc.foo.com", Type.SRV),
                                 ("1.0.168.192.in-addr.arpa", Type.PTR),
                                 ("nope.foo.com", Type.A)):
                    a = await udp_ask(logged.udp_port, name, qt, qid=1)
                    b = await udp_ask(quiet.udp_port, name, qt, qid=1)
                    assert a.rcode == b.rcode, name
                    assert ([type(x).__name__ for x in a.answers]
                            == [type(x).__name__ for x in b.answers]), name
            finally:
                await logged.stop()
                await quiet.stop()

        asyncio.run(run())
