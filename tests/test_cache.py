"""Tests for the coordination-store mirror cache (binder_tpu/store).

Covers the reference's watch-tree semantics (lib/zk.js) plus the churn /
session-reset hazards SURVEY §7.3 calls out — none of which the reference
itself tests (it has no fake store, SURVEY §4).
"""


from binder_tpu.store import FakeStore, MirrorCache, domain_to_path


DOMAIN = "foo.com"


def make_cache():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    return store, cache


def host(addr, **extra):
    rec = {"type": "host", "host": {"address": addr}}
    rec.update(extra)
    return rec


class TestDomainPath:
    def test_mapping(self):
        assert domain_to_path("a.foo.com") == "/com/foo/a"
        assert domain_to_path("foo.com") == "/com/foo"


class TestReadiness:
    def test_not_ready_before_session(self):
        store, cache = make_cache()
        assert not cache.is_ready()

    def test_ready_after_session(self):
        store, cache = make_cache()
        store.start_session()
        assert cache.is_ready()

    def test_ready_survives_session_loss(self):
        # reference keeps serving from the stale mirror during reconnects
        store, cache = make_cache()
        store.start_session()
        store.expire_session()
        assert cache.is_ready()


class TestMirror:
    def test_host_lookup(self):
        store, cache = make_cache()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.start_session()
        node = cache.lookup("web.foo.com")
        assert node is not None
        assert node.data["host"]["address"] == "10.0.0.5"

    def test_fixture_added_after_session(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        assert cache.lookup("web.foo.com").data["type"] == "host"

    def test_reverse_lookup(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        assert cache.reverse_lookup("10.0.0.5").domain == "web.foo.com"

    def test_deep_tree_children(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/svc", {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80},
        })
        for i in range(3):
            store.put_json(f"/com/foo/svc/h{i}",
                           {"type": "load_balancer",
                            "load_balancer": {"address": f"10.0.1.{i}"}})
        node = cache.lookup("svc.foo.com")
        assert len(node.children) == 3
        assert cache.lookup("h1.svc.foo.com") is not None

    def test_data_update_moves_reverse_entry(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.put_json("/com/foo/web", host("10.0.0.9"))
        assert cache.reverse_lookup("10.0.0.5") is None
        assert cache.reverse_lookup("10.0.0.9").domain == "web.foo.com"

    def test_node_removal_unbinds_subtree(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/svc", {"type": "service",
                                        "service": {"port": 80}})
        store.put_json("/com/foo/svc/h0",
                       {"type": "host", "host": {"address": "10.0.2.1"}})
        assert cache.lookup("h0.svc.foo.com") is not None
        store.rmr("/com/foo/svc")
        assert cache.lookup("svc.foo.com") is None
        assert cache.lookup("h0.svc.foo.com") is None

    def test_node_removal_drops_reverse_entry(self):
        # deliberate fix over the reference, which leaks ca_revLookup
        # entries on unbind (lib/zk.js:195-208)
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.rmr("/com/foo/web")
        assert cache.reverse_lookup("10.0.0.5") is None

    def test_reverse_entry_collision_guarded(self):
        # two nodes claim the same IP; the loser updating away must not
        # clobber the winner's entry (reference deletes unconditionally)
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/a", host("10.0.0.5"))
        store.put_json("/com/foo/b", host("10.0.0.5"))  # b now owns rev
        store.put_json("/com/foo/a", host("10.0.0.6"))
        assert cache.reverse_lookup("10.0.0.5").domain == "b.foo.com"
        assert cache.reverse_lookup("10.0.0.6").domain == "a.foo.com"


class TestBadData:
    def test_unparseable_json_keeps_old_data(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.set_data("/com/foo/web", b"{not json")
        node = cache.lookup("web.foo.com")
        assert node.data["host"]["address"] == "10.0.0.5"

    def test_scalar_json_ignored(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.set_data("/com/foo/web", b"42")
        assert cache.lookup("web.foo.com").data["type"] == "host"

    def test_null_json_accepted_as_empty(self):
        # JS typeof null === 'object': null replaces data (lib/zk.js:149-155)
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.set_data("/com/foo/web", b"null")
        assert cache.lookup("web.foo.com").data is None

    def test_no_data_node(self):
        store, cache = make_cache()
        store.start_session()
        store.mkdirp("/com/foo/empty")
        node = cache.lookup("empty.foo.com")
        assert node is not None and node.data is None


class TestSessionChurn:
    def test_rebuild_after_expiry_reflects_changes(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        store.expire_session()
        assert cache.lookup("web.foo.com").data["host"]["address"] == "10.0.0.5"
        store.put_json("/com/foo/web2", host("10.0.0.7"))
        assert cache.lookup("web2.foo.com") is not None

    def test_no_duplicate_event_delivery_after_rebinds(self):
        """Rebinding N times must not register N listeners (lib/zk.js
        clears listeners before re-adding; leak hazard in SURVEY §7.3).
        The mirror binds through the store's single-slot node binding,
        so duplication would show up as multiple deliveries per fired
        event."""
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        for _ in range(5):
            store.expire_session()
        # exactly one bound listener: one data event -> exactly one
        # application (one generation bump), not 2^rebinds
        gen0 = cache.gen
        store.set_data("/com/foo/web",
                       b'{"type": "host", "host": {"address": "10.0.0.6"}}')
        assert cache.gen - gen0 == 1
        assert cache.lookup("web.foo.com").data["host"]["address"] \
            == "10.0.0.6"

    def test_removed_subtree_watchers_are_silent(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/svc", {"type": "service",
                                        "service": {"port": 80}})
        store.put_json("/com/foo/svc/h0", host("10.0.2.1"))
        store.rmr("/com/foo/svc")
        w = store.watcher(domain_to_path("h0.svc.foo.com"))
        assert not w.has_listeners
        # re-creating the path must resurrect cleanly via the parent diff
        store.put_json("/com/foo/svc", {"type": "service",
                                        "service": {"port": 80}})
        store.put_json("/com/foo/svc/h0", host("10.0.2.9"))
        assert cache.lookup("h0.svc.foo.com").data["host"]["address"] == \
            "10.0.2.9"
        assert cache.reverse_lookup("10.0.2.9") is not None


class TestReviewRegressions:
    """Regressions from the second code-review pass."""

    def test_type_change_drops_reverse_entry(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web", host("10.0.0.5"))
        assert cache.reverse_lookup("10.0.0.5") is not None
        store.put_json("/com/foo/web",
                       {"type": "service", "service": {"port": 80}})
        assert cache.reverse_lookup("10.0.0.5") is None

    def test_rebind_not_exponential(self):
        """Session rebinds must touch each node O(1) times, not 2^depth."""
        store, cache = make_cache()
        store.start_session()
        # 6-deep chain under foo.com
        path = "/com/foo"
        for label in ["a", "b", "c", "d", "e", "f"]:
            path += f"/{label}"
            store.put_json(path, host("10.9.9.9") if label == "f" else
                           {"type": "service", "service": {"port": 1}})
        calls = {"n": 0}
        orig_bind = store.bind_node

        def counting_bind(path, node):
            if path == "/com/foo/a/b/c/d/e/f":
                calls["n"] += 1
            orig_bind(path, node)

        store.bind_node = counting_bind
        store.expire_session()
        # one session rebuild -> the deep node is re-bound exactly once
        # (each bind delivers initial children+data state)
        assert calls["n"] <= 2, calls["n"]
