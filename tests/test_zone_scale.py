"""Zone-scale representation tests (ISSUE 7): interned-name pool,
compact node records, chunked session rebuild, scale-aware
backpressure, the late-drop counter, and the binder_mirror_* metric
family pins.

The heavyweight end-to-end figures (RSS/name, 1M-name serving) live in
the bench's zone_scale axis and `make zone-smoke`; these tests pin the
MECHANISMS at sizes tier-1 can afford.
"""
import asyncio
import json
import time

from binder_tpu.dns.server import DnsServer
from binder_tpu.introspect import FlightRecorder
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.store.fake import populate_synthetic
from binder_tpu.store.names import (
    NamePool,
    compact_record,
    expand_record,
    rec_parts,
)

from tools.lint import validate_mirror_metrics  # noqa: E402
from tools.zone_probe import Harness, host_name, host_path  # noqa: E402

DOMAIN = "foo.com"


def make_cache(**kw):
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN, **kw)
    return store, cache


class TestNamePool:
    def test_interning_returns_one_canonical_object(self):
        pool = NamePool()
        a = pool.intern("host-a.foo.com")
        b = pool.intern("host-" + "a.foo.com")
        assert a is b
        assert pool.hits == 1

    def test_bytes_interning(self):
        pool = NamePool()
        a = pool.intern_bytes(b"\x03foo\x00")
        b = pool.intern_bytes(bytes(b"\x03foo\x00"))
        assert a is b

    def test_sweep_drops_dead_entries(self):
        pool = NamePool()
        keep = pool.intern("live-name.example")
        for i in range(100):
            pool.intern(f"dead-{i}.example")
        dropped = pool.sweep()
        assert dropped >= 100
        # the live name survived (we still hold a reference)
        assert pool.intern("live-name.example") is keep

    def test_stats_shape(self):
        pool = NamePool()
        pool.intern("x.example")
        st = pool.stats()
        for key in ("interned", "interned_str", "interned_bytes",
                    "hits", "sweeps"):
            assert key in st


class TestCompactRecord:
    CASES = [
        {"type": "host", "host": {"address": "10.0.0.1"}},
        {"type": "load_balancer",
         "load_balancer": {"address": "10.0.0.2", "ttl": 5}},
        {"type": "host", "host": {"address": "10.0.0.3"}, "ttl": 60},
        {"type": "rr_host",
         "rr_host": {"address": "10.9.9.9", "ttl": 1}, "ttl": 2},
    ]

    def test_host_shapes_compact_and_round_trip(self):
        for case in self.CASES:
            rec = compact_record(json.loads(json.dumps(case)))
            assert type(rec) is tuple, case
            assert expand_record(rec) == case
            rtype, addr, ttl, sttl = rec_parts(rec)
            assert rtype == case["type"]
            assert addr == case[case["type"]]["address"]

    def test_ttl_less_shape_packs_to_pair(self):
        rec = compact_record({"type": "host",
                              "host": {"address": "10.0.0.1"}})
        assert len(rec) == 2

    def test_non_host_shapes_stay_dicts(self):
        for case in (
            {"type": "service",
             "service": {"srvce": "_h", "proto": "_t", "port": 1}},
            {"type": "database", "database": {"primary": "tcp://x/"}},
            # host-like but with an extra field that must round-trip
            {"type": "host", "host": {"address": "10.0.0.1"},
             "extra": 1},
            {"type": "host",
             "host": {"address": "10.0.0.1", "ports": [1]}},
            # non-string address
            {"type": "host", "host": {"address": 42}},
        ):
            rec = compact_record(json.loads(json.dumps(case)))
            assert type(rec) is dict, case
            assert rec == case

    def test_lists_and_null_pass_through(self):
        assert compact_record(None) is None
        assert compact_record([1, 2]) == [1, 2]


class TestCompactMirror:
    def test_node_rec_is_tuple_for_hosts(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.5"}})
        node = cache.lookup("web.foo.com")
        assert type(node.rec) is tuple
        # the data property reconstructs the parsed-JSON shape
        assert node.data == {"type": "host",
                             "host": {"address": "10.0.0.5"}}
        assert node.ip == "10.0.0.5"
        assert node.name == "web"
        assert node.path == "/com/foo/web"
        # leaves allocate no kids container
        assert node.kids is None

    def test_children_resolve_through_node_index(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/svc", {
            "type": "service",
            "service": {"srvce": "_http", "proto": "_tcp", "port": 80}})
        for i in range(3):
            store.put_json(f"/com/foo/svc/h{i}",
                           {"type": "load_balancer",
                            "load_balancer": {"address": f"10.0.1.{i}"}})
        node = cache.lookup("svc.foo.com")
        assert sorted(k.name for k in node.children) == ["h0", "h1", "h2"]
        assert all(type(k.rec) is tuple for k in node.children)

    def test_canon_returns_mirror_domain_object(self):
        store, cache = make_cache()
        store.start_session()
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.5"}})
        node = cache.lookup("web.foo.com")
        # a query-decoded copy of the name canonicalizes to THE object
        copy = "web" + ".foo.com"
        assert copy is not node.domain
        assert cache.canon(copy) is node.domain


class TestChunkedRebuild:
    def _zone(self, n):
        store = FakeStore()
        populate_synthetic(store, DOMAIN, n, racks=4)
        cache = MirrorCache(store, DOMAIN)
        return store, cache

    def test_inline_rebuild_without_loop(self):
        store, cache = self._zone(500)
        store.start_session()
        assert cache.rebuild_pending() == 0
        epoch0 = cache.epoch
        store.expire_session()
        # no loop: drained inline to completion, one epoch bump
        assert cache.rebuild_pending() == 0
        assert cache.epoch == epoch0 + 1
        assert cache.lookup(host_name_under(DOMAIN, 7, 4)) is not None
        assert cache.last_rebuild_duration_s is not None

    def test_chunked_rebuild_serves_throughout(self):
        async def run():
            store, cache = self._zone(4000)
            store.start_session()       # initial build (new subtree)
            name = host_name_under(DOMAIN, 123, 4)
            assert cache.lookup(name) is not None
            epoch0 = cache.epoch
            chunks0 = cache.rebuild_chunks
            store.expire_session()
            # the walk is in flight: pending nodes remain after the
            # inline first chunk, and serving continues underneath
            assert cache.rebuild_pending() > 0
            assert cache.epoch == epoch0 + 1
            served = 0
            while cache.rebuild_pending():
                node = cache.lookup(name)
                assert node is not None, "lookup went dark mid-rebuild"
                assert node.ip is not None
                served += 1
                await asyncio.sleep(0.001)
            assert served > 0
            assert cache.rebuild_chunks - chunks0 > 1
            assert cache.epoch == epoch0 + 1   # ONE bump per rebuild
            assert cache.lookup(name).data["host"]["address"]
            return cache

        asyncio.run(run())

    def test_rebuild_superseded_by_newer_session(self):
        async def run():
            store, cache = self._zone(3000)
            store.start_session()
            store.expire_session()
            assert cache.rebuild_pending() > 0
            epoch1 = cache.epoch
            store.expire_session()      # churn mid-rebuild: restart walk
            assert cache.epoch == epoch1 + 1
            while cache.rebuild_pending():
                await asyncio.sleep(0.001)
            # converged: data intact after the doubled rebuild
            assert cache.lookup(
                host_name_under(DOMAIN, 42, 4)).ip is not None

        asyncio.run(run())

    def test_mutation_latency_independent_of_zone_size(self):
        """O(delta) pin: p50 single-name mutation latency at 20x the
        zone size stays within a small factor (an O(zone) path would
        scale ~20x)."""
        def measure(n):
            store = FakeStore()
            populate_synthetic(store, "bench.zone", n)
            cache = MirrorCache(store, "bench.zone")
            store.start_session()
            h = Harness(cache)
            racks = max(1, min(1024, n // 512))
            lats = []
            for j in range(60):
                i = (j * max(1, n // 60)) % n
                h.prime(host_name(i, racks))
                body = json.dumps(
                    {"type": "host",
                     "host": {"address": f"10.77.{j // 250}.{j % 250}"}}
                ).encode()
                t0 = time.perf_counter()
                store.set_data(host_path(i, racks), body)
                lats.append(time.perf_counter() - t0)
            lats.sort()
            return lats[len(lats) // 2]

        small = measure(1000)
        large = measure(20000)
        assert large / small < 6.0, (small, large)


def host_name_under(domain: str, i: int, racks: int) -> str:
    return f"h{i:06d}.r{i % racks:04d}.zs.{domain}"


class TestLateDropAccounting:
    def test_counter_and_flight_event(self):
        recorder = FlightRecorder(capacity=16)
        collector = MetricsCollector()
        counter = collector.counter("binder_udp_late_drops_total",
                                    "test")
        srv = DnsServer()
        srv.recorder = recorder
        srv.late_drop_counter = counter.labelled()
        srv.note_late_drops(3)
        srv.note_late_drops(2)          # same window: no second event
        assert srv.udp_late_drops == 5
        assert counter.total() == 5
        events = [e for e in recorder.events()
                  if e["type"] == "udp-late-drop"]
        assert len(events) == 1
        assert events[0]["dropped"] == 3
        assert events[0]["total"] == 3
        # a later window records again
        srv._late_drop_event_last -= srv.LATE_DROP_EVENT_WINDOW_S + 1
        srv.note_late_drops(1)
        events = [e for e in recorder.events()
                  if e["type"] == "udp-late-drop"]
        assert len(events) == 2
        assert events[-1]["total"] == 6

    def test_zero_is_a_noop(self):
        srv = DnsServer()
        srv.note_late_drops(0)
        assert srv.udp_late_drops == 0


class TestMirrorMetricsExposition:
    def test_server_scrape_passes_mirror_validator(self):
        collector = MetricsCollector()
        store = FakeStore()
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.1"}})
        cache = MirrorCache(store, DOMAIN, collector=collector)
        store.start_session()
        BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                     collector=collector, cache_size=16)
        errs = validate_mirror_metrics(collector.expose())
        assert errs == []

    def test_validator_rejects_missing_family(self):
        collector = MetricsCollector()
        store = FakeStore()
        MirrorCache(store, DOMAIN, collector=collector)
        # no server: the late-drop counter family is absent
        errs = validate_mirror_metrics(collector.expose())
        assert any("binder_udp_late_drops_total" in e for e in errs)

    def test_rebuild_metrics_move(self):
        collector = MetricsCollector()
        store = FakeStore()
        populate_synthetic(store, DOMAIN, 1000, racks=2)
        cache = MirrorCache(store, DOMAIN, collector=collector)
        store.start_session()
        store.expire_session()
        text = collector.expose()
        assert "binder_mirror_names" in text
        chunks = [line for line in text.splitlines()
                  if line.startswith("binder_mirror_rebuild_chunks")]
        assert chunks and float(chunks[0].split()[-1]) >= 1.0


class TestSharedWatchScaling:
    """ROADMAP 3b: with the mirror index offered via ``bind_source``,
    the real-ZK owner registers ONE wire watch per host leaf (the data
    watch) and children watches only where children can exist, so the
    ensemble-side watch table — and the session re-establishment
    chatter — scales with directories, not names."""

    N_HOSTS = 40
    N_SVC = 4
    N_LB = 3

    HOST = {"type": "host", "host": {"address": "10.3.0.1"}}
    SVC = {"type": "service",
           "service": {"srvce": "_http", "proto": "_tcp", "port": 80}}
    LB = {"type": "load_balancer",
          "load_balancer": {"address": "10.4.0.1"}}

    def test_watch_table_scales_with_directories_not_names(self):
        from binder_tpu.store.zk_client import ZKClient
        from binder_tpu.store.zk_testserver import ZKTestServer

        async def wait_for(pred, timeout=8.0):
            deadline = asyncio.get_running_loop().time() + timeout
            while asyncio.get_running_loop().time() < deadline:
                if pred():
                    return True
                await asyncio.sleep(0.01)
            return False

        async def run():
            server = ZKTestServer()
            await server.start()
            writer = ZKClient("127.0.0.1", port=server.port,
                              session_timeout_ms=4000)
            client = None
            try:
                assert await wait_for(writer.is_connected)
                for i in range(self.N_HOSTS):
                    await writer.mkdirp(f"/com/foo/h{i:03d}",
                                        json.dumps(self.HOST).encode())
                for s in range(self.N_SVC):
                    await writer.mkdirp(f"/com/foo/svc{s}",
                                        json.dumps(self.SVC).encode())
                    for j in range(self.N_LB):
                        await writer.mkdirp(f"/com/foo/svc{s}/lb{j}",
                                            json.dumps(self.LB).encode())

                client = ZKClient("127.0.0.1", port=server.port,
                                  session_timeout_ms=4000)
                cache = MirrorCache(client, DOMAIN)
                assert client._shared_nodes is cache.nodes  # mode is on
                client.start()

                total = 1 + self.N_HOSTS + self.N_SVC * (1 + self.N_LB)
                assert await wait_for(lambda: len(cache.nodes) == total)
                state = server.state
                sid = client._session_id

                def mine(table):
                    return {p for p, sids in table.items() if sid in sids}

                # one data watch per mirrored znode...
                assert await wait_for(
                    lambda: len(mine(state.data_watches)) == total)
                # ...but children watches ONLY on the root and the
                # service containers — none of the 52 host/lb leaves
                dirs = {"/com/foo"} | {f"/com/foo/svc{s}"
                                       for s in range(self.N_SVC)}
                assert mine(state.child_watches) == dirs
                assert len(dirs) * 8 < total  # the scaling claim itself

                # liveness is not traded away: every mutation class the
                # per-path watchers caught still flows to the mirror.
                await writer.mkdirp("/com/foo/hnew",
                                    json.dumps(self.HOST).encode())
                assert await wait_for(
                    lambda: cache.lookup("hnew.foo.com") is not None)
                await writer.set_data(
                    "/com/foo/svc0/lb0",
                    b'{"type": "load_balancer", '
                    b'"load_balancer": {"address": "10.4.9.9"}}')
                assert await wait_for(
                    lambda: cache.lookup("lb0.svc0.foo.com").ip
                    == "10.4.9.9")
                # a child appearing under an EXISTING container
                await writer.mkdirp("/com/foo/svc1/lbnew",
                                    json.dumps(self.LB).encode())
                assert await wait_for(
                    lambda: cache.lookup("lbnew.svc1.foo.com") is not None)
                # the leaf->parent case the container rule exists for:
                # a service created EMPTY gains its first child later
                await writer.mkdirp("/com/foo/svc9",
                                    json.dumps(self.SVC).encode())
                assert await wait_for(
                    lambda: cache.lookup("svc9.foo.com") is not None)
                await writer.mkdirp("/com/foo/svc9/lb0",
                                    json.dumps(self.LB).encode())
                assert await wait_for(
                    lambda: cache.lookup("lb0.svc9.foo.com") is not None)

                # session re-establishment re-registers the same SCALED
                # shape (historically this was the 2x-per-node storm)
                total += 4          # hnew, svc9, svc9/lb0, svc1/lbnew
                dirs |= {"/com/foo/svc9"}
                server.expire_session(client._session_id)
                assert await wait_for(
                    lambda: client.is_connected()
                    and client._session_id != sid)
                sid = client._session_id
                assert await wait_for(
                    lambda: len(mine(state.data_watches)) == total)
                assert await wait_for(
                    lambda: mine(state.child_watches) == dirs)
                assert len(cache.nodes) == total
            finally:
                if client is not None:
                    client.close()
                writer.close()
                await server.stop()

        asyncio.run(run())


class TestScaleAwareBackpressure:
    def test_precompile_bound_scales_with_zone(self):
        store = FakeStore()
        populate_synthetic(store, "bench.zone", 5000)
        cache = MirrorCache(store, "bench.zone")
        store.start_session()
        h = Harness(cache)
        assert h.pc._max_pending() >= 5000
        # and stays hard-capped
        assert h.pc._max_pending() <= h.pc.MAX_PENDING_CAP

    def test_compiled_answers_match_engine_at_scale(self):
        store = FakeStore()
        n = 3000
        populate_synthetic(store, "bench.zone", n)
        cache = MirrorCache(store, "bench.zone")
        store.start_session()
        h = Harness(cache)
        racks = max(1, min(1024, n // 512))
        for i in (0, n // 2, n - 1):
            name = host_name(i, racks)
            h.prime(name)
            assert h.compiled_wire(name) == h.engine_wire(name)
            # and across a mutation (the re-render path)
            store.set_data(host_path(i, racks),
                           b'{"type": "host", '
                           b'"host": {"address": "10.99.0.1"}}')
            assert h.compiled_wire(name) == h.engine_wire(name)

    def test_ptr_follows_compact_representation(self):
        store = FakeStore()
        populate_synthetic(store, "bench.zone", 600)
        cache = MirrorCache(store, "bench.zone")
        store.start_session()
        h = Harness(cache)
        racks = max(1, min(1024, 600 // 512))
        name = host_name(0, racks)
        node = cache.lookup(name)
        rev = cache.reverse_lookup(node.ip)
        assert rev is node
        plan = h.resolver.plan_ptr(
            ".".join(reversed(node.ip.split("."))) + ".in-addr.arpa")
        assert plan.groups and plan.groups[0][0][0].target == name
