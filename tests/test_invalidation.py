"""Per-name answer-cache invalidation (tags + epoch).

Correctness under mutation was already covered by test_answer_cache.py;
this module pins the new *selectivity* property — a mirrored mutation
drops exactly the answers whose dependency tag it touched, so unrelated
cached answers survive churn — plus the tag bookkeeping underneath it
(MirrorCache tag emission, AnswerCache tag index, the native cache's
fp_invalidate_tag via the _binderfastio module) and the epoch full-drop
on session rebuilds.
"""
import asyncio
import random

from binder_tpu.dns import Rcode, Type
from binder_tpu.resolver.answer_cache import AnswerCache
from binder_tpu.store import FakeStore, MirrorCache

from test_answer_cache import build, udp_ask

DOMAIN = "foo.com"


class TestAnswerCacheTags:
    def test_invalidate_tag_drops_only_matching(self):
        c = AnswerCache()
        c.put("k1", 0, "v1", tag="web.foo.com")
        c.put("k2", 0, "v2", tag="api.foo.com")
        c.put("k3", 0, "v3", tag="web.foo.com")
        assert c.invalidate_tag("web.foo.com") == 2
        assert c.get("k1", 0) is None
        assert c.get("k2", 0) == "v2"
        assert c.get("k3", 0) is None
        # index cleaned: a second invalidation is a no-op
        assert c.invalidate_tag("web.foo.com") == 0

    def test_eviction_keeps_tag_index_consistent(self):
        c = AnswerCache(size=2)
        c.put("k1", 0, "v1", tag="t")
        c.put("k2", 0, "v2", tag="t")
        c.put("k3", 0, "v3", tag="t")     # evicts k1
        assert c.invalidate_tag("t") == 2  # k2, k3 — not the evicted k1
        assert not c._entries and not c._by_tag

    def test_epoch_mismatch_still_drops(self):
        c = AnswerCache()
        c.put("k", 7, "v", tag="t")
        assert c.get("k", 8) is None       # stale epoch
        assert not c._entries and not c._by_tag


class TestMirrorTagEmission:
    def collect(self, store_mutations):
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.1.2.3"}})
        store.start_session()
        seen = []
        cache.on_invalidate(lambda tags: seen.append(set(tags)))
        store_mutations(store)
        return set().union(*seen) if seen else set()

    def test_data_change_emits_name_parent_and_both_rev_names(self):
        tags = self.collect(lambda s: s.put_json(
            "/com/foo/web", {"type": "host",
                             "host": {"address": "10.9.9.9"}}))
        assert {"web.foo.com", "foo.com",
                "3.2.1.10.in-addr.arpa",
                "9.9.9.10.in-addr.arpa"} <= tags

    def test_child_creation_emits_parent_and_child(self):
        tags = self.collect(lambda s: s.put_json(
            "/com/foo/api", {"type": "host",
                             "host": {"address": "10.4.4.4"}}))
        assert {"api.foo.com", "foo.com"} <= tags

    def test_delete_emits_name_parent_and_rev(self):
        tags = self.collect(lambda s: s.delete("/com/foo/web"))
        assert {"web.foo.com", "foo.com",
                "3.2.1.10.in-addr.arpa"} <= tags


class TestSelectiveInvalidation:
    def test_unrelated_mutation_keeps_cache_hot(self):
        """The perf property the global generation counter could not
        give: churn on one name must not evict every cached answer."""
        async def run():
            store, cache, server = build()
            await server.start()
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A, 1)
                await udp_ask(server.udp_port, "web.foo.com", Type.A, 2)
                hits_before = server.answer_cache.hits
                # churn a completely different subtree, hard
                for i in range(50):
                    store.put_json(
                        "/com/foo/churny",
                        {"type": "host",
                         "host": {"address": f"10.8.0.{i + 1}"}})
                r = await udp_ask(server.udp_port, "web.foo.com",
                                  Type.A, 3)
                hits_after = server.answer_cache.hits
                return r, hits_before, hits_after
            finally:
                await server.stop()

        r, before, after = asyncio.run(run())
        assert r.answers[0].address == "192.168.0.1"
        assert after == before + 1     # still a cache hit after 50 mutations

    def test_mutated_name_served_fresh_others_stay_cached(self):
        async def run():
            store, cache, server = build()
            await server.start()
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A, 1)
                r_srv1 = await udp_ask(server.udp_port,
                                       "_pg._tcp.svc.foo.com", Type.SRV, 2)
                store.put_json(
                    "/com/foo/web",
                    {"type": "host", "host": {"address": "172.16.0.9"}})
                r_web = await udp_ask(server.udp_port, "web.foo.com",
                                      Type.A, 3)
                r_old_ptr = await udp_ask(server.udp_port,
                                          "1.0.168.192.in-addr.arpa",
                                          Type.PTR, 4)
                r_new_ptr = await udp_ask(server.udp_port,
                                          "9.0.16.172.in-addr.arpa",
                                          Type.PTR, 5)
                return r_srv1, r_web, r_old_ptr, r_new_ptr
            finally:
                await server.stop()

        r_srv, r_web, r_old_ptr, r_new_ptr = asyncio.run(run())
        assert r_web.answers[0].address == "172.16.0.9"
        assert r_old_ptr.rcode == Rcode.REFUSED
        assert r_new_ptr.answers[0].target == "web.foo.com"
        assert len(r_srv.answers) == 4

    def test_service_child_add_refreshes_parent_answers(self):
        async def run():
            store, cache, server = build()
            await server.start()
            try:
                # warm the rotation set fully (4 LBs, rotatable entries
                # need the variant set collected)
                for i in range(12):
                    await udp_ask(server.udp_port, "svc.foo.com",
                                  Type.A, 10 + i)
                store.put_json("/com/foo/svc/lb99",
                               {"type": "load_balancer",
                                "load_balancer": {"address": "10.0.1.99"}})
                seen = set()
                for i in range(12):
                    r = await udp_ask(server.udp_port, "svc.foo.com",
                                      Type.A, 40 + i)
                    seen.update(a.address for a in r.answers)
                return seen
            finally:
                await server.stop()

        seen = asyncio.run(run())
        assert "10.0.1.99" in seen

    def test_session_rebuild_epoch_drops_everything(self):
        async def run():
            store, cache, server = build()
            await server.start()
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A, 1)
                await udp_ask(server.udp_port, "web.foo.com", Type.A, 2)
                epoch_before = cache.epoch
                cache.rebuild()          # session event
                assert cache.epoch == epoch_before + 1
                hits_before = server.answer_cache.hits
                r = await udp_ask(server.udp_port, "web.foo.com",
                                  Type.A, 3)
                return r, hits_before, server.answer_cache.hits
            finally:
                await server.stop()

        r, before, after = asyncio.run(run())
        assert r.answers[0].address == "192.168.0.1"
        assert after == before           # re-resolved, not served stale


class TestNativeTagInvalidation:
    def test_fastpath_invalidate_by_tag(self):
        try:
            from binder_tpu import _binderfastio as fastio
        except ImportError:
            import pytest
            pytest.skip("_binderfastio not built")
        cap = fastio.fastpath_new(64, 60000, [0.001, 0.01], [100.0])
        # key layout: [flags][payload BE16][qtype BE16][qclass BE16][qname]
        qname = b"\x03web\x03foo\x03com\x00"
        key = bytes([1, 0x04, 0xd0, 0, 1, 0, 1]) + qname
        wire = b"\x00\x00\x84\x00\x00\x01\x00\x01\x00\x00\x00\x00" \
            + qname + b"\x00\x01\x00\x01" + b"\xc0\x0c\x00\x01\x00\x01" \
            + b"\x00\x00\x00\x1e\x00\x04\x0a\x01\x02\x03"
        assert fastio.fastpath_put(cap, key, 1, 0, [wire], -1, qname)
        assert fastio.fastpath_stats(cap)["entries"] == 1
        # wrong tag: nothing dropped
        assert fastio.fastpath_invalidate(
            cap, b"\x03api\x03foo\x03com\x00") == 0
        assert fastio.fastpath_stats(cap)["entries"] == 1
        # right tag
        assert fastio.fastpath_invalidate(cap, qname) == 1
        stats = fastio.fastpath_stats(cap)
        assert stats["entries"] == 0
        # the monotonic drop counter feeds the server's
        # binder_answer_cache_invalidations gauge (absolute, not delta)
        assert stats["invalidations"] == 1


class TestDifferentialChurn:
    def test_service_and_ptr_churn_never_serves_stale(self):
        """Service-shaped churn: load_balancer children come and go under
        a service node while SRV, rotated A, and PTR queries interleave —
        every answer must reflect current children/addresses (the
        parent-tag and reverse-tag emission paths)."""
        async def run():
            store, cache, server = build()
            await server.start()
            rng = random.Random(11)
            members = {}     # child name -> address (under svc.foo.com)
            next_id = [100]
            next_addr = [0]

            def fresh_addr():
                # unique addresses: the reverse index is last-writer-wins
                # on duplicates, which is not what this test probes
                next_addr[0] += 1
                return f"10.6.{next_addr[0] >> 8}.{next_addr[0] & 255}"
            try:
                for i in range(4):   # the fixture's lb0..lb3
                    members[f"lb{i}"] = f"10.0.1.{i + 1}"
                for step in range(200):
                    op = rng.random()
                    if op < 0.2 and len(members) < 12:
                        name = f"m{next_id[0]}"
                        next_id[0] += 1
                        addr = fresh_addr()
                        store.put_json(
                            f"/com/foo/svc/{name}",
                            {"type": "load_balancer",
                             "load_balancer": {"address": addr}})
                        members[name] = addr
                    elif op < 0.35 and len(members) > 1:
                        victim = rng.choice(sorted(members))
                        removed_addr = members.pop(victim)
                        store.delete(f"/com/foo/svc/{victim}")
                        # the just-removed address must stop resolving
                        # (unbind's reverse-tag emission)
                        rev = ".".join(reversed(removed_addr.split("."))) \
                            + ".in-addr.arpa"
                        r = await udp_ask(server.udp_port, rev, Type.PTR,
                                          (step * 7 + 5) % 65536)
                        assert r.rcode == Rcode.REFUSED, \
                            f"step {step}: stale PTR for {removed_addr}"
                    elif op < 0.5 and members:
                        victim = rng.choice(sorted(members))
                        addr = fresh_addr()
                        store.put_json(
                            f"/com/foo/svc/{victim}",
                            {"type": "load_balancer",
                             "load_balancer": {"address": addr}})
                        members[victim] = addr

                    want = sorted(members.values())
                    # rotated A answers over the full member set
                    r = await udp_ask(server.udp_port, "svc.foo.com",
                                      Type.A, step * 3 % 65536)
                    got = sorted(a.address for a in r.answers)
                    assert got == want, f"step {step}: A {got} != {want}"
                    # SRV answers carry every member as a target port
                    r = await udp_ask(server.udp_port,
                                      "_pg._tcp.svc.foo.com", Type.SRV,
                                      (step * 3 + 1) % 65536)
                    assert len(r.answers) == len(members), \
                        f"step {step}: SRV {len(r.answers)}"
                    # PTR for one current member resolves; a just-removed
                    # address must not
                    if members:
                        addr = rng.choice(sorted(members.values()))
                        rev = ".".join(reversed(addr.split("."))) \
                            + ".in-addr.arpa"
                        r = await udp_ask(server.udp_port, rev,
                                          Type.PTR,
                                          (step * 3 + 2) % 65536)
                        assert r.rcode == Rcode.NOERROR, \
                            f"step {step}: PTR {addr} -> {r.rcode}"
            finally:
                await server.stop()

        asyncio.run(run())

    def test_random_churn_never_serves_stale(self):
        """Randomized soak: interleave mutations and queries; every
        answer must reflect the store state at query time (the fake
        store delivers watches synchronously, so there is no propagation
        window to excuse)."""
        async def run():
            store, cache, server = build()
            await server.start()
            rng = random.Random(7)
            state = {}
            try:
                for step in range(300):
                    name = f"h{rng.randrange(8)}"
                    if rng.random() < 0.4:
                        addr = f"10.5.{rng.randrange(256)}.{rng.randrange(1, 255)}"
                        store.put_json(
                            f"/com/foo/{name}",
                            {"type": "host", "host": {"address": addr}})
                        state[name] = addr
                    elif rng.random() < 0.15 and name in state:
                        store.delete(f"/com/foo/{name}")
                        del state[name]
                    r = await udp_ask(server.udp_port,
                                      f"{name}.foo.com", Type.A,
                                      step % 65536)
                    if name in state:
                        assert [a.address for a in r.answers] == \
                            [state[name]], f"step {step}: stale answer"
                    else:
                        assert r.rcode == Rcode.REFUSED, \
                            f"step {step}: expected REFUSED"
            finally:
                await server.stop()

        asyncio.run(run())
