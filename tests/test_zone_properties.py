"""Property-based differential test for zone precompilation.

tests/test_zone.py pins hand-picked shapes; this property covers the
space systematically: for RANDOM store trees (hosts, services with
members, database records, garbage — valid and invalid values mixed)
and RANDOM query shapes, a zone-enabled server and a zone-disabled
server must answer identically in content.  The zone's one contract is
"never different, only faster"; any eligibility rule that drifts from
the engine (TTL typing, address canonicality, suffix policy, SRV label
matching, member validity) shows up here as a differential
counterexample long before a client would find it.

Servers run over real UDP sockets (the zone only serves inside the
C drain / wire entry), so this also property-tests the native
serve path's assembly against the Python encoder's.
"""
import asyncio

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis")
from hypothesis import HealthCheck, given, settings, strategies as st  # noqa: E402

from binder_tpu.dns import Message, Type, make_query  # noqa: E402
from binder_tpu.store import FakeStore, MirrorCache  # noqa: E402

# shared server/ask helpers — this file and test_zone.py must stay in
# lockstep, so the construction pattern lives in one place (also brings
# the fastio importorskip gates)
from tests.test_zone import DOMAIN, start_server, udp_ask_raw  # noqa: E402

NAMES = ["web", "api", "db0", "x-y_z", "deep"]
MEMBERS = ["m0", "m1", "m2"]

# values chosen to straddle every eligibility boundary: canonical and
# non-canonical addresses, int and garbage TTLs, lowercase and
# uppercase SRV labels, valid and junk URLs
addresses = st.sampled_from(
    ["10.0.0.1", "10.0.0.2", "192.168.7.9", "010.0.0.1", "10.0.0.256",
     "not-an-ip", "", None])
ttls = st.sampled_from([None, 0, 30, 77, "soon", -1, 2**33])
ports = st.sampled_from([53, 5432, 0, 65535, 70000, "http", None])
srv_labels = st.sampled_from(["_pg", "_PG", "_http", "pg", "_"])

host_record = st.fixed_dictionaries({
    "type": st.sampled_from(["host", "load_balancer", "moray_host"]),
}).flatmap(lambda base: st.fixed_dictionaries({
    "address": addresses, "ttl": ttls,
}).map(lambda sub: {**base,
                    base["type"]: {k: v for k, v in sub.items()
                                   if v is not None}}))

database_record = st.sampled_from([
    {"type": "database", "database": {"primary": "tcp://10.3.3.3:1/x"}},
    {"type": "database", "ttl": 9,
     "database": {"primary": "tcp://db.example.net:1/x"}},
    {"type": "database", "database": {"primary": 45}},
    {"type": "database", "database": {}},
])

service_record = st.builds(
    lambda srvce, proto, port, ttl: {
        "type": "service",
        **({"ttl": ttl} if ttl is not None else {}),
        "service": {k: v for k, v in
                    (("srvce", srvce), ("proto", proto),
                     ("port", port)) if v is not None}},
    srv_labels, srv_labels, ports, ttls)

member_record = st.builds(
    lambda addr, ttl, ports_l: {
        "type": "load_balancer",
        "load_balancer": {
            **({"address": addr} if addr is not None else {}),
            **({"ttl": ttl} if ttl is not None else {}),
            **({"ports": ports_l} if ports_l is not None else {})}},
    addresses, ttls,
    st.sampled_from([None, [80], [80, 443], [], "x"]))

garbage_record = st.sampled_from([
    {"type": "mystery", "mystery": {}},
    {"type": 7},
    ["not", "a", "dict"],
    {},
])

tree = st.fixed_dictionaries({
    name: st.one_of(host_record, database_record, garbage_record)
    for name in NAMES
} | {
    "svc": service_record,
} | {
    f"svc/{m}": member_record for m in MEMBERS
})


def _queries():
    qs = []
    qid = 1
    for name in NAMES + ["svc", "absent"]:
        for qtype in (Type.A, Type.AAAA):
            qs.append(make_query(f"{name}.{DOMAIN}", qtype,
                                 qid=qid).encode())
            qid += 1
    for srv in ("_pg._tcp", "_PG._tcp", "_http._udp", "_x._y"):
        qs.append(make_query(f"{srv}.svc.{DOMAIN}", Type.SRV,
                             qid=qid).encode())
        qid += 1
    for ip in ("10.0.0.1", "192.168.7.9", "10.9.9.9"):
        qs.append(make_query(
            ".".join(reversed(ip.split("."))) + ".in-addr.arpa",
            Type.PTR, qid=qid).encode())
        qid += 1
    return qs


QUERIES = _queries()


def _shape(data: bytes):
    """Transport-visible content — header flags and the echoed question
    included (a flag or case-echo divergence is client-visible too) —
    order-insensitive only where the engine legitimately shuffles
    (multi-answer sets rotate/shuffle differently per server)."""
    try:
        m = Message.decode(data)
    except Exception:  # noqa: BLE001 — compare raw on undecodable
        return ("raw", data)

    def rec(r):
        return tuple(sorted(
            (k, repr(v)) for k, v in vars(r).items()))
    return (m.rcode, m.tc, m.aa, m.ra, m.rd, m.qr, m.opcode,
            tuple(rec(q) for q in m.questions),
            tuple(sorted(rec(a) for a in m.answers)),
            tuple(sorted(rec(a) for a in m.additionals)),
            tuple(sorted(rec(a) for a in m.authorities)))


# derandomize + no example database: the run is a pure function of the
# code under test — a CI box must never inherit replay state from a
# developer's (possibly deliberately-broken) local exploration, and a
# failure here must reproduce exactly on the next run
@settings(max_examples=60, deadline=None, derandomize=True,
          database=None,
          suppress_health_check=[HealthCheck.too_slow])
@given(spec=tree)
def test_zone_differential_over_random_trees(spec):
    async def run():
        def build():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            for rel, record in spec.items():
                store.put_json(f"/com/foo/{rel}", record)
            store.start_session()
            return cache

        servers = []
        try:
            for zone in (True, False):
                servers.append(await start_server(
                    build(), zone_precompile=zone))
            on, off = servers
            for wire in QUERIES:
                got = _shape(await udp_ask_raw(on.udp_port, wire))
                want = _shape(await udp_ask_raw(off.udp_port, wire))
                assert got == want, (wire, got, want)
        finally:
            for s in servers:
                await s.stop()

    asyncio.run(run())
