#!/usr/bin/env -S python3 -S -E
"""A fake ``systemctl`` for exercising instance_adjust's systemd backend.

Installed on PATH as ``systemctl`` by tests/test_instance_adjust_systemd.py.
Keeps unit state in $FAKE_SYSTEMD_STATE:

    log           one line per invocation (for command-protocol asserts)
    units/<unit>  two lines: ``state=<active|inactive|failed>``,
                  ``enabled=<0|1>``

Behavioral model (the slice instance_adjust relies on):
  - ``list-units`` shows loaded units — here: anything active or failed
    (inactive disabled template instances are garbage-collected by real
    systemd, so they vanish from listings the same way);
  - ``list-unit-files`` shows enabled instances;
  - ``start`` creates $FAKE_SOCKDIR/<port> when that env var is set (the
    binder instance's balancer socket), ``stop`` removes it — so ``-w``
    online-wait sees the real readiness signal;
  - a ``fail-start`` marker file makes the next ``start`` land the unit in
    ``failed`` (crash-on-startup simulation).
"""
import os
import shlex
import sys


STATE = os.environ["FAKE_SYSTEMD_STATE"]
UNITS = os.path.join(STATE, "units")


def log(argv):
    with open(os.path.join(STATE, "log"), "a") as f:
        f.write(shlex.join(argv) + "\n")


def unit_file(unit):
    return os.path.join(UNITS, unit)


def read_unit(unit):
    try:
        with open(unit_file(unit)) as f:
            d = dict(line.strip().split("=", 1) for line in f if "=" in line)
    except FileNotFoundError:
        return {"state": "inactive", "enabled": "0", "known": False}
    d.setdefault("state", "inactive")
    d.setdefault("enabled", "0")
    d["known"] = True
    return d


def write_unit(unit, d):
    os.makedirs(UNITS, exist_ok=True)
    with open(unit_file(unit), "w") as f:
        f.write(f"state={d['state']}\nenabled={d['enabled']}\n")


def unit_port(unit):
    # binder@5301.service -> 5301
    if "@" not in unit:
        return None
    tail = unit.split("@", 1)[1]
    tail = tail[:-len(".service")] if tail.endswith(".service") else tail
    return tail if tail.isdigit() else None


def touch_socket(unit, create):
    sockdir = os.environ.get("FAKE_SOCKDIR")
    port = unit_port(unit)
    if not sockdir or port is None:
        return
    path = os.path.join(sockdir, port)
    if create:
        os.makedirs(sockdir, exist_ok=True)
        with open(path, "w"):
            pass
    else:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass


def do_start(unit):
    d = read_unit(unit)
    if os.path.exists(os.path.join(STATE, "fail-start")):
        d["state"] = "failed"
        write_unit(unit, d)
        touch_socket(unit, create=False)
        return 1
    d["state"] = "active"
    write_unit(unit, d)
    touch_socket(unit, create=True)
    return 0


def gc_unit(unit):
    """Real systemd unloads (forgets) template instances that are
    inactive, disabled, and have no drop-in config."""
    d = read_unit(unit)
    if d["known"] and d["state"] == "inactive" and d["enabled"] == "0":
        os.unlink(unit_file(unit))


def do_stop(unit):
    d = read_unit(unit)
    if d["state"] == "active":
        d["state"] = "inactive"
        write_unit(unit, d)
    touch_socket(unit, create=False)
    gc_unit(unit)
    return 0


def match(unit, pattern):
    import fnmatch
    return fnmatch.fnmatch(unit, pattern)


def main(argv):
    log(argv)
    cmd, rest = argv[0], argv[1:]
    flags = [a for a in rest if a.startswith("-")]
    args = [a for a in rest if not a.startswith("-")]

    if cmd == "daemon-reload":
        return 0

    if cmd in ("list-units", "list-unit-files"):
        pattern = args[0] if args else "*"
        rows = []
        if os.path.isdir(UNITS):
            for unit in sorted(os.listdir(UNITS)):
                if not match(unit, pattern):
                    continue
                d = read_unit(unit)
                if cmd == "list-units" and d["state"] in ("active", "failed"):
                    sub = "running" if d["state"] == "active" else "failed"
                    rows.append(f"{unit} loaded {d['state']} {sub}")
                elif cmd == "list-unit-files" and d["enabled"] == "1":
                    rows.append(f"{unit} enabled")
        print("\n".join(rows))
        return 0

    if cmd == "show":
        # show -p ActiveState --value <unit> — "-p ActiveState" puts the
        # property name in args, so the unit is the final argument
        print(read_unit(args[-1])["state"])
        return 0

    if cmd == "is-active":
        d = read_unit(args[0])
        if "--quiet" not in flags:
            print(d["state"])
        return 0 if d["state"] == "active" else 3

    if cmd == "is-failed":
        d = read_unit(args[0])
        if "--quiet" not in flags:
            print(d["state"])
        return 0 if d["state"] == "failed" else 1

    if cmd == "enable":
        for unit in args:
            d = read_unit(unit)
            d["enabled"] = "1"
            write_unit(unit, d)
            if "--now" in flags:
                do_start(unit)
        return 0

    if cmd == "disable":
        rc = 0
        for unit in args:
            d = read_unit(unit)
            d["enabled"] = "0"
            write_unit(unit, d)
            if "--now" in flags:
                rc |= do_stop(unit)
            else:
                gc_unit(unit)
        return rc

    if cmd == "start":
        rc = 0
        for unit in args:
            rc |= do_start(unit)
        return rc

    if cmd == "stop":
        rc = 0
        for unit in args:
            rc |= do_stop(unit)
        return rc

    if cmd == "restart":
        rc = 0
        for unit in args:
            do_stop(unit)
            rc |= do_start(unit)
        return rc

    if cmd == "try-restart":
        rc = 0
        for unit in args:
            if read_unit(unit)["state"] == "active":
                do_stop(unit)
                rc |= do_start(unit)
        return rc

    if cmd == "reset-failed":
        for unit in args:
            d = read_unit(unit)
            if d["state"] == "failed":
                d["state"] = "inactive"
                write_unit(unit, d)
            gc_unit(unit)
        return 0

    print(f"fake systemctl: unknown command {cmd}", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
