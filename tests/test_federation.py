"""Multi-DC federation tests (ISSUE 11): the watched ``/dcs`` registry,
cross-DC forwarding through the registry-fed routing table, the
foreign-answer cache's stale-serve/withhold policy for dark DCs, the
per-query upstream budget, and the ``binder_federation_*`` metric pins.

The wire-outcome matrix this suite pins (docs/federation.md):

    foreign name, owning DC live, name unknown    -> REFUSED
    foreign name, owning DC dark, cached answer   -> NOERROR, TTL clamped
    foreign name, owning DC dark, past cap        -> SERVFAIL (withheld)
    foreign name, owning DC dark, nothing cached  -> REFUSED
    local name                                    -> unaffected by any of it

A dark DC is a transport-level fact (timeout, socket death): a live
peer answering NXDOMAIN/REFUSED stays an ordinary negative answer.
"""
import asyncio

from binder_tpu.dns import Message, Rcode, Type
from binder_tpu.federation import DcRegistry, Federation
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.recursion import DnsClient, Recursion
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

from tests.test_recursion import (
    make_remote_fixture,
    start_remote,
    udp_ask,
    udp_ask_wire,
)
from tools.lint import validate_federation_metrics

DOMAIN = "foo.com"


async def start_federated(remotes, fed_cfg=None, server_kw=None, **rkw):
    """Local binder whose routing table comes from the watched ``/dcs``
    subtree of its own store.  ``remotes`` maps dc name -> peer list;
    each becomes a ``/dcs/<dc>`` record before the session starts."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    # the local DC's own names, served straight from the mirror
    store.put_json("/com/foo/local", {"type": "service",
                                      "service": {"port": 53}})
    store.put_json("/com/foo/local/web",
                   {"type": "host", "host": {"address": "10.1.0.1",
                                             "ttl": 30}})
    for dc, peers in remotes.items():
        store.put_json(f"/dcs/{dc}", {"zones": [dc], "peers": peers})
    store.start_session()
    collector = MetricsCollector()
    federation = Federation(store=store, dns_domain=DOMAIN,
                            datacenter_name="local",
                            config=fed_cfg, collector=collector)
    federation.start()
    recursion = Recursion(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="local",
        source=federation.resolver_source(),
        nic_provider=lambda: [],  # tests use 127.0.0.1 resolvers
        collector=collector, **rkw)
    federation.attach(recursion)
    await recursion.wait_ready()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="local", recursion=recursion,
                          host="127.0.0.1", port=0, collector=collector,
                          **(server_kw or {}))
    server.federation = federation
    await server.start()
    return server, recursion, federation


def fast_client():
    """Short-timeout client so dark-DC tests pay ~0.3s, not 3s."""
    return DnsClient(concurrency=2, timeout=0.3)


class TestDcRegistry:
    def test_join_leave_and_data_change(self):
        store = FakeStore()
        store.put_json("/dcs/east", {"zones": ["east"],
                                     "peers": ["10.0.0.1:53"]})
        store.start_session()
        reg = DcRegistry(store, self_name="local")
        reg.start()
        assert set(reg.records) == {"east"}
        assert reg.foreign_zone_map() == {"east": ["10.0.0.1:53"]}
        assert reg.joins == 1

        # a DC joining is just a mutation under /dcs
        store.put_json("/dcs/west", {"zones": ["west", "w2"],
                                     "peers": ["10.0.0.2:53"]})
        assert reg.zone_owner("w2") == "west"
        assert reg.joins == 2

        # a peer-set change propagates through the data watcher
        store.put_json("/dcs/east", {"zones": ["east"],
                                     "peers": ["10.0.0.9:53"]})
        assert reg.foreign_zone_map()["east"] == ["10.0.0.9:53"]
        assert reg.joins == 2  # an update, not a re-join

        # a DC leaving is a child deletion
        store.delete("/dcs/west")
        assert set(reg.records) == {"east"}
        assert reg.leaves == 1

    def test_self_excluded_from_routing(self):
        store = FakeStore()
        store.put_json("/dcs/local", {"zones": ["local"],
                                      "peers": ["10.0.0.1:53"]})
        store.put_json("/dcs/east", {"zones": ["east"],
                                     "peers": ["10.0.0.2:53"]})
        store.start_session()
        reg = DcRegistry(store, self_name="local")
        reg.start()
        assert set(reg.records) == {"local", "east"}
        assert reg.foreign_zone_map() == {"east": ["10.0.0.2:53"]}
        assert reg.zone_owner("local") is None

    def test_malformed_record_drops_dc(self):
        store = FakeStore()
        store.put_json("/dcs/east", {"zones": ["east"],
                                     "peers": ["10.0.0.1:53"]})
        store.start_session()
        reg = DcRegistry(store, self_name="local")
        reg.start()
        assert "east" in reg.records
        # garbage record: routing on stale peers would be worse than
        # not knowing the DC at all
        store.set_data("/dcs/east", b"not json")
        assert "east" not in reg.records

    def test_static_bootstrap(self):
        # shard ReplicaStore workers: the mutation log doesn't carry
        # /dcs, so the supervisor-passed config seeds the map
        store = FakeStore()
        store.start_session()
        reg = DcRegistry(store, self_name="local", static_records=[
            {"name": "east", "zones": ["east"], "peers": ["10.0.0.1:53"]},
        ])
        reg.start()
        assert reg.foreign_zone_map() == {"east": ["10.0.0.1:53"]}

    def test_dcs_created_after_start(self):
        # mkdirp fires the parent's children watcher at each created
        # level, so a /dcs subtree born after start() still lands
        store = FakeStore()
        store.start_session()
        reg = DcRegistry(store, self_name="local")
        reg.start()
        assert reg.records == {}
        store.put_json("/dcs/east", {"zones": ["east"],
                                     "peers": ["10.0.0.1:53"]})
        assert "east" in reg.records


class TestFederatedForwarding:
    def test_foreign_name_resolves_through_registry(self):
        async def run():
            remote = await start_remote("east", "10.77.0.1")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            try:
                r = await udp_ask(server.udp_port, "web.east.foo.com",
                                  Type.A)
                local = await udp_ask(server.udp_port,
                                      "web.local.foo.com", Type.A)
                return r, local, federation.forwards
            finally:
                await server.stop()
                await recursion.close()
                await remote.stop()

        r, local, forwards = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "10.77.0.1"
        assert r.answers[0].ttl == 44
        assert local.rcode == Rcode.NOERROR
        assert local.answers[0].address == "10.1.0.1"
        assert forwards >= 1

    def test_cross_dc_parity_with_direct_modulo_id(self):
        """The federated binder's forwarded answer must be byte-equal
        with the owning DC's direct render, modulo the id bytes and the
        RA bit (the forwarding binder IS a recursive service; the
        owning DC is not)."""
        async def run():
            remote = await start_remote("east", "10.77.0.5")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            try:
                direct = await udp_ask_wire(remote.udp_port,
                                            "web.east.foo.com", Type.A)
                fwd = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
            finally:
                await server.stop()
                await recursion.close()
                await remote.stop()
            return direct, fwd

        direct, fwd = asyncio.run(run())
        a, b = bytearray(direct), bytearray(fwd)
        assert b[3] & 0x80, "forwarded answer must set RA"
        a[3] |= 0x80  # mask the RA difference
        assert a[2:] == b[2:], "cross-DC answer diverged from direct"

    def test_membership_change_updates_routing(self):
        async def run():
            r1 = await start_remote("east", "10.77.0.1")
            r2 = await start_remote("west", "10.88.0.1")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{r1.udp_port}"]})
            try:
                miss = await udp_ask(server.udp_port, "web.west.foo.com",
                                     Type.A)
                # west joins: one mutation under /dcs, no restart
                federation.registry.store.put_json(
                    "/dcs/west", {"zones": ["west"],
                                  "peers": [f"127.0.0.1:{r2.udp_port}"]})
                for _ in range(20):
                    if "west" in recursion.dcs:
                        break
                    await asyncio.sleep(0.02)
                hit = await udp_ask(server.udp_port, "web.west.foo.com",
                                    Type.A)
                return miss, hit
            finally:
                await server.stop()
                await recursion.close()
                await r1.stop()
                await r2.stop()

        miss, hit = asyncio.run(run())
        assert miss.rcode == Rcode.REFUSED
        assert hit.rcode == Rcode.NOERROR
        assert hit.answers[0].address == "10.88.0.1"


class TestShedNotCached:
    def test_rate_limit_refused_never_enters_answer_cache(self):
        """An admission shed is a PER-CLIENT transient: the synchronous
        REFUSED it produces must never be deposited in the shared
        answer cache, or one client's flood poisons the name with
        REFUSED for every other client until expiry (regression: found
        by the cross_dc bench axis, where the load generator's own
        sheds made foreign names unresolvable after the flood ended)."""
        async def run():
            remote = await start_remote("east", "10.77.0.9")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                server_kw={"admission": {"recursionRate": 0.001,
                                         "recursionBurst": 2.0}})
            try:
                shed = None
                for _ in range(8):
                    r = await udp_ask(server.udp_port,
                                      "web.east.foo.com", Type.A)
                    if r.rcode == Rcode.REFUSED:
                        shed = r
                        break
                # an evicted (or simply different) client starts with a
                # full bucket — clearing the table models "another
                # client asks the same name after the flood"
                server._admission._buckets.clear()
                after = await udp_ask(server.udp_port,
                                      "web.east.foo.com", Type.A)
                return shed, after
            finally:
                await server.stop()
                await recursion.close()
                await remote.stop()

        shed, after = asyncio.run(run())
        assert shed is not None, "flood never tripped the rate limit"
        assert after.rcode == Rcode.NOERROR, \
            "shed REFUSED leaked into the shared answer cache"
        assert after.answers[0].address == "10.77.0.9"


class TestDarkDcPolicy:
    def test_stale_served_with_clamped_ttl(self):
        async def run():
            remote = await start_remote("east", "10.77.0.2")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                fed_cfg={"staleTtlClampSeconds": 5},
                client=fast_client())
            try:
                warm = await udp_ask(server.udp_port, "web.east.foo.com",
                                     Type.A)
                await remote.stop()  # the whole DC goes dark
                stale = await udp_ask(server.udp_port,
                                      "web.east.foo.com", Type.A)
                local = await udp_ask(server.udp_port,
                                      "web.local.foo.com", Type.A)
                return warm, stale, local, federation
            finally:
                await server.stop()
                await recursion.close()

        warm, stale, local, federation = asyncio.run(run())
        assert warm.rcode == Rcode.NOERROR and warm.answers[0].ttl == 44
        assert stale.rcode == Rcode.NOERROR
        assert stale.answers[0].address == "10.77.0.2"
        assert stale.answers[0].ttl == 5, "stale answer must clamp TTL"
        # local serving is untouched by a foreign DC's darkness
        assert local.rcode == Rcode.NOERROR and local.answers[0].ttl == 30
        assert federation.dark_dcs() == ["east"]
        assert federation.last_convergence_s is not None

    def test_withheld_past_staleness_cap(self):
        async def run():
            remote = await start_remote("east", "10.77.0.3")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                fed_cfg={"maxStalenessSeconds": 0.0},
                client=fast_client())
            try:
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                await remote.stop()
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
                return raw
            finally:
                await server.stop()
                await recursion.close()

        raw = asyncio.run(run())
        # withheld: a well-formed SERVFAIL, never a timeout
        assert raw[3] & 0x0F == Rcode.SERVFAIL

    def test_withheld_refused_action(self):
        async def run():
            remote = await start_remote("east", "10.77.0.4")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                fed_cfg={"maxStalenessSeconds": 0.0,
                         "exhaustedAction": "refused"},
                client=fast_client())
            try:
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                await remote.stop()
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
                return raw
            finally:
                await server.stop()
                await recursion.close()

        assert asyncio.run(run())[3] & 0x0F == Rcode.REFUSED

    def test_dark_with_nothing_cached_refused(self):
        async def run():
            server, recursion, federation = await start_federated(
                {"east": ["127.0.0.1:9"]},  # discard port: dark from birth
                client=fast_client())
            try:
                raw = await udp_ask_wire(server.udp_port,
                                         "web.east.foo.com", Type.A)
                return raw, federation
            finally:
                await server.stop()
                await recursion.close()

        raw, federation = asyncio.run(run())
        assert raw[3] & 0x0F == Rcode.REFUSED
        assert federation.dark_dcs() == ["east"]

    def test_live_negative_is_not_dark(self):
        """A peer answering REFUSED is alive: no dark transition, no
        stale-serve — foreign NXDOMAIN-ish outcomes stay negative."""
        async def run():
            remote = await start_remote("east", "10.77.0.1")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]})
            try:
                r = await udp_ask(server.udp_port, "nope.east.foo.com",
                                  Type.A)
                return r, federation
            finally:
                await server.stop()
                await recursion.close()
                await remote.stop()

        r, federation = asyncio.run(run())
        assert r.rcode == Rcode.REFUSED
        assert federation.dark_dcs() == []

    def test_recovery_after_dark(self):
        async def run():
            remote = await start_remote("east", "10.77.0.6")
            port = remote.udp_port
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{port}"]},
                client=fast_client())
            try:
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                await remote.stop()
                stale = await udp_ask(server.udp_port,
                                      "web.east.foo.com", Type.A)
                assert stale.answers, "expected a stale-served answer"
                assert federation.dark_dcs() == ["east"]
                # the DC comes back on the same address
                remote2 = BinderServer(
                    zk_cache=make_remote_fixture("east", "10.77.0.6"),
                    dns_domain=DOMAIN, datacenter_name="east",
                    host="127.0.0.1", port=port,
                    collector=MetricsCollector())
                await remote2.start()
                try:
                    # breakers half-open after backoff; poll until the
                    # forward path proves the peer alive again
                    for _ in range(80):
                        await udp_ask(server.udp_port, "web.east.foo.com",
                                      Type.A, timeout=5.0)
                        if not federation.dark_dcs():
                            break
                        await asyncio.sleep(0.1)
                    return federation.dark_dcs()
                finally:
                    await remote2.stop()
            finally:
                await server.stop()
                await recursion.close()

        assert asyncio.run(run()) == []


class TestUpstreamBudget:
    def test_ptr_fanout_clamped(self):
        async def run():
            r1 = await start_remote("east", "10.77.0.1")
            r2 = await start_remote("west", "10.88.0.1")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{r1.udp_port}"],
                 "west": [f"127.0.0.1:{r2.udp_port}"]},
                fed_cfg={"upstreamBudget": 1})
            try:
                assert recursion.upstream_budget == 1
                await udp_ask(server.udp_port, "1.0.88.10.in-addr.arpa",
                              Type.PTR)
                clamps = server.collector.get(
                    "binder_federation_budget_clamped_total").total()
                return clamps
            finally:
                await server.stop()
                await recursion.close()
                await r1.stop()
                await r2.stop()

        # the 2-upstream PTR fan-out was clamped to 1
        assert asyncio.run(run()) >= 1

    def test_unbounded_by_default_outside_federation(self):
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        store.start_session()
        rec = Recursion(zk_cache=cache, dns_domain=DOMAIN,
                        datacenter_name="local")
        assert rec.upstream_budget is None


def _echo_question(data: bytes) -> bytes:
    """Empty NOERROR response echoing the query's question verbatim
    (dns0x20: the client validates the exact case mask it sent)."""
    q = Message.decode(data)
    resp = bytearray(Message(id=q.id, qr=True,
                             questions=list(q.questions)).encode())
    off = 12
    while data[off] != 0:
        off += 1 + data[off]
    qlen = off + 5 - 12
    resp[12:12 + qlen] = data[12:12 + qlen]
    return bytes(resp)


class TestSingleFlight:
    def test_identical_lookups_coalesced(self):
        async def run():
            loop = asyncio.get_running_loop()

            class SlowUpstream(asyncio.DatagramProtocol):
                hits = 0

                def connection_made(self, transport):
                    self.transport = transport

                def datagram_received(self, data, addr):
                    type(self).hits += 1

                    def reply():
                        self.transport.sendto(
                            _echo_question(data), addr)

                    loop.call_later(0.15, reply)

            tr, _ = await loop.create_datagram_endpoint(
                SlowUpstream, local_addr=("127.0.0.1", 0))
            port = tr.get_extra_info("sockname")[1]
            client = DnsClient(concurrency=2, timeout=2.0)
            try:
                outs = await asyncio.gather(*[
                    client.lookup_raw("x.foo.com", Type.A,
                                      [f"127.0.0.1:{port}"])
                    for _ in range(5)])
            finally:
                client.close()
                tr.close()
            return outs, client.coalesced, SlowUpstream.hits

        outs, coalesced, hits = asyncio.run(run())
        assert len(outs) == 5 and all(o == outs[0] for o in outs)
        assert coalesced == 4, "4 of 5 identical lookups must coalesce"
        assert hits == 1, "one upstream exchange for 5 callers"

    def test_different_names_not_coalesced(self):
        async def run():
            loop = asyncio.get_running_loop()

            class Upstream(asyncio.DatagramProtocol):
                def connection_made(self, transport):
                    self.transport = transport

                def datagram_received(self, data, addr):
                    self.transport.sendto(_echo_question(data), addr)

            tr, _ = await loop.create_datagram_endpoint(
                Upstream, local_addr=("127.0.0.1", 0))
            port = tr.get_extra_info("sockname")[1]
            client = DnsClient(concurrency=2, timeout=2.0)
            try:
                await asyncio.gather(*[
                    client.lookup_raw(f"x{i}.foo.com", Type.A,
                                      [f"127.0.0.1:{port}"])
                    for i in range(3)])
            finally:
                client.close()
                tr.close()
            return client.coalesced

        assert asyncio.run(run()) == 0


class TestFederationObservability:
    def test_metrics_validate_and_status_section(self):
        async def run():
            remote = await start_remote("east", "10.77.0.7")
            server, recursion, federation = await start_federated(
                {"east": [f"127.0.0.1:{remote.udp_port}"]},
                client=fast_client())
            try:
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                await remote.stop()
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                text = server.collector.expose()

                from binder_tpu.introspect import Introspector
                snap = Introspector(server=server).snapshot()
                return text, snap
            finally:
                await server.stop()
                await recursion.close()

        text, snap = asyncio.run(run())
        assert validate_federation_metrics(text) == [], \
            validate_federation_metrics(text)
        # a forward to east was dispatched and counted per-DC
        assert 'binder_federation_forwards_total{dc="east"}' in text

        fed = snap["federation"]
        assert fed is not None
        assert fed["datacenter"] == "local"
        assert "east" in fed["registry"]["dcs"]
        assert fed["dark"] == ["east"]
        assert fed["forwards"] >= 2
        assert fed["foreign_cache"]["entries"] >= 1
        assert fed["last_convergence_seconds"] is not None

    def test_flight_events_on_membership_and_failover(self):
        async def run():
            from binder_tpu.introspect import FlightRecorder
            recorder = FlightRecorder()
            remote = await start_remote("east", "10.77.0.8")
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/dcs/east",
                           {"zones": ["east"],
                            "peers": [f"127.0.0.1:{remote.udp_port}"]})
            store.start_session()
            federation = Federation(store=store, dns_domain=DOMAIN,
                                    datacenter_name="local",
                                    recorder=recorder)
            federation.start()
            recursion = Recursion(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="local",
                source=federation.resolver_source(),
                nic_provider=lambda: [], client=fast_client())
            federation.attach(recursion)
            await recursion.wait_ready()
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="local",
                                  recursion=recursion, host="127.0.0.1",
                                  port=0, collector=MetricsCollector())
            server.federation = federation
            await server.start()
            try:
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                await remote.stop()
                await udp_ask(server.udp_port, "web.east.foo.com", Type.A)
                store.delete("/dcs/east")
                return [e["type"] for e in recorder.events()]
            finally:
                await server.stop()
                await recursion.close()

        kinds = asyncio.run(run())
        for expected in ("dc-join", "dc-dark", "federation-failover",
                         "dc-leave"):
            assert expected in kinds, f"missing flight event {expected}"
