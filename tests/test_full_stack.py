"""Whole-system integration: ZooKeeper wire protocol end to end.

One test rig wiring every layer at once — the in-process ZK server
(jute protocol), two binder backends whose mirrors watch it, the native
C++ balancer fronting them over the balancer-socket protocol, and a UDP
client — then exercising the full invalidation chain: a ZK write flows
through watch delivery → mirror update → generation bump → control
frame → balancer cache clear, and the next query serves the new data.

This is the deployment shape the reference only ever exercises in
production (SURVEY §4: recursion, balancer, reconciler have zero
automated tests there).
"""
import asyncio
import json
import os

import pytest

from binder_tpu.dns import Rcode, Type
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import MirrorCache
from binder_tpu.store.zk_client import ZKClient
from binder_tpu.store.zk_testserver import ZKTestServer

from tests.test_balancer import BALANCER, read_stats, start_balancer, udp_ask

DOMAIN = "foo.com"

pytestmark = pytest.mark.skipif(
    not os.path.exists(BALANCER),
    reason="mbalancer not built (make -C native)")


async def wait_for(predicate, timeout=5.0, interval=0.02):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return False


async def put_json(zk: ZKClient, path: str, obj) -> None:
    data = json.dumps(obj).encode()
    if await zk.exists(path):
        await zk.set_data(path, data)
    else:
        await zk.mkdirp(path, data)


def test_zk_to_balancer_full_chain(tmp_path):
    sockdir = str(tmp_path)

    async def run():
        zkserver = ZKTestServer()
        await zkserver.start()

        writer = ZKClient(address="127.0.0.1", port=zkserver.port)
        writer.start()
        assert await wait_for(writer.is_connected)
        await put_json(writer, "/com/foo/web",
                       {"type": "host", "host": {"address": "10.5.0.1"}})

        backends = []
        for i in range(2):
            client = ZKClient(address="127.0.0.1", port=zkserver.port,
                              session_timeout_ms=2000)
            cache = MirrorCache(client, DOMAIN)
            client.start()
            server = BinderServer(
                zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
                host="127.0.0.1", port=0,
                balancer_socket=os.path.join(sockdir, str(i)),
                collector=MetricsCollector())
            await server.start()
            backends.append((client, cache, server))
        assert await wait_for(lambda: all(
            c.lookup("web.foo.com") is not None for _, c, _s in backends))

        proc, port = await start_balancer(sockdir, direct=False)
        try:
            await asyncio.sleep(0.4)

            # resolve + repeat: the repeat is served by the balancer
            # cache, filled from a backend whose data came over the real
            # ZK wire protocol
            for qid in (1, 2, 3):
                m = await udp_ask(port, "web.foo.com", Type.A, qid=qid)
                assert m.rcode == Rcode.NOERROR
                assert m.answers[0].address == "10.5.0.1"
            stats = read_stats(sockdir)
            assert stats["cache_hits"] >= 1
            assert all(be["gen_known"] for be in stats["backends"]
                       if be["healthy"])

            # ZK write -> watch -> mirror -> gen bump -> control frame
            # -> balancer cache clear -> fresh answer
            await writer.set_data("/com/foo/web", json.dumps(
                {"type": "host",
                 "host": {"address": "10.5.0.99"}}).encode())
            assert await wait_for(lambda: all(
                c.lookup("web.foo.com").data["host"]["address"]
                == "10.5.0.99" for _, c, _s in backends))
            await asyncio.sleep(0.1)   # control-frame delivery
            m = await udp_ask(port, "web.foo.com", Type.A, qid=50)
            assert m.answers[0].address == "10.5.0.99"
            # and the fresh answer is cacheable again
            m = await udp_ask(port, "web.foo.com", Type.A, qid=51)
            assert m.answers[0].address == "10.5.0.99"

            # node added over ZK becomes resolvable through the balancer
            await put_json(writer, "/com/foo/late",
                           {"type": "host",
                            "host": {"address": "10.5.7.7"}})
            assert await wait_for(lambda: all(
                c.lookup("late.foo.com") is not None
                and c.lookup("late.foo.com").data is not None
                for _, c, _s in backends))
            m = await udp_ask(port, "late.foo.com", Type.A, qid=60)
            assert m.answers[0].address == "10.5.7.7"

            # ZK session expiry on one backend: it rebuilds and keeps
            # serving; the balancer keeps answering throughout
            zkserver.expire_session()
            await asyncio.sleep(0.3)
            for qid in range(70, 76):
                m = await udp_ask(port, "web.foo.com", Type.A, qid=qid)
                assert m.answers[0].address == "10.5.0.99"
        finally:
            proc.kill()
            await proc.wait()
            for client, _c, server in backends:
                await server.stop()
                client.close()
            writer.close()
            await zkserver.stop()

    asyncio.run(run())


def test_balancer_invalidation_is_per_name(tmp_path):
    """Ordinary churn must drop only the affected balancer entries
    (opcode-1 per-name invalidate frames): after mutating one name over
    the real ZK protocol, the other name keeps serving from the
    balancer cache, and the stats socket reports the selective drop."""
    sockdir = str(tmp_path)

    async def run():
        zkserver = ZKTestServer()
        await zkserver.start()
        writer = ZKClient(address="127.0.0.1", port=zkserver.port)
        writer.start()
        assert await wait_for(writer.is_connected)
        await put_json(writer, "/com/foo/web",
                       {"type": "host", "host": {"address": "10.5.0.1"}})
        await put_json(writer, "/com/foo/api",
                       {"type": "host", "host": {"address": "10.5.0.2"}})

        client = ZKClient(address="127.0.0.1", port=zkserver.port,
                          session_timeout_ms=2000)
        cache = MirrorCache(client, DOMAIN)
        client.start()
        server = BinderServer(
            zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
            host="127.0.0.1", port=0,
            balancer_socket=os.path.join(sockdir, "0"),
            collector=MetricsCollector())
        await server.start()
        assert await wait_for(
            lambda: cache.lookup("api.foo.com") is not None
            and cache.lookup("api.foo.com").data is not None)

        proc, port = await start_balancer(sockdir, direct=False)
        try:
            await asyncio.sleep(0.4)
            # fill the balancer cache for both names
            for qid, name in ((1, "web.foo.com"), (2, "api.foo.com"),
                              (3, "web.foo.com"), (4, "api.foo.com")):
                m = await udp_ask(port, name, Type.A, qid=qid)
                assert m.rcode == Rcode.NOERROR
            hits0 = read_stats(sockdir)["cache_hits"]
            assert hits0 >= 2

            # mutate web only
            await writer.set_data("/com/foo/web", json.dumps(
                {"type": "host",
                 "host": {"address": "10.5.0.88"}}).encode())
            assert await wait_for(
                lambda: cache.lookup("web.foo.com").data["host"]["address"]
                == "10.5.0.88")
            # control-frame delivery: poll the stats socket, no sleeps
            assert await wait_for(
                lambda: read_stats(sockdir)["cache_invalidations"] >= 1)
            # api survived the churn: next ask is another balancer hit
            m = await udp_ask(port, "api.foo.com", Type.A, qid=10)
            assert m.answers[0].address == "10.5.0.2"
            assert read_stats(sockdir)["cache_hits"] > hits0
            # web re-resolves fresh
            m = await udp_ask(port, "web.foo.com", Type.A, qid=11)
            assert m.answers[0].address == "10.5.0.88"
        finally:
            proc.kill()
            await proc.wait()
            await server.stop()
            client.close()
            writer.close()
            await zkserver.stop()

    asyncio.run(run())


def test_recursion_through_balancer_not_cached(tmp_path):
    """Cross-DC recursion behind the balancer: answers forwarded from a
    remote binder are served but carry the do-not-store marker, so the
    balancer never caches another DC's data — a remote mutation is
    visible on the very next query."""
    from binder_tpu.recursion import Recursion, StaticResolverSource
    from binder_tpu.store import FakeStore

    sockdir = str(tmp_path)

    async def run():
        # remote DC binder (direct UDP, its own store)
        rstore = FakeStore()
        rcache = MirrorCache(rstore, DOMAIN)
        rstore.put_json("/com/foo/east", {"type": "service",
                                          "service": {"port": 53}})
        rstore.put_json("/com/foo/east/web",
                        {"type": "host",
                         "host": {"address": "10.66.0.1"}})
        rstore.start_session()
        remote = BinderServer(zk_cache=rcache, dns_domain=DOMAIN,
                              datacenter_name="east", host="127.0.0.1",
                              port=0, collector=MetricsCollector())
        await remote.start()

        # local backend with recursion to the remote, behind the balancer
        lstore = FakeStore()
        lcache = MirrorCache(lstore, DOMAIN)
        lstore.put_json("/com/foo/web",
                        {"type": "host", "host": {"address": "10.1.0.1"}})
        lstore.start_session()
        recursion = Recursion(
            zk_cache=lcache, dns_domain=DOMAIN, datacenter_name="local",
            source=StaticResolverSource(
                {"east": [f"127.0.0.1:{remote.udp_port}"]}),
            nic_provider=lambda: [])
        await recursion.wait_ready()
        local = BinderServer(zk_cache=lcache, dns_domain=DOMAIN,
                             datacenter_name="local", recursion=recursion,
                             host="127.0.0.1", port=0,
                             balancer_socket=os.path.join(sockdir, "0"),
                             collector=MetricsCollector())
        await local.start()

        proc, port = await start_balancer(sockdir, direct=False)
        try:
            await asyncio.sleep(0.4)
            # local name: cacheable as usual
            for qid in (1, 2):
                m = await udp_ask(port, "web.foo.com", Type.A, qid=qid)
                assert m.answers[0].address == "10.1.0.1"
            hits_after_local = read_stats(sockdir)["cache_hits"]
            assert hits_after_local == 1

            # remote-DC name with RD: forwarded every time, never cached
            for qid in (10, 11, 12):
                m = await udp_ask(port, "web.east.foo.com", Type.A,
                                  qid=qid, rd=True)
                assert m.rcode == Rcode.NOERROR
                assert m.answers[0].address == "10.66.0.1"
            stats = read_stats(sockdir)
            assert stats["cache_hits"] == hits_after_local  # no new hits

            # remote mutation is visible immediately (nothing cached the
            # old answer anywhere on the local side)
            rstore.put_json("/com/foo/east/web",
                            {"type": "host",
                             "host": {"address": "10.66.0.99"}})
            m = await udp_ask(port, "web.east.foo.com", Type.A,
                              qid=20, rd=True)
            assert m.answers[0].address == "10.66.0.99"
        finally:
            proc.kill()
            await proc.wait()
            await local.stop()
            await recursion.close()
            await remote.stop()

    asyncio.run(run())
