"""Serving-plane verification + propagation tracing (ISSUE 16).

What this pins down:

- the incremental checker catches each scripted corruption through the
  invariant that owns it: a dropped reverse entry (ptr-coherence), a
  missing service member (dangling-srv), a byte flipped mid-wire in a
  compiled answer (compiled-bytes), an old-epoch entry surviving past
  the post-flush sweep (stale-epoch), and a skewed mutation log
  (replica-digest);
- a violation is surfaced everywhere at once: flight-recorder event,
  ``binder_verify_violations_total`` counter, and the ``/status``
  verify section — and ``validate_verify_metrics`` /
  ``validate_status_snapshot`` hold throughout;
- the delta queue sheds (counted, never unbounded) past MAX_QUEUE;
- the propagation tracer: distinct trace ids per store event, handed-
  down contexts consumed exactly once, stage latencies folded into the
  introspected p50/p99, and the mutation->render->install chain
  observed end to end through a live server;
- replica-digest mechanics: the rolling digest is deterministic over
  the replicated substance and blind to trace freight; a replica
  flags a divergence exactly once and resyncs; digests stay in parity
  across a snapshot re-attach (the shard-kill/respawn path);
- the audit stays inside its time budget per slice at a 20k-name zone
  (100k behind an env gate) — the checker must never become the loop
  stall it exists to detect;
- the chaos DSL parses the verify-plane actions (string selectors
  included) and the driver dispatches them to the verify target.
"""
import asyncio
import importlib.machinery
import importlib.util
import os
import socket
import time

import pytest

from binder_tpu.chaos import ChaosDriver, FaultPlan
from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.introspect import FlightRecorder, Introspector
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.shard import ReplicaStore, protocol
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.store.cache import domain_to_path
from binder_tpu.store.fake import populate_synthetic
from binder_tpu.verify import PropagationTracer, Verifier
from tools.lint import validate_status_snapshot, validate_verify_metrics

DOMAIN = "verify.unit"


def make_fixture(recorder=None, collector=None):
    """8 hosts, one service with 3 members — every invariant has
    something to bite on."""
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    for i in range(8):
        store.put_json(domain_to_path(f"w{i}.{DOMAIN}"),
                       {"type": "host",
                        "host": {"address": f"10.77.0.{i + 1}"}})
    store.put_json(domain_to_path(f"svc.{DOMAIN}"),
                   {"type": "service",
                    "service": {"srvce": "_http", "proto": "_tcp",
                                "port": 80}})
    for i in range(3):
        store.put_json(domain_to_path(f"m{i}.svc.{DOMAIN}"),
                       {"type": "host",
                        "host": {"address": f"10.77.9.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(recorder, collector, **kw):
    store, cache = make_fixture(recorder=recorder, collector=collector)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1",
                          port=0, collector=collector,
                          query_log=kw.pop("query_log", False),
                          flight_recorder=recorder,
                          answer_precompile=True,
                          verify={"auditIntervalSeconds": 0.05}, **kw)
    await server.start()
    return server, store


async def udp_ask(port, name, qtype, qid=1):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return Message.decode(await asyncio.wait_for(fut, 5.0))
    finally:
        transport.close()


# -- the incremental checker (no loop: enqueue drains inline) --

class TestIncrementalChecker:
    def test_clean_zone_checks_without_violations(self):
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache)
        vf.enqueue_tags(list(cache.nodes))
        assert sum(vf.checks.values()) > 0
        assert sum(vf.violations.values()) == 0

    def test_dropped_reverse_entry_is_ptr_coherence(self):
        recorder = FlightRecorder(capacity=64)
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache, recorder=recorder)
        ip = "10.77.0.3"
        assert cache.rev_lookup.pop(ip) is not None
        vf.enqueue_tags([f"w2.{DOMAIN}"])
        assert vf.violations["ptr-coherence"] == 1
        ev = [e for e in recorder.events()
              if e["type"] == "verify-violation"]
        assert ev and ev[-1]["invariant"] == "ptr-coherence"
        assert ev[-1]["ip"] == ip

    def test_reverse_name_tag_checks_the_reverse_side(self):
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache)
        # corrupt the map: reverse entry points at a node the mirror
        # no longer carries
        node = cache.rev_lookup["10.77.0.1"]
        del cache.nodes[node.domain]
        vf.enqueue_tags(["1.0.77.10.in-addr.arpa"])
        assert vf.violations["ptr-coherence"] == 1

    def test_missing_service_member_is_dangling_srv(self):
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache)
        del cache.nodes[f"m1.svc.{DOMAIN}"]
        vf.enqueue_tags([f"svc.{DOMAIN}"])
        assert vf.violations["dangling-srv"] == 1

    def test_queue_sheds_past_cap_and_counts(self):
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache)
        n = vf.MAX_QUEUE + 500

        class _Tags:
            """Generator-shaped tag feed: shed must not require a
            materialized list."""
            def __iter__(self):
                return (f"ghost{i}.{DOMAIN}" for i in range(n))

        vf.enqueue_tags(_Tags())
        assert vf.skipped["queue-shed"] == 500

    def test_note_digest_counts_and_violates(self):
        recorder = FlightRecorder(capacity=64)
        _, cache = make_fixture()
        vf = Verifier(zk_cache=cache, recorder=recorder)
        vf.note_digest(7, True)
        vf.note_digest(8, False, have="aaaa", want="bbbb")
        assert vf.checks["replica-digest"] == 2
        assert vf.violations["replica-digest"] == 1
        ev = [e for e in recorder.events()
              if e["type"] == "verify-violation"]
        assert ev[-1]["generation"] == 8
        assert ev[-1]["have"] == "aaaa"


# -- compiled-table invariants + the full surfacing round trip --

class TestViolationRoundTrip:
    def run(self, coro):
        return asyncio.run(coro)

    def test_corrupt_answer_to_flight_metrics_status(self):
        async def go():
            recorder = FlightRecorder(capacity=256)
            collector = MetricsCollector()
            server, store = await start_server(recorder, collector)
            vf = server._verify
            try:
                # query evidence keeps the shape in the compiled table
                msg = await udp_ask(server.udp_port, f"w0.{DOMAIN}",
                                    Type.A)
                assert msg.rcode == Rcode.NOERROR and msg.answers
                ckey = server.corrupt_answer()
                assert ckey is not None
                vf.audit_cycle()
                assert vf.violations["compiled-bytes"] >= 1

                # flight recorder
                ev = [e for e in recorder.events()
                      if e["type"] == "verify-violation"]
                assert any(e["invariant"] == "compiled-bytes"
                           for e in ev)
                # metrics: counter advanced, full family validates
                text = collector.expose()
                assert 'invariant="compiled-bytes"' in text
                assert validate_verify_metrics(text) == []
                # /status: section present, snapshot schema holds
                intro = Introspector(server=server, recorder=recorder,
                                     name="t")
                intro.set_loop(asyncio.get_running_loop())
                snap = intro.snapshot()
                assert validate_status_snapshot(snap) == []
                sec = snap["verify"]
                assert sec["violations"]["compiled-bytes"] >= 1
                assert any(v["invariant"] == "compiled-bytes"
                           for v in sec["recent_violations"])
                # and the operator CLI renders it loudly
                loader = importlib.machinery.SourceFileLoader(
                    "bstat_cli", os.path.join(
                        os.path.dirname(os.path.dirname(
                            os.path.abspath(__file__))),
                        "bin", "bstat"))
                spec = importlib.util.spec_from_loader(
                    "bstat_cli", loader)
                bstat = importlib.util.module_from_spec(spec)
                loader.exec_module(bstat)
                out = bstat.render(snap)
                assert "VIOLATION compiled-bytes" in out
            finally:
                await server.stop()

        self.run(go())

    def test_drop_reverse_detected_by_audit(self):
        async def go():
            recorder = FlightRecorder(capacity=256)
            collector = MetricsCollector()
            server, store = await start_server(recorder, collector)
            vf = server._verify
            try:
                ip = server.drop_reverse()
                assert ip is not None
                vf.audit_cycle()
                assert vf.violations["ptr-coherence"] >= 1
            finally:
                await server.stop()

        self.run(go())

    def test_stale_epoch_survivor_past_sweep(self):
        async def go():
            recorder = FlightRecorder(capacity=256)
            collector = MetricsCollector()
            server, store = await start_server(recorder, collector)
            vf = server._verify
            cache = server.zk_cache
            ac = server.answer_cache
            try:
                # flush: epoch bump invalidates everything compiled;
                # the sweep purges old-epoch entries WITHOUT violating
                # (they are expected in the window)
                cache.invalidate_all("test-flush")
                vf.audit_cycle()
                assert vf._sweep_done
                assert vf.violations["stale-epoch"] == 0
                assert all(e[0] == cache.epoch
                           for e in ac._compiled.values())
                # an old-epoch entry AFTER the table was declared
                # clean is the violation
                ac.put_compiled(Type.A, f"w3.{DOMAIN}",
                                cache.epoch - 1,
                                [(b"\x00" * 24, 0)], False,
                                f"w3.{DOMAIN}")
                vf.audit_cycle()
                assert vf.violations["stale-epoch"] == 1
                # and the zombie was purged, not just reported
                assert (Type.A, f"w3.{DOMAIN}") not in ac._compiled
            finally:
                await server.stop()

        self.run(go())


# -- propagation tracing --

class TestPropagationTracer:
    def test_distinct_ids_per_store_event(self):
        tr = PropagationTracer()
        tr.on_store_event(1)
        a = tr.current[0]
        tr.on_store_event(2)
        b = tr.current[0]
        assert a != b

    def test_observe_without_context_is_noop(self):
        tr = PropagationTracer()
        tr.observe("mirror-apply")
        assert tr.observed == 0

    def test_inherited_context_consumed_exactly_once(self):
        tr = PropagationTracer()
        tr.inherit("m1-aa", time.monotonic() - 0.5)
        tr.on_store_event(3)
        assert tr.current[0] == "m1-aa"
        tr.on_store_event(4)
        assert tr.current[0] != "m1-aa"
        # malformed handed-down fields never become a context
        tr.inherit(None, "not-a-time")
        tr.on_store_event(5)
        assert tr.current[0] != "m1-aa"

    def test_stage_latencies_fold_into_introspection(self):
        tr = PropagationTracer()
        tr.inherit("m1-bb", time.monotonic() - 0.25)
        tr.on_store_event(1)
        tr.observe("mirror-apply")
        tr.observe("replica-apply")
        snap = tr.introspect()
        assert snap["observed"] == 2
        st = snap["stages"]["mirror-apply"]
        assert st["count"] == 1
        assert 0.2 < st["p50_seconds"] < 5.0
        slow = snap["slowest"]
        assert slow and slow[0]["trace"] == "m1-bb"

    def test_mutation_to_install_traced_through_live_server(self):
        async def go():
            recorder = FlightRecorder(capacity=256)
            collector = MetricsCollector()
            # the evidence query must surface in Python (only
            # evidenced shapes re-render on mutation): with the native
            # extension built, the precompile seed fills the C caches
            # too and a default server answers entirely in C.
            # query_log on without the JSON log ring stands the native
            # tier down (_fastpath_active), the documented way to make
            # every query surface
            server, store = await start_server(recorder, collector,
                                               zone_precompile=False,
                                               query_log=True)
            vf = server._verify
            try:
                # query evidence first: only evidenced shapes re-render
                msg = await udp_ask(server.udp_port, f"w1.{DOMAIN}",
                                    Type.A)
                assert msg.rcode == Rcode.NOERROR
                store.put_json(domain_to_path(f"w1.{DOMAIN}"),
                               {"type": "host",
                                "host": {"address": "10.77.0.99"}})
                # deadline poll, not a fixed sleep: the precompiler
                # drains its queue in budgeted loop passes
                want = ("mirror-apply", "precompile-render",
                        "compiled-install")
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    prop = vf.introspect()["propagation"]
                    if all(prop["stages"][s]["count"] >= 1
                           for s in want):
                        break
                    await asyncio.sleep(0.02)
                for stage in want:
                    assert prop["stages"][stage]["count"] >= 1, stage
                assert prop["observed"] >= 3
            finally:
                await server.stop()

        asyncio.run(go())


# -- replica-digest mechanics --

class TestReplicaDigest:
    def _node_frame(self, name, addr, tr=None, t0=None):
        return protocol.node_frame(
            f"{name}.{DOMAIN}",
            {"type": "host", "host": {"address": addr}}, tr, t0)

    def test_digest_deterministic_and_blind_to_trace_freight(self):
        f1 = self._node_frame("x", "10.1.1.1")
        f2 = self._node_frame("x", "10.1.1.1", "m1-ff", 123.25)
        f3 = self._node_frame("x", "10.1.1.2")
        assert protocol.delta_digest("0", f1) \
            == protocol.delta_digest("0", f2)
        assert protocol.delta_digest("0", f1) \
            != protocol.delta_digest("0", f3)
        # chaining is order-sensitive (it is a log digest, not a set)
        ab = protocol.delta_digest(protocol.delta_digest("0", f1), f3)
        ba = protocol.delta_digest(protocol.delta_digest("0", f3), f1)
        assert ab != ba

    def test_replica_flags_divergence_once_and_resyncs(self):
        sup_end, worker_end = socket.socketpair()
        try:
            replica = ReplicaStore(worker_end, 0)
            replica._dg = "0"           # as armed at snap-end
            outcomes = []
            replica.on_digest = lambda gen, ok, have, want: \
                outcomes.append((gen, ok))

            f = self._node_frame("y", "10.2.2.2")
            replica._apply(f)
            good = protocol.delta_digest("0", f)
            replica._apply(protocol.digest_frame(1, good))
            assert outcomes == [(1, True)]

            # owner claims a digest we never saw the frames for
            replica._apply(protocol.digest_frame(2, "feedbeefdead0000"))
            assert outcomes[-1] == (2, False)
            # resynced to the owner's roll: no cascade next frame
            assert replica._dg == "feedbeefdead0000"
            # the mismatch went up-channel as a digest report
            sup_end.settimeout(5.0)
            frames = protocol.decode_frames(
                bytearray(sup_end.recv(65536)))
            reports = [fr for fr in frames
                       if fr.get("op") == "digest-report"]
            assert len(reports) == 1
            assert reports[0]["ok"] is False
            assert reports[0]["want"] == "feedbeefdead0000"
        finally:
            sup_end.close()
            worker_end.close()

    def test_delta_frame_trace_feeds_replica_tracer(self):
        """The worker-side half of _wire_shard_worker: the replica
        stages the frame's handed-down context, the mirror's bump_gen
        consumes it, and replica-apply reports against the OWNER's
        t0."""
        sup_end, worker_end = socket.socketpair()
        try:
            replica = ReplicaStore(worker_end, 0)
            tracer = PropagationTracer()
            replica.tracer = tracer
            cache = MirrorCache(replica, DOMAIN)
            cache.tracer = tracer
            replica.start_session()
            # untraced create first: a node CREATE fires the parent's
            # children-watch too (two store events — the second would
            # clobber the inherited context with a fresh one); the
            # traced hot-churn flow is an UPDATE on an existing node,
            # which fires exactly one
            replica._apply(self._node_frame("z", "10.3.3.1"))
            replica._apply(self._node_frame(
                "z", "10.3.3.3", "m9-01", time.monotonic() - 0.1))
            snap = tracer.introspect()
            assert snap["stages"]["replica-apply"]["count"] >= 1
            traced = [s for s in snap["slowest"]
                      if s["trace"] == "m9-01"]
            # end-to-end latency: against the owner's 0.1s-old t0
            assert traced and traced[0]["seconds"] > 0.05
            assert cache.lookup(f"z.{DOMAIN}").data["host"][
                "address"] == "10.3.3.3"
        finally:
            sup_end.close()
            worker_end.close()

    def test_parity_across_snapshot_reattach(self):
        """The shard-kill/respawn path: a replica that re-attaches via
        a fresh snapshot re-arms at "0" alongside the owner's roll, so
        digests agree again — divergence cannot outlive a respawn."""
        from binder_tpu.shard.supervisor import ShardLink, ShardSupervisor

        class _StubProc:
            pid = 0

            def poll(self):
                return None

        async def run():
            store, cache = make_fixture()
            sup = ShardSupervisor(
                options={"shards": 1, "host": "127.0.0.1", "port": 0,
                         "dnsDomain": DOMAIN},
                store=store, cache=cache, collector=MetricsCollector())
            sup._loop = asyncio.get_running_loop()

            def attach(shard):
                sup_end, worker_end = socket.socketpair()
                sup_end.setblocking(False)
                link = ShardLink(shard, _StubProc(), sup_end)
                sup.links[shard] = link
                sup._send_snapshot(link)
                replica = ReplicaStore(worker_end, shard)
                while link.snap_queue is not None:
                    sup._pump_snapshot(link)
                replica.read_snapshot(timeout=30.0)
                return link, replica

            def drain_until(replica, done):
                replica._sock.settimeout(5.0)
                while not done():
                    for frame in replica._recv_frames():
                        replica._apply(frame)

            link, replica = attach(0)
            outcomes = []
            replica.on_digest = lambda gen, ok, have, want: \
                outcomes.append(ok)
            assert replica._dg == "0" and link.dg == "0"

            store.put_json(domain_to_path(f"w0.{DOMAIN}"),
                           {"type": "host",
                            "host": {"address": "10.77.0.201"}})
            drain_until(replica, lambda: outcomes)
            assert outcomes and all(outcomes)
            assert replica._dg == link.dg != "0"

            # kill + respawn: fresh link, fresh snapshot, fresh roll
            sup._close_link(link)
            del sup.links[0]
            replica.close()
            link2, replica2 = attach(0)
            outcomes2 = []
            replica2.on_digest = lambda gen, ok, have, want: \
                outcomes2.append(ok)
            assert replica2._dg == "0" and link2.dg == "0"
            assert replica2.exists(
                domain_to_path(f"w0.{DOMAIN}"))
            store.put_json(domain_to_path(f"w0.{DOMAIN}"),
                           {"type": "host",
                            "host": {"address": "10.77.0.202"}})
            drain_until(replica2, lambda: outcomes2)
            assert outcomes2 and all(outcomes2)
            assert replica2._dg == link2.dg
            sup._close_link(link2)
            replica2.close()

        asyncio.run(run())


# -- the sampled audit at zone scale --

def _audit_scale(names, budget_factor):
    store = FakeStore()
    populate_synthetic(store, DOMAIN, names)
    cache = MirrorCache(store, DOMAIN)
    store.start_session()
    vf = Verifier(zk_cache=cache, config={"auditSample": 4})
    worst = 0.0
    passes0 = vf.audit_passes
    while vf.audit_passes == passes0 or vf._audit_work:
        t0 = time.perf_counter()
        vf.audit_slice()
        worst = max(worst, time.perf_counter() - t0)
    assert vf.audit_passes == passes0 + 1
    assert sum(vf.violations.values()) == 0
    assert vf.checks["ptr-coherence"] > 0
    # each slice must stay well under the loop-lag watchdog's 250 ms
    # stall threshold — the 2 ms budget plus one refill's list() over
    # the node index; the factor absorbs CI-box jitter
    assert worst < 0.25 * budget_factor, worst
    return worst


class TestAuditScale:
    def test_20k_zone_slices_stay_inside_budget(self):
        _audit_scale(20000, budget_factor=0.5)

    @pytest.mark.skipif(
        "BINDER_VERIFY_SCALE" not in os.environ,
        reason="set BINDER_VERIFY_SCALE=1 for the 100k audit tier")
    def test_100k_zone_slices_stay_inside_budget(self):
        _audit_scale(100000, budget_factor=1.0)


# -- chaos DSL: the verify-plane actions --

class TestChaosVerifyActions:
    def test_parse_actions_with_string_selectors(self):
        plan = FaultPlan.parse(
            "at 0.5 corrupt-answer qname=web.foo.com\n"
            "at 1.0 drop-reverse ip=10.0.0.1\n"
            "at 1.5 skew-replica shard=0 frames=2\n"
            "at 2.0 corrupt-answer")
        acts = [(t, a, kw) for t, a, kw in plan.timeline]
        assert acts[0] == (0.5, "corrupt-answer",
                           {"qname": "web.foo.com"})
        assert acts[1] == (1.0, "drop-reverse", {"ip": "10.0.0.1"})
        assert acts[2] == (1.5, "skew-replica",
                           {"shard": 0, "frames": 2})
        assert acts[3] == (2.0, "corrupt-answer", {})

    def test_parse_rejects_empty_selector(self):
        with pytest.raises(ValueError):
            FaultPlan.parse("at 1 corrupt-answer qname=")

    def test_driver_dispatches_to_verify_target(self):
        calls = []

        class Target:
            def corrupt_answer(self, qname=None):
                calls.append(("corrupt", qname))
                return (1, qname)

            def drop_reverse(self, ip=None):
                calls.append(("drop", ip))
                return ip

            def skew_replica(self, shard=-1, frames=1):
                calls.append(("skew", shard, frames))
                return shard

        plan = (FaultPlan()
                .at(0.0, "corrupt-answer", qname="a.b")
                .at(0.0, "drop-reverse", ip="10.9.9.9")
                .at(0.0, "skew-replica", shard=1, frames=3))
        recorder = FlightRecorder(capacity=64)
        driver = ChaosDriver(plan, verify_target=Target(),
                             recorder=recorder)
        asyncio.run(driver.run())
        assert ("corrupt", "a.b") in calls
        assert ("drop", "10.9.9.9") in calls
        assert ("skew", 1, 3) in calls
        injected = [e for e in recorder.events()
                    if e["type"] == "chaos-inject"]
        assert len(injected) == 3

    def test_missing_target_or_hook_is_skipped_not_fatal(self):
        plan = FaultPlan().at(0.0, "corrupt-answer")
        asyncio.run(ChaosDriver(plan).run())
        asyncio.run(ChaosDriver(plan, verify_target=object()).run())
