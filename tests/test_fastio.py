"""Tests for the batched UDP syscall extension (native/fastio/fastio.c).

The batched datapath replaces the reference's one-syscall-per-packet hot
path (mname's UDP handling); these tests pin the extension's contract so
the asyncio reader in binder_tpu/dns/server.py can rely on it.  The full
server path over the batched reader is exercised by every UDP test in
test_server.py whenever the extension is built.
"""
import socket
import time

import pytest

fastio = pytest.importorskip(
    "binder_tpu._binderfastio",
    reason="fastio extension not built (make -C native)")


def _udp_pair(host="127.0.0.1"):
    fam = socket.AF_INET6 if ":" in host else socket.AF_INET
    a = socket.socket(fam, socket.SOCK_DGRAM)
    a.bind((host, 0))
    a.setblocking(False)
    b = socket.socket(fam, socket.SOCK_DGRAM)
    b.bind((host, 0))
    b.setblocking(False)
    return a, b


def _drain(sock, want, tries=50):
    got = []
    for _ in range(tries):
        got += fastio.recv_batch(sock.fileno(), 64)
        if len(got) >= want:
            break
        time.sleep(0.01)
    return got


def test_roundtrip_ipv4():
    a, b = _udp_pair()
    dst = a.getsockname()
    msgs = [(b"payload-%d" % i, (dst[0], dst[1])) for i in range(10)]
    assert fastio.send_batch(b.fileno(), msgs) == 10
    got = _drain(a, 10)
    assert [p for p, _ in got] == [p for p, _ in msgs]
    # source addresses name b's bound port
    assert all(addr == b.getsockname()[:2] for _, addr in got)
    a.close(), b.close()


def test_roundtrip_ipv6():
    a, b = _udp_pair("::1")
    dst = a.getsockname()
    assert fastio.send_batch(b.fileno(), [(b"six", (dst[0], dst[1]))]) == 1
    got = _drain(a, 1)
    assert got[0][0] == b"six"
    assert got[0][1][0] == "::1"
    a.close(), b.close()


def test_recv_empty_when_would_block():
    a, _b = _udp_pair()
    assert fastio.recv_batch(a.fileno(), 64) == []
    a.close(), _b.close()


def test_recv_respects_max_n():
    a, b = _udp_pair()
    dst = a.getsockname()[:2]
    fastio.send_batch(b.fileno(), [(b"x%d" % i, dst) for i in range(8)])
    time.sleep(0.05)
    first = fastio.recv_batch(a.fileno(), 3)
    assert len(first) == 3
    rest = _drain(a, 5)
    assert len(first) + len(rest) == 8
    a.close(), b.close()


def test_send_batch_over_64_chunks_internally():
    a, b = _udp_pair()
    dst = a.getsockname()[:2]
    msgs = [(b"m%d" % i, dst) for i in range(150)]
    sent = fastio.send_batch(b.fileno(), msgs)
    assert sent == 150
    got = _drain(a, 150)
    assert len(got) == 150
    a.close(), b.close()


def test_send_batch_skips_bad_destination():
    # one unreachable destination must not drop other clients' responses
    # (port 0 fails at the first datagram with EINVAL, exercising the
    # skip-and-continue branch in fastio.c)
    a, b = _udp_pair()
    dst = a.getsockname()[:2]
    msgs = [(b"doomed", ("127.0.0.1", 0)), (b"fine-1", dst),
            (b"fine-2", dst)]
    assert fastio.send_batch(b.fileno(), msgs) == 3
    got = _drain(a, 2)
    assert [p for p, _ in got] == [b"fine-1", b"fine-2"]
    a.close(), b.close()


def test_send_batch_bad_args():
    a, b = _udp_pair()
    with pytest.raises(TypeError):
        fastio.send_batch(b.fileno(), [(b"x",)])
    with pytest.raises(ValueError):
        fastio.send_batch(b.fileno(), [(b"x", ("not-an-ip", 1))])
    a.close(), b.close()
