"""Real-systemd conformance tier for instance_adjust (-m systemd).

tests/test_instance_adjust_systemd.py proves the systemctl *command
protocol* against a fake; this tier proves the protocol drives a REAL
systemd to the intended states — the reference's smf_adjust is only
ever exercised against real SMF (src/smf_adjust.c:866-931), so the
rebuild needs at least an opt-in path where real PID-1 behavior
(daemon-reload visibility, failed-state bookkeeping, disable --now
semantics) is the oracle.

Opt-in mirror of the real-ZooKeeper tier (tests/test_conformance.py):

    BINDER_SYSTEMD_CONFORMANCE=1 python -m pytest tests/test_systemd_real_conformance.py

Requires a booted systemd (PID 1) and root: the tier installs a
transient stub template unit ``binder-conftest@.service`` under
/run/systemd/system (gone on reboot by construction), converges real
instances against it on high ports, and removes everything — including
on failure.  Skip-marked everywhere else, and visible either way in the
`make ci` tier report (tools/conformance_tiers.py).
"""
import os
import subprocess
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ADJUST = os.path.join(ROOT, "native", "build", "instance_adjust")

BASE = "binder-conftest"          # never collides with a real deployment
BASEPORT = 47301
UNIT_DIR = "/run/systemd/system"  # transient: cleared on reboot

OPTED_IN = os.environ.get("BINDER_SYSTEMD_CONFORMANCE") == "1"


def _booted_systemd() -> bool:
    """True only when systemd is actually PID 1 of this context —
    /run/systemd/system alone can be a bind-mount artifact in
    containers."""
    try:
        with open("/proc/1/comm") as f:
            return f.read().strip() == "systemd"
    except OSError:
        return False


pytestmark = [
    pytest.mark.skipif(
        not OPTED_IN,
        reason="set BINDER_SYSTEMD_CONFORMANCE=1 to drive real systemd "
               "units (installs a transient stub template under "
               "/run/systemd/system; requires root on a systemd host)"),
    pytest.mark.skipif(OPTED_IN and not _booted_systemd(),
                       reason="systemd is not PID 1 here"),
    pytest.mark.skipif(OPTED_IN and os.geteuid() != 0,
                       reason="requires root (writes /run/systemd/system)"),
    pytest.mark.skipif(not os.path.exists(ADJUST),
                       reason="instance_adjust not built (make -C native)"),
]

# Stub instance: binds a unix socket at the drop-in-provided
# BINDER_SOCKET_PATH (what `-w` waits for), then idles.  Single-quoted
# for systemd's ExecStart unquoting; no single quotes inside.
STUB_UNIT = f"""\
[Unit]
Description=instance_adjust conformance stub on port %i

[Service]
Type=simple
Environment=BINDER_PORT=%i
Environment=BINDER_SOCKET_PATH=/run/{BASE}/%i
ExecStart=/usr/bin/python3 -c 'import os, signal, socket; \
p = os.environ["BINDER_SOCKET_PATH"]; \
os.makedirs(os.path.dirname(p), exist_ok=True); \
os.path.exists(p) and os.unlink(p); \
s = socket.socket(socket.AF_UNIX); s.bind(p); signal.pause()'
ExecStopPost=/bin/sh -c 'rm -f "$BINDER_SOCKET_PATH"'
"""


def _systemctl(*args, check=True):
    proc = subprocess.run(["systemctl", *args], capture_output=True,
                          text=True, timeout=60)
    if check:
        assert proc.returncode == 0, (args, proc.stdout, proc.stderr)
    return proc.stdout


def _active_state(port: int) -> str:
    return _systemctl("show", "-p", "ActiveState", "--value",
                      f"{BASE}@{port}.service").strip()


@pytest.fixture
def real_sd(tmp_path):
    """Install the stub template; tear down every trace afterwards."""
    unit_path = os.path.join(UNIT_DIR, f"{BASE}@.service")
    with open(unit_path, "w") as f:
        f.write(STUB_UNIT)
    _systemctl("daemon-reload")

    sockdir = tmp_path / "sockets"
    sockdir.mkdir()

    class Env:
        sockets = sockdir

        def adjust(self, count, extra=None, expect_rc=0):
            cmd = [ADJUST, "-m", "systemd", "-D", UNIT_DIR,
                   "-b", BASE, "-B", str(BASEPORT), "-i", str(count),
                   "-d", str(self.sockets)]
            cmd += extra or []
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120)
            assert proc.returncode == expect_rc, (proc.stdout, proc.stderr)
            return proc.stdout.splitlines()

    try:
        yield Env()
    finally:
        # converge to zero through the tool under test, then belt and
        # braces by hand for anything a mid-test failure left behind
        subprocess.run([ADJUST, "-m", "systemd", "-D", UNIT_DIR,
                        "-b", BASE, "-B", str(BASEPORT), "-i", "0",
                        "-d", str(sockdir)],
                       capture_output=True, timeout=120)
        for port in range(BASEPORT, BASEPORT + 8):
            u = f"{BASE}@{port}.service"
            subprocess.run(["systemctl", "disable", "--now", u],
                           capture_output=True, timeout=60)
            subprocess.run(["systemctl", "reset-failed", u],
                           capture_output=True, timeout=60)
            d = os.path.join(UNIT_DIR, u + ".d")
            if os.path.isdir(d):
                for fn in os.listdir(d):
                    os.unlink(os.path.join(d, fn))
                os.rmdir(d)
        os.unlink(unit_path)
        subprocess.run(["systemctl", "daemon-reload"], capture_output=True,
                       timeout=60)


class TestRealSystemd:
    def test_full_lifecycle(self, real_sd):
        """create → no-op → config change → failed restore → removal,
        with real systemd state as the oracle at every step."""
        # -- create: units really active, sockets really bound (-w) --
        out = real_sd.adjust(2, extra=["-w"])
        assert f"create {BASE}-{BASEPORT}" in out, out
        for port in (BASEPORT, BASEPORT + 1):
            assert _active_state(port) == "active"
            assert (real_sd.sockets / str(port)).is_socket()
        assert "enabled" in _systemctl(
            "is-enabled", f"{BASE}@{BASEPORT}.service")

        # -- converged re-run is a no-op --
        out = real_sd.adjust(2)
        assert f"unchanged {BASE}-{BASEPORT}" in out, out
        assert f"unchanged {BASE}-{BASEPORT + 1}" in out, out

        # -- config change: drop-in rewritten, running unit restarted --
        pid_before = _systemctl(
            "show", "-p", "MainPID", "--value",
            f"{BASE}@{BASEPORT}.service").strip()
        real_sd.sockets = real_sd.sockets.parent / "sockets2"
        real_sd.sockets.mkdir()
        out = real_sd.adjust(2, extra=["-w"])
        assert f"configure {BASE}-{BASEPORT}" in out, out
        assert (real_sd.sockets / str(BASEPORT)).is_socket()
        pid_after = _systemctl(
            "show", "-p", "MainPID", "--value",
            f"{BASE}@{BASEPORT}.service").strip()
        assert pid_after not in ("", "0", pid_before)

        # -- failed instance is restored (flush_status analog) --
        _systemctl("kill", "--signal=SIGKILL",
                   f"{BASE}@{BASEPORT}.service")
        deadline = time.time() + 10
        while _active_state(BASEPORT) not in ("failed",) and \
                time.time() < deadline:
            time.sleep(0.2)
        assert _active_state(BASEPORT) == "failed"
        out = real_sd.adjust(2, extra=["-w"])
        assert f"restore {BASE}-{BASEPORT}" in out, out
        assert _active_state(BASEPORT) == "active"

        # -- scale down removes real units and their drop-ins --
        out = real_sd.adjust(1)
        assert f"remove {BASE}-{BASEPORT + 1}" in out, out
        assert _active_state(BASEPORT + 1) in ("inactive", "unknown", "")
        assert not os.path.isdir(os.path.join(
            UNIT_DIR, f"{BASE}@{BASEPORT + 1}.service.d"))
        assert _active_state(BASEPORT) == "active"  # survivor untouched
