"""Mutation-time answer precompilation (resolver/precompile.py).

Pins the tentpole properties of the precompiled answer layer:

- a store mutation re-renders the affected names' answers and installs
  them, so the post-churn query is a compiled-table probe + ID/flags
  patch (``log_ctx["precompiled"]``), never an engine resolve;
- invalidate-then-reinstall under sustained churn keeps read-your-writes
  (the drop is synchronous, the re-render immediate on the inline path);
- precompiled wires are byte-for-byte what the engine would encode —
  including every round-robin rotation variant, SRV answer+additional
  sections, negative answers, and both EDNS postures (modulo the 16-bit
  id, which is patched per query);
- a watch storm that outruns the bounded work queue SHEDS (metrics +
  flight-recorder event) and those names degrade to today's lazy
  resolution — correct answers, just slower;
- negative answers (NXDOMAIN / NODATA) are cached with their own
  accounting; SERVFAIL is never cached or compiled;
- the ``binder_precompile_*`` metric family is pinned by
  ``tools/lint.py validate_precompile_metrics`` against the real
  exposition text.
"""
import asyncio

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.query import QueryCtx
from binder_tpu.introspect import FlightRecorder
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

from tools.lint import validate_precompile_metrics

DOMAIN = "foo.com"
SVC = "/com/foo/svc"


def build(precompile=True, recorder=None, **kw):
    """Server over a fake store; fixtures are loaded AFTER construction
    so every put_json is a live mutation event (the precompiler's input),
    delivered synchronously (no loop -> inline compile)."""
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN, recorder=recorder)
    store.start_session()
    server = BinderServer(
        zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
        collector=MetricsCollector(), query_log=False,
        answer_precompile=precompile, flight_recorder=recorder, **kw)
    return store, cache, server


def ask(server, name, qtype, rd=False, edns=1232, qid=7):
    sent = []
    req = make_query(name, qtype, qid=qid, rd=rd, edns_payload=edns)
    q = QueryCtx(req, ("127.0.0.1", 5353), "udp", sent.append)
    pending = server._on_query(q)
    assert pending is None
    assert len(sent) == 1, "server must respond exactly once"
    return Message.decode(sent[0]), sent[0], q


def put_host(store, path, addr, **extra):
    rec = {"type": "host", "host": {"address": addr}}
    rec.update(extra)
    store.put_json(path, rec)


def put_service(store, n_members=3):
    store.put_json(SVC, {"type": "service",
                         "service": {"srvce": "_pg", "proto": "_tcp",
                                     "port": 5432}})
    for i in range(n_members):
        store.put_json(f"{SVC}/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})


def forbid_engine(server):
    """Any resolve past the compiled table is a test failure."""
    def boom(_query):
        raise AssertionError("engine consulted; precompiled layer missed")
    server.resolver.handle = boom


class TestMutationInstalls:
    """Mutation-path re-rendering is EVIDENCE-BASED: the shapes a
    mutation's invalidation actually dropped (things being served) are
    re-rendered eagerly; churn on unqueried names costs nothing.  The
    startup seed covers the cold mirror.  So the pattern here is:
    prime (one lazy query), mutate, then the engine is forbidden."""

    def test_mutation_recompiles_served_host_answer(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        ask(server, "web.foo.com", Type.A, qid=1)         # evidence
        put_host(store, "/com/foo/web", "10.9.9.9")       # mutation
        forbid_engine(server)
        r, _, q = ask(server, "web.foo.com", Type.A, qid=2)
        assert r.rcode == Rcode.NOERROR
        assert [a.address for a in r.answers] == ["10.9.9.9"]
        assert q.log_ctx.get("precompiled") is True

    def test_unqueried_churn_compiles_nothing(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        for i in range(5):
            put_host(store, "/com/foo/web", f"10.1.2.{i + 4}")
        assert server._precompiler.compiled == 0
        assert server.answer_cache.stats()["compiled_entries"] == 0

    def test_mutation_recompiles_served_ptr(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        ask(server, "3.2.1.10.in-addr.arpa", Type.PTR, qid=1)
        # address unchanged, record rewritten (ttl added): the reverse
        # shape's per-key entry drops and is re-rendered
        put_host(store, "/com/foo/web", "10.1.2.3", ttl=55)
        forbid_engine(server)
        r, _, q = ask(server, "3.2.1.10.in-addr.arpa", Type.PTR, qid=2)
        assert r.answers[0].target == "web.foo.com"
        assert r.answers[0].ttl == 55
        assert q.log_ctx.get("precompiled") is True

    def test_mutation_recompiles_served_srv(self):
        store, cache, server = build()
        put_service(store)
        ask(server, "_pg._tcp.svc.foo.com", Type.SRV, qid=1)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer",
                        "load_balancer": {"address": "10.0.9.9"}})
        forbid_engine(server)
        r, _, q = ask(server, "_pg._tcp.svc.foo.com", Type.SRV, qid=2)
        assert r.rcode == Rcode.NOERROR
        assert len(r.answers) == 3 and all(a.port == 5432
                                           for a in r.answers)
        addl = {a.name: a.address for a in r.additionals
                if hasattr(a, "address")}
        assert addl["lb0.svc.foo.com"] == "10.0.9.9"
        assert q.log_ctx.get("precompiled") is True

    def test_seed_mirror_compiles_preexisting_names(self):
        # fixture loaded BEFORE the server subscribed: only the startup
        # seed can compile it (the _zone_fill analog)
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        store.start_session()
        put_host(store, "/com/foo/old", "10.9.9.9")
        server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                              datacenter_name="dc0",
                              collector=MetricsCollector(),
                              query_log=False, answer_precompile=True)
        server._precompiler.seed_mirror()
        forbid_engine(server)
        r, _, q = ask(server, "old.foo.com", Type.A)
        assert [a.address for a in r.answers] == ["10.9.9.9"]
        assert q.log_ctx.get("precompiled") is True
        # the reverse shape seeded too
        r, _, _q = ask(server, "9.9.9.10.in-addr.arpa", Type.PTR)
        assert r.answers[0].target == "old.foo.com"

    def test_servfail_shape_never_compiled(self):
        store, cache, server = build()
        store.put_json("/com/foo/junk", {"type": "host"})  # no sub-object
        pc = server._precompiler
        pc.seed_mirror()
        assert pc.declined > 0
        assert server.answer_cache.stats()["compiled_entries"] == 0
        r, _, q = ask(server, "junk.foo.com", Type.A)
        assert r.rcode == Rcode.SERVFAIL
        assert "precompiled" not in q.log_ctx
        # and the SERVFAIL was not cached either (the absolute rule)
        assert server.answer_cache.stats()["entries"] == 0

    def test_recursion_miss_not_compiled(self):
        class _Rec:
            pass
        store, cache, server = build(recursion=_Rec())
        put_host(store, "/com/foo/web", "10.1.2.3")
        store.rmr("/com/foo/web")
        # the deleted name's answer is RD-dependent now (REFUSED vs
        # cross-DC forward): only the lazy path may decide
        assert server.answer_cache.get_compiled(
            Type.A, "web.foo.com", cache.epoch) is None


class TestChurn:
    def test_invalidated_then_reinstalled_under_churn(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.0.0.1")
        put_host(store, "/com/foo/stable", "10.7.7.7")
        # serving evidence: one lazy query each
        ask(server, "web.foo.com", Type.A, qid=1)
        ask(server, "stable.foo.com", Type.A, qid=1)
        for i in range(2, 60):
            addr = f"10.0.{i % 250}.{i % 250}"
            put_host(store, "/com/foo/web", addr)
            r, _, q = ask(server, "web.foo.com", Type.A, qid=i)
            # read-your-writes through the compiled path: the mutation's
            # drop was synchronous and the re-render immediate, so the
            # post-churn query serves the NEW address, precompiled
            assert [a.address for a in r.answers] == [addr]
            assert q.log_ctx.get("precompiled") is True
            # the unmutated neighbor keeps serving (per-name selectivity)
            r2, _, _q2 = ask(server, "stable.foo.com", Type.A, qid=i)
            assert [a.address for a in r2.answers] == ["10.7.7.7"]

    def test_dropped_negative_shape_reinstalled(self):
        store, cache, server = build()
        put_service(store)
        # a concrete negative qname a client actually asked: cached by
        # the query path with its question identity (qkey)
        r, _, _q = ask(server, "_http._tcp.svc.foo.com", Type.SRV)
        assert r.rcode == Rcode.NXDOMAIN
        # churn the service: the dropped key's identity rides to the
        # precompiler, which re-renders the negative eagerly
        store.put_json(SVC, {"type": "service",
                             "service": {"srvce": "_pg", "proto": "_tcp",
                                         "port": 5433}})
        forbid_engine(server)
        r, _, q = ask(server, "_http._tcp.svc.foo.com", Type.SRV, qid=9)
        assert r.rcode == Rcode.NXDOMAIN
        assert q.log_ctx.get("precompiled") is True


class TestWireParity:
    """Precompiled wires must be byte-for-byte what the engine encodes
    (modulo the 16-bit id and the rotation variant — here both are
    pinned: same qid, rng stubbed to a known rotation)."""

    def fixture_pair(self, load):
        s1, c1, srv1 = build(precompile=True)
        s2, c2, srv2 = build(precompile=False)
        load(s1)
        load(s2)
        srv1._precompiler.seed_mirror()   # the cold-start walk
        return srv1, srv2

    def assert_parity(self, name, qtype, load, edns=1232, rd=False,
                      prime=False, perturb=None):
        """``prime=True`` for shapes only reachable through the
        dropped-key path (concrete negative qnames): ask once lazily so
        the question identity is cached, then mutate so the
        invalidation hands it to the precompiler for re-render."""
        srv_pre, srv_eng = self.fixture_pair(load)
        if prime:
            s1 = srv_pre.zk_cache.store
            ask(srv_pre, name, qtype, qid=99, edns=edns, rd=rd)
            # a REAL mutation (identical re-puts no longer invalidate:
            # unchanged data cannot change answers), restored to the
            # canonical fixture so the parity comparison holds
            if perturb is None:
                perturb = lambda s: s.put_json(  # noqa: E731
                    SVC, {"type": "service",
                          "service": {"srvce": "_pg", "proto": "_tcp",
                                      "port": 5433}})
            perturb(s1)
            load(s1)                    # restore == second mutation
        forbid_engine(srv_pre)
        _, wire_pre, q = ask(srv_pre, name, qtype, qid=3, edns=edns,
                             rd=rd)
        assert q.log_ctx.get("precompiled") is True
        _, wire_eng, _q = ask(srv_eng, name, qtype, qid=3, edns=edns,
                              rd=rd)
        assert wire_pre == wire_eng

    def test_host_a_parity(self):
        load = lambda s: put_host(s, "/com/foo/web", "10.1.2.3", ttl=77)
        self.assert_parity("web.foo.com", Type.A, load)
        self.assert_parity("web.foo.com", Type.A, load, edns=None)
        self.assert_parity("web.foo.com", Type.A, load, rd=True)

    def test_database_parity(self):
        self.assert_parity("pg.foo.com", Type.A, lambda s: s.put_json(
            "/com/foo/pg",
            {"type": "database",
             "database": {"primary": "tcp://10.99.99.14:5432/x"}}))

    def test_ptr_parity(self):
        self.assert_parity(
            "3.2.1.10.in-addr.arpa", Type.PTR,
            lambda s: put_host(s, "/com/foo/web", "10.1.2.3"))

    def test_nodata_soa_parity(self):
        load = lambda s: put_host(s, "/com/foo/web", "10.1.2.3", ttl=60)
        touch = lambda s: put_host(s, "/com/foo/web", "10.9.9.9",
                                   ttl=60)
        self.assert_parity("_pg._tcp.web.foo.com", Type.SRV, load,
                           prime=True, perturb=touch)
        self.assert_parity("_pg._tcp.web.foo.com", Type.SRV, load,
                           edns=None, prime=True, perturb=touch)

    def test_nxdomain_parity(self):
        self.assert_parity("_http._udp.svc.foo.com", Type.SRV,
                           put_service, prime=True)

    class _RotRng:
        """shuffle() = rotate left by k — the cyclic variant the
        precompiler renders as variant k."""

        def __init__(self, k):
            self.k = k

        def shuffle(self, lst):
            k = self.k % len(lst) if lst else 0
            lst[:] = lst[k:] + lst[:k]

    def test_rotation_variant_parity_plain_a(self):
        for k in range(3):
            srv_pre, srv_eng = self.fixture_pair(put_service)
            srv_eng.resolver.rng = self._RotRng(k)
            forbid_engine(srv_pre)
            # compiled serves rotate 0,1,2,... — advance to variant k
            for i in range(k):
                ask(srv_pre, "svc.foo.com", Type.A, qid=50 + i)
            _, wire_pre, q = ask(srv_pre, "svc.foo.com", Type.A, qid=3)
            assert q.log_ctx.get("precompiled") is True
            _, wire_eng, _q = ask(srv_eng, "svc.foo.com", Type.A, qid=3)
            assert wire_pre == wire_eng

    def test_rotation_variant_parity_srv(self):
        for k in range(3):
            srv_pre, srv_eng = self.fixture_pair(put_service)
            srv_eng.resolver.rng = self._RotRng(k)
            forbid_engine(srv_pre)
            for i in range(k):
                ask(srv_pre, "_pg._tcp.svc.foo.com", Type.SRV,
                    qid=50 + i)
            _, wire_pre, q = ask(srv_pre, "_pg._tcp.svc.foo.com",
                                 Type.SRV, qid=3)
            assert q.log_ctx.get("precompiled") is True
            _, wire_eng, _q = ask(srv_eng, "_pg._tcp.svc.foo.com",
                                  Type.SRV, qid=3)
            assert wire_pre == wire_eng

    def test_all_variants_cover_member_set(self):
        store, cache, server = build()
        put_service(store)
        server._precompiler.seed_mirror()
        forbid_engine(server)
        firsts = set()
        for i in range(3):
            r, _, _q = ask(server, "svc.foo.com", Type.A, qid=i + 1)
            assert sorted(a.address for a in r.answers) == \
                ["10.0.1.1", "10.0.1.2", "10.0.1.3"]
            firsts.add(r.answers[0].address)
        # round-robin: consecutive serves lead with different members
        assert len(firsts) == 3


class TestStormShedding:
    def test_storm_sheds_to_lazy(self):
        recorder = FlightRecorder(capacity=64)

        async def run():
            store, cache, server = build(recorder=recorder)
            pc = server._precompiler
            # instance shadow of the bound (the cap too: the effective
            # bound scales with zone size up to MAX_PENDING_CAP)
            pc.MAX_PENDING = pc.MAX_PENDING_CAP = 4
            # 40 served names (the evidence that makes their mutations
            # re-render work)
            for i in range(40):
                put_host(store, f"/com/foo/s{i}", f"10.1.0.{i + 1}")
                ask(server, f"s{i}.foo.com", Type.A, qid=i + 1)
            await asyncio.sleep(0)
            # storm: every served name mutated within one loop pass (no
            # drain runs in between) — far more work than the queue
            # admits
            for i in range(40):
                put_host(store, f"/com/foo/s{i}", f"10.2.0.{i + 1}")
            assert pc.shed > 0
            assert len(pc._pending) <= pc.MAX_PENDING
            # lazy fallback: a shed name still answers correctly (the
            # engine path), just without the precompiled serve
            r, _, q = ask(server, "s39.foo.com", Type.A, qid=99)
            assert r.rcode == Rcode.NOERROR
            assert [a.address for a in r.answers] == ["10.2.0.40"]
            # draining the queue compiles what was admitted
            while pc._pending:
                await asyncio.sleep(0)
            assert pc.compiled > 0
            return server

        asyncio.run(run())
        events = [e for e in recorder.events()
                  if e["type"] == "precompile-shed"]
        assert events, "shedding must leave flight-recorder evidence"
        assert events[0]["shed"] > 0

    def test_shed_then_requeued_on_next_mutation(self):
        async def run():
            store, cache, server = build()
            pc = server._precompiler
            for i in range(10):
                put_host(store, f"/com/foo/b{i}", f"10.2.0.{i + 1}")
                ask(server, f"b{i}.foo.com", Type.A, qid=i + 1)
            await asyncio.sleep(0)
            pc.MAX_PENDING = pc.MAX_PENDING_CAP = 2
            for i in range(10):
                put_host(store, f"/com/foo/b{i}", f"10.3.0.{i + 1}")
            assert pc.shed > 0
            while pc._pending:
                await asyncio.sleep(0)
            # a fresh mutation of a (possibly shed) name re-renders it
            # normally once the storm is over and the bound is back
            pc.MAX_PENDING = type(pc).MAX_PENDING
            pc.MAX_PENDING_CAP = type(pc).MAX_PENDING_CAP
            ask(server, "b9.foo.com", Type.A, qid=90)   # evidence again
            put_host(store, "/com/foo/b9", "10.2.9.9")
            while pc._pending:
                await asyncio.sleep(0)
            forbid_engine(server)
            r, _, q = ask(server, "b9.foo.com", Type.A, qid=91)
            assert [a.address for a in r.answers] == ["10.2.9.9"]
            assert q.log_ctx.get("precompiled") is True

        asyncio.run(run())


class TestNegativeCaching:
    def count_engine(self, server):
        calls = {"n": 0}
        inner = server.resolver.handle

        def counting(query):
            calls["n"] += 1
            return inner(query)
        server.resolver.handle = counting
        return calls

    def test_nxdomain_cached_with_accounting(self):
        store, cache, server = build(precompile=False)
        put_service(store)
        calls = self.count_engine(server)
        r, _, _q = ask(server, "_http._tcp.svc.foo.com", Type.SRV,
                       qid=1)
        assert r.rcode == Rcode.NXDOMAIN
        r, _, q = ask(server, "_http._tcp.svc.foo.com", Type.SRV, qid=2)
        assert r.rcode == Rcode.NXDOMAIN
        assert calls["n"] == 1, "repeat negative must not hit the engine"
        assert server.answer_cache.stats()["neg_hits"] == 1

    def test_nodata_cached(self):
        store, cache, server = build(precompile=False)
        put_host(store, "/com/foo/web", "10.1.2.3")
        calls = self.count_engine(server)
        for qid in (1, 2):
            r, _, _q = ask(server, "_pg._tcp.web.foo.com", Type.SRV,
                           qid=qid)
            assert r.rcode == Rcode.NOERROR and not r.answers
            assert r.authorities
        assert calls["n"] == 1

    def test_negative_invalidated_by_its_tag(self):
        store, cache, server = build(precompile=False)
        put_service(store)
        r, _, _q = ask(server, "_http._tcp.svc.foo.com", Type.SRV,
                       qid=1)
        assert r.rcode == Rcode.NXDOMAIN
        # the service re-registers under the asked name: the cached
        # negative must die with its dependency tag
        store.put_json(SVC, {"type": "service",
                             "service": {"srvce": "_http",
                                         "proto": "_tcp", "port": 80}})
        r, _, _q = ask(server, "_http._tcp.svc.foo.com", Type.SRV,
                       qid=2)
        assert r.rcode == Rcode.NOERROR and r.answers

    def test_servfail_never_cached(self):
        store, cache, server = build(precompile=False)
        store.put_json("/com/foo/junk", {"type": "host"})
        calls = self.count_engine(server)
        for qid in (1, 2, 3):
            r, _, _q = ask(server, "junk.foo.com", Type.A, qid=qid)
            assert r.rcode == Rcode.SERVFAIL
        assert calls["n"] == 3, "every SERVFAIL must re-check the store"


class TestMetrics:
    def test_precompile_exposition_validates(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        ask(server, "web.foo.com", Type.A)
        text = server.collector.expose()
        assert validate_precompile_metrics(text) == []
        assert "binder_precompile_compiled" in text
        assert "binder_precompile_serves" in text

    def test_validator_rejects_missing_family(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        text = server.collector.expose()
        broken = "\n".join(
            ln for ln in text.splitlines()
            if "binder_precompile_shed" not in ln) + "\n"
        assert any("binder_precompile_shed" in e
                   for e in validate_precompile_metrics(broken))

    def test_introspect_section(self):
        store, cache, server = build()
        put_host(store, "/com/foo/web", "10.1.2.3")
        server._precompiler.seed_mirror()
        pc = server._precompiler.introspect()
        assert pc["compiled"] >= 1
        assert pc["queue_depth"] == 0
        assert pc["max_pending"] > 0


class TestSessionFlapSoak:
    """ZK session *flapping* (ISSUE 4 satellite): rapid
    connected -> degraded -> connected cycles while names churn must
    not leak precompile work — every cycle's queue drains back to
    empty, shed work is bounded by MAX_PENDING, and the compiled table
    still serves the final state."""

    def test_flap_cycles_leave_no_queue_leak(self):
        async def run():
            store, cache, server = build()
            pc = server._precompiler
            for i in range(12):
                put_host(store, f"/com/foo/f{i}", f"10.4.0.{i + 1}")
                ask(server, f"f{i}.foo.com", Type.A, qid=i + 1)
            await asyncio.sleep(0)
            for cycle in range(8):
                store.lose_session()
                # mutations while dark are not mirrored (no watch
                # events) — nothing may enqueue
                depth_dark = len(pc._pending)
                store.start_session()     # rebind storms the watchers
                for i in range(12):
                    put_host(store, f"/com/foo/f{i}",
                             f"10.5.{cycle}.{i + 1}")
                assert len(pc._pending) <= pc.MAX_PENDING
                # drain completely between flaps: a leak would show as
                # monotonic queue growth across cycles
                for _ in range(1000):
                    if not pc._pending:
                        break
                    await asyncio.sleep(0)
                assert not pc._pending, \
                    f"queue leaked {len(pc._pending)} items " \
                    f"(cycle {cycle}, dark depth {depth_dark})"
            # post-flap: the final addresses serve (precompiled or
            # lazily — correctness first), and the queue is at rest
            r, _, q = ask(server, "f11.foo.com", Type.A, qid=99)
            assert r.rcode == Rcode.NOERROR
            assert [a.address for a in r.answers] == ["10.5.7.12"]
            assert pc.introspect()["queue_depth"] == 0

        asyncio.run(run())

    def test_flap_with_expire_session_keeps_read_your_writes(self):
        async def run():
            store, cache, server = build()
            pc = server._precompiler
            put_host(store, "/com/foo/flap", "10.6.0.1")
            ask(server, "flap.foo.com", Type.A, qid=1)
            for cycle in range(6):
                store.expire_session()   # loss + immediate re-establish
                put_host(store, "/com/foo/flap", f"10.6.0.{cycle + 2}")
                for _ in range(1000):
                    if not pc._pending:
                        break
                    await asyncio.sleep(0)
                r, _, _q = ask(server, "flap.foo.com", Type.A,
                               qid=cycle + 10)
                assert [a.address for a in r.answers] \
                    == [f"10.6.0.{cycle + 2}"]
            assert not pc._pending

        asyncio.run(run())
