"""Store-layer observability: Gauge collector + mirror/ZK-client metrics.

The reference gets its store-client metrics by passing the shared artedi
collector into zkstream (``lib/zk.js:26-38``); these tests pin the
rebuild's equivalents — mirror watch/rebuild counters, structural gauges
sampled at scrape time, and the ZooKeeper client's session/request
counters over the real wire protocol.
"""
import asyncio

from binder_tpu.metrics.collector import Gauge, MetricsCollector
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


class TestGauge:
    def test_set_and_expose(self):
        g = Gauge("g_test", "help text")
        g.set(3.5)
        g.set(7, labels={"kind": "b"})
        text = g.expose()
        assert "# TYPE g_test gauge" in text
        assert "g_test 3.5" in text
        assert 'g_test{kind="b"} 7' in text

    def test_function_sampled_at_scrape(self):
        vals = [1]
        g = Gauge("g_fn", "")
        g.set_function(lambda: vals[0])
        assert "g_fn 1" in g.expose()
        vals[0] = 42
        assert "g_fn 42" in g.expose()
        assert g.value() == 42.0

    def test_bad_sampler_does_not_break_scrape(self):
        g = Gauge("g_bad", "")
        g.set(5, labels={"ok": "y"})
        g.set_function(lambda: 1 / 0, labels={"ok": "n"})
        text = g.expose()
        assert 'g_bad{ok="y"} 5' in text
        assert '{ok="n"}' not in text

    def test_collector_registry(self):
        c = MetricsCollector()
        g = c.gauge("g_reg", "h")
        assert c.gauge("g_reg") is g
        g.set(1)
        assert "g_reg 1" in c.expose()


def mirror_with_collector():
    collector = MetricsCollector()
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN, collector=collector)
    return store, cache, collector


class TestMirrorMetrics:
    def test_counters_and_gauges_track_mutations(self):
        store, cache, collector = mirror_with_collector()
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.1"}})
        store.start_session()

        text = collector.expose()
        assert "binder_store_session_rebuilds 1" in text
        # root foo.com + web.foo.com
        assert collector.get("binder_store_mirrored_nodes").value() == 2
        assert "binder_store_reverse_entries 1" in text
        assert "binder_store_ready 1" in text
        assert 'binder_store_watch_events{kind="children"}' in text
        assert 'binder_store_watch_events{kind="data"}' in text

        events_before = collector.get(
            "binder_store_watch_events").value({"kind": "data"})
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.2"}})
        assert collector.get("binder_store_watch_events").value(
            {"kind": "data"}) > events_before

    def test_parse_failure_counter(self):
        store, cache, collector = mirror_with_collector()
        store.start_session()
        store.mkdirp("/com/foo/bad", b"{not json")
        assert collector.get(
            "binder_store_node_parse_failures").value() == 1

    def test_not_ready_gauge_before_session(self):
        _, cache, collector = mirror_with_collector()
        assert "binder_store_ready 0" in collector.expose()

    def test_bare_cache_needs_no_collector(self):
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        store.put_json("/com/foo/web",
                       {"type": "host", "host": {"address": "10.0.0.1"}})
        store.start_session()
        assert cache.is_ready()


class TestZKClientMetrics:
    def test_session_and_request_counters(self):
        from binder_tpu.store.zk_client import ZKClient
        from binder_tpu.store.zk_testserver import ZKTestServer

        async def run():
            server = ZKTestServer()
            await server.start()
            collector = MetricsCollector()
            client = ZKClient(address="127.0.0.1", port=server.port,
                              session_timeout_ms=2000,
                              collector=collector)
            cache = MirrorCache(client, DOMAIN, collector=collector)
            client.start()
            try:
                deadline = asyncio.get_running_loop().time() + 5
                while (asyncio.get_running_loop().time() < deadline
                       and not client.is_connected()):
                    await asyncio.sleep(0.02)
                assert client.is_connected()
                import json as _json
                await client.mkdirp(
                    "/com/foo/web",
                    _json.dumps({"type": "host",
                                 "host": {"address": "10.1.1.1"}}).encode())
                deadline = asyncio.get_running_loop().time() + 5
                while (asyncio.get_running_loop().time() < deadline
                       and cache.lookup("web.foo.com") is None):
                    await asyncio.sleep(0.02)
                assert cache.lookup("web.foo.com") is not None

                text = collector.expose()
                assert "binder_zk_sessions_established 1" in text
                assert "binder_zk_connected 1" in text
                assert collector.get("binder_zk_requests").value() > 0
                assert collector.get(
                    "binder_zk_watch_notifications").value() > 0
            finally:
                client.close()
                await server.stop()
        asyncio.run(run())
