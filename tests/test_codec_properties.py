"""Property-based tests for the other two hand-rolled codecs:

- jute (binder_tpu/store/jute.py) — the ZooKeeper wire primitives the
  client, test server, and zlogcat all build on;
- BER (binder_tpu/recursion/ber.py) — the LDAPv3 substrate that parses
  untrusted directory responses in the UFDS client.

Same contract style as test_wire_properties.py: round-trips hold over
the whole representable space, and decoding arbitrary bytes only ever
raises the codec's own error type.
"""
import struct

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from binder_tpu.recursion import ber
from binder_tpu.store import jute
from binder_tpu.store.jute import Buf

i32s = st.integers(min_value=-2**31, max_value=2**31 - 1)
i64s = st.integers(min_value=-2**63, max_value=2**63 - 1)
blobs = st.binary(max_size=200)
texts = st.text(max_size=100)


class TestJute:
    @settings(max_examples=300, deadline=None)
    @given(i32s, i64s, st.booleans(), blobs, texts)
    def test_primitive_round_trip(self, a, b, flag, blob, s):
        wire = (jute.i32(a) + jute.i64(b) + jute.boolean(flag)
                + jute.buffer(blob) + jute.string(s))
        buf = Buf(wire)
        assert buf.i32() == a
        assert buf.i64() == b
        assert buf.boolean() == flag
        assert buf.buffer() == blob
        assert buf.string() == s

    @settings(max_examples=200, deadline=None)
    @given(blobs)
    def test_frame_is_length_prefixed(self, payload):
        f = jute.frame(payload)
        (length,) = struct.unpack(">i", f[:4])
        assert length == len(payload)
        assert f[4:] == payload

    @settings(max_examples=200, deadline=None)
    @given(st.integers(0, 2**31 - 1), st.integers(0, 2**31 - 1),
           st.integers(0, 1000), st.integers(0, 1000))
    def test_stat_round_trip(self, czxid, mzxid, version, cversion):
        wire = jute.pack_stat(czxid=czxid, mzxid=mzxid, version=version,
                              cversion=cversion)
        stat = jute.read_stat(Buf(wire))
        assert stat["czxid"] == czxid
        assert stat["version"] == version
        assert stat["cversion"] == cversion

    @settings(max_examples=500, deadline=None)
    @given(st.binary(max_size=64))
    def test_buf_reads_never_raise_anything_else(self, data):
        """Truncated/garbage buffers raise the Buf's own error type
        (whatever the reads use — ValueError/IndexError/struct.error are
        all caught by the client's session loop), never hang."""
        for read in ("i32", "i64", "boolean", "buffer", "string"):
            buf = Buf(data)
            try:
                getattr(buf, read)()
            except Exception as e:  # noqa: BLE001 — asserting the type set
                assert isinstance(
                    e, (ValueError, IndexError, struct.error)), e


ber_values = st.recursive(
    st.one_of(
        st.tuples(st.just("int"), st.integers(-2**31, 2**31 - 1)),
        st.tuples(st.just("str"), st.text(max_size=50)),
        st.tuples(st.just("bool"), st.booleans()),
    ),
    lambda children: st.tuples(st.just("seq"),
                               st.lists(children, max_size=4)),
    max_leaves=10,
)


def ber_encode(value):
    kind, v = value
    if kind == "int":
        return ber.encode_int(v)
    if kind == "str":
        return ber.encode_str(v)
    if kind == "bool":
        return ber.encode_bool(v)
    return ber.encode_seq([ber_encode(x) for x in v])


def ber_check(value, tag, content):
    kind, v = value
    if kind == "int":
        assert tag == ber.INTEGER
        assert ber.decode_int(content) == v
    elif kind == "str":
        assert tag == ber.OCTET_STRING
        assert content == v.encode("utf-8")
    elif kind == "bool":
        assert tag == ber.BOOLEAN
        assert content == (b"\xff" if v else b"\x00")   # DER canonical
    else:
        assert tag == ber.SEQUENCE
        parts = ber.decode_all(content)
        assert len(parts) == len(v)
        for sub, (stag, scontent) in zip(v, parts):
            ber_check(sub, stag, scontent)


class TestBer:
    @settings(max_examples=300, deadline=None)
    @given(ber_values)
    def test_round_trip(self, value):
        wire = ber_encode(value)
        tag, content, end = ber.decode_tlv(wire)
        assert end == len(wire)
        ber_check(value, tag, content)

    @settings(max_examples=1000, deadline=None)
    @given(st.binary(max_size=300))
    def test_decode_never_raises_anything_but_bererror(self, data):
        try:
            ber.decode_tlv(data)
        except ber.BerError:
            pass
        try:
            ber.decode_all(data)
        except ber.BerError:
            pass
        try:
            ber.frame_length(data)
        except ber.BerError:
            pass

    @settings(max_examples=200, deadline=None)
    @given(ber_values)
    def test_frame_length_matches_encoding(self, value):
        wire = ber_encode(value)
        assert ber.frame_length(wire) == len(wire)
