"""Resolution-engine tests.

Mirrors the reference's integration suites case-for-case (SURVEY §4):
test/host.test.js, test/service.test.js, test/database.test.js — plus the
rcode-policy and TTL-precedence cases the reference never unit-tests.
Responses are asserted on decoded wire bytes, not internal objects.
"""
import asyncio

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.query import QueryCtx
from binder_tpu.resolver import Resolver
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"
DC = "coal"


@pytest.fixture()
def stack():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    resolver = Resolver(cache, dns_domain=DOMAIN, datacenter_name=DC)
    store.start_session()
    return store, cache, resolver


def ask(resolver, name, qtype, rd=False):
    """Run one query through the engine; return the decoded wire response."""
    sent = []
    q = QueryCtx(make_query(name, qtype, qid=99, rd=rd), ("127.0.0.1", 5353),
                 "udp", sent.append)
    pending = resolver.handle(q)
    if pending is not None:  # recursion path returns an awaitable
        asyncio.run(pending)
    assert len(sent) == 1, "engine must respond exactly once"
    return Message.decode(sent[0])


def put_host(store, path, addr, **extra):
    rec = {"type": "host", "host": {"address": addr}}
    rec.update(extra)
    store.put_json(path, rec)


class TestHost:
    """Reference test/host.test.js."""

    def test_a_lookup(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        r = ask(resolver, "web.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR and r.aa
        assert [a.address for a in r.answers] == ["192.168.0.1"]
        assert r.answers[0].ttl == 30  # default

    def test_ptr_lookup(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        r = ask(resolver, "1.0.168.192.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].target == "web.foo.com"

    def test_unknown_name_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "nope.foo.com", Type.A)
        assert r.rcode == Rcode.REFUSED and not r.answers

    def test_unknown_reverse_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "9.9.9.9.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.REFUSED

    def test_partial_reverse_refused(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        r = ask(resolver, "0.168.192.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.REFUSED

    def test_non_reverse_ptr_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "web.foo.com", Type.PTR)
        assert r.rcode == Rcode.REFUSED

    def test_ipv6_reverse_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "1.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0.0."
                          "0.0.0.0.0.0.d.f.ip6.arpa", Type.PTR)
        assert r.rcode == Rcode.REFUSED


SVC = "/com/foo/svc"


def put_service(store, port=5432, srvce="_pg", proto="_tcp", **svc_extra):
    svc = {"srvce": srvce, "proto": proto, "port": port}
    svc.update(svc_extra)
    store.put_json(SVC, {"type": "service", "service": svc})


def put_members(store):
    """3 hosts + 2 load_balancers, as in test/service.test.js."""
    for i in range(3):
        store.put_json(f"{SVC}/host{i}",
                       {"type": "host",
                        "host": {"address": f"10.0.0.{i + 1}"}})
    for i in range(2):
        store.put_json(f"{SVC}/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})


class TestService:
    """Reference test/service.test.js."""

    def test_round_robin_a_only_lb_children(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "svc.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR
        # only load_balancer-type children are served (lib/server.js:352-360)
        assert sorted(a.address for a in r.answers) == ["10.0.1.1", "10.0.1.2"]

    def test_a_answers_shuffled(self, stack):
        store, cache, resolver = stack
        put_service(store)
        for i in range(8):
            store.put_json(f"{SVC}/lb{i}",
                           {"type": "load_balancer",
                            "load_balancer": {"address": f"10.0.1.{i + 1}"}})
        orders = {tuple(a.address for a in
                        ask(resolver, "svc.foo.com", Type.A).answers)
                  for _ in range(20)}
        assert len(orders) > 1, "answers must be shuffled for round-robin"

    def test_srv_answers(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "_pg._tcp.svc.foo.com", Type.SRV)
        assert r.rcode == Rcode.NOERROR
        assert len(r.answers) == 2
        assert all(a.port == 5432 for a in r.answers)
        assert sorted(a.target for a in r.answers) == \
            ["lb0.svc.foo.com", "lb1.svc.foo.com"]
        # additionals carry the A records for the SRV targets
        addl = {a.name: a.address for a in r.additionals
                if hasattr(a, "address")}
        assert addl == {"lb0.svc.foo.com": "10.0.1.1",
                        "lb1.svc.foo.com": "10.0.1.2"}

    def test_srv_wrong_service_nxdomain(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "_http._tcp.svc.foo.com", Type.SRV)
        assert r.rcode == Rcode.NXDOMAIN

    def test_srv_wrong_proto_nxdomain(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "_pg._udp.svc.foo.com", Type.SRV)
        assert r.rcode == Rcode.NXDOMAIN

    def test_srv_unknown_name_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "_pg._tcp.other.foo.com", Type.SRV)
        assert r.rcode == Rcode.REFUSED

    def test_srv_invalid_shape_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "svc.foo.com", Type.SRV)
        assert r.rcode == Rcode.REFUSED

    def test_member_a_record(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "host1.svc.foo.com", Type.A)
        assert [a.address for a in r.answers] == ["10.0.0.2"]

    def test_member_ptr(self, stack):
        store, cache, resolver = stack
        put_service(store)
        put_members(store)
        r = ask(resolver, "2.1.0.10.in-addr.arpa", Type.PTR)
        assert r.answers[0].target == "lb1.svc.foo.com"

    def test_empty_service_noerror(self, stack):
        store, cache, resolver = stack
        put_service(store)
        r = ask(resolver, "svc.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR and not r.answers

    def test_srv_on_host_nodata_with_soa(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1", ttl=77)
        r = ask(resolver, "_pg._tcp.web.foo.com", Type.SRV)
        assert r.rcode == Rcode.NOERROR and not r.answers
        soa = r.authorities[0]
        assert soa.mname == DOMAIN and soa.minimum == 77 and soa.ttl == 77

    def test_member_with_null_address_skipped(self, stack):
        store, cache, resolver = stack
        put_service(store)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer",
                        "load_balancer": {"address": None}})
        store.put_json(f"{SVC}/lb1",
                       {"type": "load_balancer",
                        "load_balancer": {"address": "10.0.1.2"}})
        r = ask(resolver, "svc.foo.com", Type.A)
        assert [a.address for a in r.answers] == ["10.0.1.2"]

    def test_member_ports_list_multiple_srv(self, stack):
        store, cache, resolver = stack
        put_service(store)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer",
                        "load_balancer": {"address": "10.0.1.1",
                                          "ports": [80, 443]}})
        r = ask(resolver, "_pg._tcp.svc.foo.com", Type.SRV)
        assert sorted(a.port for a in r.answers) == [80, 443]

    def test_bad_member_record_servfail(self, stack):
        store, cache, resolver = stack
        put_service(store)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer", "load_balancer": None})
        r = ask(resolver, "svc.foo.com", Type.A)
        assert r.rcode == Rcode.SERVFAIL


class TestDatabase:
    """Reference test/database.test.js."""

    def test_a_from_primary_url(self, stack):
        store, cache, resolver = stack
        store.put_json("/com/foo/pg", {
            "type": "database",
            "database": {"primary": "tcp://10.99.99.14:5432/postgres"},
        })
        r = ask(resolver, "pg.foo.com", Type.A)
        assert [a.address for a in r.answers] == ["10.99.99.14"]


class TestTTLPrecedence:
    """The three-level TTL mess (SURVEY §7.3, lib/server.js:262-274)."""

    def test_default_30(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/w", "10.1.1.1")
        assert ask(resolver, "w.foo.com", Type.A).answers[0].ttl == 30

    def test_root_ttl(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/w", "10.1.1.1", ttl=120)
        assert ask(resolver, "w.foo.com", Type.A).answers[0].ttl == 120

    def test_sub_ttl_wins(self, stack):
        store, cache, resolver = stack
        store.put_json("/com/foo/w", {
            "type": "host", "ttl": 120,
            "host": {"address": "10.1.1.1", "ttl": 5}})
        assert ask(resolver, "w.foo.com", Type.A).answers[0].ttl == 5

    def test_nested_service_service_ttl(self, stack):
        store, cache, resolver = stack
        store.put_json(SVC, {
            "type": "service",
            "service": {"service": {"srvce": "_pg", "proto": "_tcp",
                                    "port": 5432, "ttl": 11}}})
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer",
                        "load_balancer": {"address": "10.0.1.1"}})
        r = ask(resolver, "_pg._tcp.svc.foo.com", Type.SRV)
        assert r.answers[0].ttl == 11

    def test_service_a_uses_min_ttl(self, stack):
        store, cache, resolver = stack
        put_service(store, ttl=100)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer", "ttl": 7,
                        "load_balancer": {"address": "10.0.1.1"}})
        # membership TTL (100) vs member TTL (7): serve the smaller
        assert ask(resolver, "svc.foo.com", Type.A).answers[0].ttl == 7

    def test_srv_additional_uses_member_ttl(self, stack):
        store, cache, resolver = stack
        put_service(store, ttl=100)
        store.put_json(f"{SVC}/lb0",
                       {"type": "load_balancer", "ttl": 7,
                        "load_balancer": {"address": "10.0.1.1"}})
        r = ask(resolver, "_pg._tcp.svc.foo.com", Type.SRV)
        assert r.answers[0].ttl == 100       # SRV carries service ttl
        assert r.additionals[-1].ttl == 7    # A additional carries member ttl


class TestPolicy:
    """Failover-oriented rcode policy (lib/server.js:156-246)."""

    def test_outside_domain_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "example.com", Type.A)
        assert r.rcode == Rcode.REFUSED

    def test_doubled_suffix_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "web.foo.com.foo.com", Type.A)
        assert r.rcode == Rcode.REFUSED

    def test_dc_doubled_suffix_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, f"web.foo.com.{DC}.foo.com", Type.A)
        assert r.rcode == Rcode.REFUSED

    def test_store_down_servfail(self):
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        resolver = Resolver(cache, dns_domain=DOMAIN, datacenter_name=DC)
        # no session started
        r = ask(resolver, "web.foo.com", Type.A)
        assert r.rcode == Rcode.SERVFAIL

    def test_store_down_ptr_servfail(self):
        store = FakeStore()
        cache = MirrorCache(store, DOMAIN)
        resolver = Resolver(cache, dns_domain=DOMAIN, datacenter_name=DC)
        r = ask(resolver, "1.0.168.192.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.SERVFAIL

    def test_invalid_chars_refused(self, stack):
        store, cache, resolver = stack
        r = ask(resolver, "bad!name.foo.com", Type.A)
        assert r.rcode == Rcode.REFUSED

    def test_unsupported_qtype_notimp(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        r = ask(resolver, "web.foo.com", Type.AAAA)
        assert r.rcode == Rcode.NOTIMP

    def test_invalid_record_servfail(self, stack):
        store, cache, resolver = stack
        store.put_json("/com/foo/junk", {"type": "host"})  # no sub-object
        r = ask(resolver, "junk.foo.com", Type.A)
        assert r.rcode == Rcode.SERVFAIL

    def test_node_without_data_servfail(self, stack):
        store, cache, resolver = stack
        store.mkdirp("/com/foo/empty")
        r = ask(resolver, "empty.foo.com", Type.A)
        assert r.rcode == Rcode.SERVFAIL

    def test_unknown_record_type_empty_noerror(self, stack):
        store, cache, resolver = stack
        store.put_json("/com/foo/odd", {"type": "widget", "widget": {}})
        r = ask(resolver, "odd.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR and not r.answers

    def test_case_insensitive_lookup(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        r = ask(resolver, "WEB.Foo.COM", Type.A)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "192.168.0.1"


class TestReviewRegressions:
    """Regressions from the second code-review pass."""

    def test_suffix_check_respects_label_boundary(self, stack):
        """'xfoo.com' merely string-ending with 'foo.com' must not trip
        the doubled-suffix REFUSED."""
        store, cache, resolver = stack
        store.put_json("/com/foo/com/xfoo",
                       {"type": "host", "host": {"address": "10.5.5.5"}})
        r = ask(resolver, "xfoo.com.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "10.5.5.5"

    def test_ptr_survives_typeless_record(self, stack):
        store, cache, resolver = stack
        put_host(store, "/com/foo/web", "192.168.0.1")
        # rewrite with no 'type': reverse entry must drop, PTR -> REFUSED
        store.put_json("/com/foo/web", {"mystery": True})
        r = ask(resolver, "1.0.168.192.in-addr.arpa", Type.PTR)
        assert r.rcode == Rcode.REFUSED
