"""Shard-mode tier-1 suite: supervisor lifecycle + answer parity.

What ISSUE 6 pins here:

- ``--shards N`` serves correct answers from N distinct PIDs behind
  ONE kernel-balanced UDP port, with exactly one store session total
  (the supervisor's) — workers run ``ReplicaStore`` and never touch
  the store;
- crashed-shard respawn with snapshot catch-up: a SIGKILLed worker is
  respawned by the supervisor and converges on mutations that landed
  while it was dead;
- SIGTERM drain leaves no orphan worker PIDs;
- answer byte-parity (modulo ID) between N=1 and N=4 across the
  record shapes (host A, PTR, REFUSED policy, rotated service sets);
- the ``binder_shard_*`` exposition passes
  ``tools/lint.py validate_shard_metrics`` (this is the family's
  tier-1 wiring, like the tcp/precompile validators);
- the chaos DSL's ``shard-kill`` action parses and dispatches to the
  driver's ``shard_target``.

The suite boots REAL worker subprocesses (``python -m binder_tpu.main
--shard-worker``) under an in-process supervisor, so what is tested is
the production process topology, not a simulation.
"""
import asyncio
import json
import os
import socket
import sys
import tempfile
import time
import urllib.request

import pytest

from binder_tpu.chaos import ChaosDriver, FaultPlan
from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.main import run as binder_run
from tools.lint import validate_shard_metrics

DOMAIN = "shard.test"

FIXTURE = {
    **{f"/test/shard/w{i}":
       {"type": "host", "host": {"address": f"10.50.0.{i + 1}"}}
       for i in range(4)},
    "/test/shard/svc": {
        "type": "service",
        "service": {"srvce": "_http", "proto": "_tcp", "port": 8080}},
    **{f"/test/shard/svc/m{i}":
       {"type": "load_balancer",
        "load_balancer": {"address": f"10.50.1.{i + 1}"}}
       for i in range(3)},
}

#: the parity shapes: single-answer wires must be byte-identical
#: modulo ID; rotated sets compare as sorted answer summaries
SINGLE_ANSWER_QUERIES = [
    ("w0.shard.test", Type.A),           # host A
    ("w3.shard.test", Type.A),
    ("1.0.50.10.in-addr.arpa", Type.PTR),  # reverse
    ("nosuch.shard.test", Type.A),       # miss -> REFUSED policy
    ("w0.other.test", Type.A),           # out-of-suffix -> REFUSED
    ("w0.shard.test", Type.TXT),         # NODATA shape
]
ROTATED_QUERIES = [
    ("svc.shard.test", Type.A),
    ("_http._tcp.svc.shard.test", Type.SRV),
]


async def boot(tmpdir: str, shards: int):
    """Boot a shard supervisor (fake owner store + fixture) with REAL
    worker subprocesses; returns the supervisor."""
    fixture = os.path.join(tmpdir, "fixture.json")
    with open(fixture, "w") as f:
        json.dump(FIXTURE, f)
    options = {
        "dnsDomain": DOMAIN, "datacenterName": "dc0",
        "host": "127.0.0.1", "port": 0, "queryLog": False,
        "expiry": 60000, "size": 10000,
        "store": {"backend": "fake", "fixture": fixture},
        "shards": shards,
    }
    return await binder_run(options)


async def ask_fresh(port: int, name: str, qtype: int, qid: int,
                    timeout: float = 3.0) -> bytes:
    """One query on a fresh socket — a new source port, so the
    reuseport hash gets a fresh draw across the worker group."""
    loop = asyncio.get_running_loop()
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    sock.connect(("127.0.0.1", port))
    try:
        for _ in range(3):
            sock.send(make_query(name, qtype, qid=qid).encode())
            try:
                return await asyncio.wait_for(
                    loop.sock_recv(sock, 4096), timeout)
            except asyncio.TimeoutError:
                continue
        raise AssertionError(f"no answer for {name} in 3 tries")
    finally:
        sock.close()


async def wait_for(predicate, timeout: float = 10.0, what: str = ""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what or predicate}")


def worker_status(sup, shard: int) -> dict:
    mport = sup.links[shard].hello["metrics_port"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/status", timeout=5) as r:
        return json.loads(r.read())


async def collect_answers(port: int, samples: int = 18):
    """Normalized answer shapes over many fresh sockets (both parity
    sides sample the same way)."""
    singles = {}
    for name, qtype in SINGLE_ANSWER_QUERIES:
        wires = set()
        for s in range(6):
            data = await ask_fresh(port, name, qtype,
                                   qid=(hash((name, s)) & 0x7FFF) + 1)
            wires.add(b"\x00\x00" + data[2:])   # modulo ID
        singles[(name, qtype)] = wires
    rotated = {}
    for name, qtype in ROTATED_QUERIES:
        shapes = set()
        for s in range(samples):
            data = await ask_fresh(port, name, qtype, qid=s + 1)
            msg = Message.decode(data)
            shapes.add((msg.rcode,
                        tuple(sorted(str(a) for a in msg.answers)),
                        len(msg.answers)))
        rotated[(name, qtype)] = shapes
    return singles, rotated


class TestShardServing:
    def test_two_pids_one_port_one_session(self, tmp_path):
        async def run():
            sup = await boot(str(tmp_path), 2)
            try:
                port = sup.udp_port
                # correct answers over many fresh flows on ONE port
                for s in range(24):
                    data = await ask_fresh(port, f"w{s % 4}.{DOMAIN}",
                                           Type.A, qid=s + 1)
                    msg = Message.decode(data)
                    assert msg.rcode == Rcode.NOERROR
                    assert msg.answers[0].address == \
                        f"10.50.0.{s % 4 + 1}"
                # N distinct worker PIDs, none of them the supervisor
                pids = {sup._pid(i) for i in range(2)}
                assert len(pids) == 2
                assert os.getpid() not in pids
                # exactly ONE store session in the whole topology: the
                # supervisor's; workers run ReplicaStore (no store
                # client at all) off the one mutation log
                assert sup.store.session_establishments == 1
                for i in range(2):
                    snap = worker_status(sup, i)
                    assert snap["store"]["backend"] == "ReplicaStore"
                    assert snap["service"]["pid"] == sup._pid(i)
                    assert snap["mirror"]["ready"] is True
                # every shard answered (the kernel spread the flows):
                # per-shard requests fold comes from 1 Hz stats frames
                await wait_for(
                    lambda: all(sup._requests_total.get(i, 0) > 0
                                for i in range(2)),
                    timeout=10, what="per-shard request folds")
            finally:
                await sup.drain()

        asyncio.run(run())

    def test_shard_metrics_exposition(self, tmp_path):
        """Tier-1 wiring for tools/lint.py validate_shard_metrics: the
        live supervisor's scrape passes, and the validator actually
        detects a broken exposition (a family with no samples)."""
        async def run():
            sup = await boot(str(tmp_path), 2)
            try:
                text = sup.collector.expose()
                assert validate_shard_metrics(text) == []
                broken = "\n".join(
                    line for line in text.splitlines()
                    if not line.startswith("binder_shard_up"))
                errs = validate_shard_metrics(broken)
                assert any("binder_shard_up" in e for e in errs)
                # per-shard series must carry the shard label
                unlabeled = text.replace('shard="0"', 'notshard="0"')
                errs = validate_shard_metrics(unlabeled)
                assert any("shard" in e and "label" in e for e in errs)
                # the supervisor snapshot names every worker
                snap = sup.snapshot()
                assert snap["shards"]["count"] == 2
                assert len(snap["shards"]["workers"]) == 2
                assert all(w["pid"] for w in snap["shards"]["workers"])
            finally:
                await sup.drain()

        asyncio.run(run())


class TestShardLifecycle:
    def test_respawn_with_snapshot_catchup(self, tmp_path):
        async def run():
            sup = await boot(str(tmp_path), 2)
            try:
                port = sup.udp_port
                pid0 = sup._pid(0)
                gen_before = sup.cache.gen
                assert sup.kill_shard(0) == pid0
                # supervisor respawns with a fresh incarnation
                await wait_for(
                    lambda: sup._pid(0) not in (None, pid0)
                    and sup.links[0].hello is not None,
                    timeout=15, what="shard respawn")
                assert sup.respawns[0] == 1
                # a mutation AFTER the crash: the respawned worker's
                # snapshot predates it, so convergence proves the
                # delta feed re-attached, not just the snapshot
                sup.store.put_json(
                    "/test/shard/w0",
                    {"type": "host", "host": {"address": "10.50.9.9"}})
                assert sup.cache.gen > gen_before   # owner monotonic

                async def all_converged():
                    for s in range(12):
                        data = await ask_fresh(port, f"w0.{DOMAIN}",
                                               Type.A, qid=500 + s)
                        msg = Message.decode(data)
                        if not msg.answers or \
                                msg.answers[0].address != "10.50.9.9":
                            return False
                    return True

                deadline = time.monotonic() + 10
                while not await all_converged():
                    assert time.monotonic() < deadline, \
                        "respawned group never converged on the " \
                        "post-crash mutation"
                    await asyncio.sleep(0.2)
            finally:
                await sup.drain()

        asyncio.run(run())

    def test_sigterm_drain_leaves_no_orphans(self, tmp_path):
        async def run():
            sup = await boot(str(tmp_path), 2)
            pids = [sup._pid(i) for i in range(2)]
            procs = [sup.links[i].proc for i in range(2)]
            await sup.drain()
            # every worker exited AND was reaped (no zombies: poll()
            # returns the code only after a successful waitpid)
            for proc in procs:
                assert proc.poll() is not None
            for pid in pids:
                with pytest.raises(ProcessLookupError):
                    os.kill(pid, 0)
            # drain is terminal: nothing respawns afterwards
            await asyncio.sleep(1.2)
            assert not sup.links

        asyncio.run(run())


class TestShardParity:
    def test_answers_identical_n1_vs_n4(self, tmp_path):
        """Byte parity (modulo ID) between N=1 and N=4 for the
        single-answer shapes, set parity for the rotated service
        shapes — N processes must be indistinguishable from one."""
        async def run():
            with tempfile.TemporaryDirectory() as d1:
                sup = await boot(d1, 1)
                try:
                    singles1, rotated1 = await collect_answers(
                        sup.udp_port)
                finally:
                    await sup.drain()
            with tempfile.TemporaryDirectory() as d4:
                sup = await boot(d4, 4)
                try:
                    assert len({sup._pid(i) for i in range(4)}) == 4
                    singles4, rotated4 = await collect_answers(
                        sup.udp_port)
                    assert sup.store.session_establishments == 1
                finally:
                    await sup.drain()
            for key in singles1:
                assert singles1[key] == singles4[key], \
                    f"answer wires differ for {key}"
                assert len(singles1[key]) == 1, \
                    f"single-answer shape {key} was not deterministic"
            for key in rotated1:
                assert rotated1[key] == rotated4[key], \
                    f"rotated answer shapes differ for {key}"

        asyncio.run(run())


class _StubProc:
    """Stands in for a worker Popen on an in-process link: alive, no
    PID of interest."""

    pid = 0

    def poll(self):
        return None


class TestLargeSnapshotAttach:
    """ISSUE 7 satellite: shard snapshot attach against a LARGE
    (>=50k-name) mirror.  In-process links (real socketpairs, real
    ReplicaStores, the real chunked pump) instead of worker
    subprocesses, so what is measured is the snapshot protocol at
    scale, not 50k names of process-boot overhead."""

    NAMES = 50000

    def test_50k_snapshot_heartbeats_convergence_parity(self):
        from binder_tpu.metrics.collector import MetricsCollector
        from binder_tpu.resolver.engine import Resolver
        from binder_tpu.resolver.precompile import Precompiler
        from binder_tpu.shard import ReplicaStore
        from binder_tpu.shard.supervisor import ShardLink, ShardSupervisor
        from binder_tpu.store import FakeStore, MirrorCache
        from binder_tpu.store.fake import populate_synthetic

        def render(cache, qname):
            plan = Resolver(cache, dns_domain=DOMAIN).plan(qname, Type.A)
            answers = [r for g in plan.groups for r in g[0]]
            adds = [r for g in plan.groups for r in g[1]]
            return Precompiler._render(qname, Type.A, plan, answers,
                                       adds, False)

        async def run():
            store = FakeStore()
            populate_synthetic(store, DOMAIN, self.NAMES)
            cache = MirrorCache(store, DOMAIN)
            store.start_session()
            n_owner = len(cache.nodes)
            assert n_owner >= self.NAMES

            sup = ShardSupervisor(
                options={"shards": 2, "host": "127.0.0.1", "port": 0,
                         "dnsDomain": DOMAIN},
                store=store, cache=cache,
                collector=MetricsCollector())
            loop = asyncio.get_running_loop()
            sup._loop = loop

            replicas = []
            for i in range(2):
                sup_end, worker_end = socket.socketpair()
                sup_end.setblocking(False)
                link = ShardLink(i, _StubProc(), sup_end)
                sup.links[i] = link
                sup._send_snapshot(link)
                replicas.append(ReplicaStore(worker_end, i))
            # the pump must NOT have materialized the whole zone in the
            # link buffers (chunked streaming, not an eager build)
            assert all(len(lk.wbuf) <= sup.SNAP_HIGH_WATER + (1 << 20)
                       for lk in sup.links.values())

            futs = [loop.run_in_executor(None, r.read_snapshot, 120.0)
                    for r in replicas]
            # heartbeats + a mid-snapshot mutation while the snapshot
            # streams: both must interleave cleanly into the stream
            racks = max(1, min(1024, self.NAMES // 512))
            moved = f"h000123.r{123 % racks:04d}.zs.{DOMAIN}"
            ticks = 0
            mutated = False
            while not all(f.done() for f in futs):
                sup._tick()
                ticks += 1
                if not mutated and ticks >= 2:
                    store.put_json(
                        f"/test/shard/zs/r{123 % racks:04d}/h000123",
                        {"type": "host",
                         "host": {"address": "10.88.88.88"}})
                    mutated = True
                await asyncio.sleep(0.02)
            counts = [await f for f in futs]
            assert all(c == lk.snap_sent for c, lk in
                       zip(counts, sup.links.values()))
            assert mutated and ticks >= 2

            for r, c in zip(replicas, counts):
                # heartbeats kept flowing DURING snapshot streaming:
                # beyond the node frames, the replica applied the
                # leading state frame plus at least one mid-stream
                # heartbeat/delta
                assert r.frames_applied >= c + 2
                assert r.is_connected()

            # convergence: a worker-side mirror over each replica
            # reproduces the owner's view exactly
            mirrors = []
            for r in replicas:
                rc = MirrorCache(r, DOMAIN)
                mirrors.append(rc)
                assert len(rc.nodes) == len(cache.nodes)
                assert len(rc.rev_lookup) == len(cache.rev_lookup)
                assert rc.lookup(moved).data["host"]["address"] \
                    == "10.88.88.88"

            # N=1 vs N=2 byte parity modulo ID: both replicas render
            # byte-identical answers to the owner for sampled names
            # (render IDs are 0 on all sides)
            step = max(1, self.NAMES // 7)
            for i in range(0, self.NAMES, step):
                qname = f"h{i:06d}.r{i % racks:04d}.zs.{DOMAIN}"
                want = render(cache, qname)
                for rc in mirrors:
                    assert render(rc, qname) == want, qname

            for r in replicas:
                r.close()
            for lk in sup.links.values():
                sup._close_link(lk)

        asyncio.run(run())


class TestShardAuto:
    def test_auto_resolves_to_core_count(self):
        from binder_tpu.config.options import parse_options
        from binder_tpu.main import resolve_shard_count
        opts = parse_options(["--shards", "auto", "-f",
                              "etc/config.json"])
        assert opts["shards"] == "auto"
        n = resolve_shard_count(opts)
        assert n == (os.cpu_count() or 1) and n >= 1
        # explicit counts and the unset default pass through untouched
        assert resolve_shard_count({"shards": 3}) == 3
        assert resolve_shard_count({}) == 0


class TestChaosShardKill:
    def test_dsl_parses_and_dispatches(self):
        plan = FaultPlan.parse("at 0.5 shard-kill shard=1\n"
                               "at 1.0 shard-kill")
        assert [(t, a) for t, a, _ in plan.timeline] == \
            [(0.5, "shard-kill"), (1.0, "shard-kill")]
        killed = []
        driver = ChaosDriver(plan, shard_target=killed.append)
        driver.apply("shard-kill", {"shard": 1})
        driver.apply("shard-kill", {})
        assert killed == [1, -1]

    def test_no_target_is_skipped_not_fatal(self):
        driver = ChaosDriver(FaultPlan())
        driver.apply("shard-kill", {"shard": 0})   # must not raise
        assert [a for _, a in driver.applied] == ["shard-kill"]


class TestChaosRollAndFlood:
    """ISSUE 19 satellite: the ``worker-roll`` and ``rrl-flood`` chaos
    actions parse and dispatch (the live end-to-end exercise is
    ``tools/population_smoke.py`` phase B)."""

    def test_worker_roll_parses_and_dispatches(self):
        plan = FaultPlan.parse("at 0.5 worker-roll shard=1\n"
                               "at 1.0 worker-roll")
        assert [(t, a) for t, a, _ in plan.timeline] == \
            [(0.5, "worker-roll"), (1.0, "worker-roll")]
        rolled = []
        driver = ChaosDriver(plan, roll_target=rolled.append)
        driver.apply("worker-roll", {"shard": 1})
        driver.apply("worker-roll", {})
        assert rolled == [1, -1]

    def test_rrl_flood_sends_from_hostile_prefixes(self):
        """rrl-flood binds real sockets in the hostile /24s and fires
        decodable queries at the UDP target — the same source prefixes
        tools/hostile.py floods from, so RRL judges them alike."""
        from binder_tpu.chaos.plan import FLOOD_PREFIXES
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(2.0)
        try:
            port = recv.getsockname()[1]
            driver = ChaosDriver(
                FaultPlan(),
                udp_target=("127.0.0.1", port, f"w0.{DOMAIN}"))
            driver.apply("rrl-flood", {"n": 32})
            srcs, data = set(), b""
            for _ in range(32):
                data, addr = recv.recvfrom(4096)
                srcs.add(addr[0].rsplit(".", 1)[0])
            # flood traffic really arrives FROM the hostile prefixes
            assert srcs <= set(FLOOD_PREFIXES) and len(srcs) >= 2
            msg = Message.decode(data)
            assert msg.questions[0].name == f"w0.{DOMAIN}"
        finally:
            recv.close()

    def test_no_target_is_skipped_not_fatal(self):
        driver = ChaosDriver(FaultPlan())
        driver.apply("worker-roll", {})            # must not raise
        driver.apply("rrl-flood", {"n": 4})        # must not raise
        assert [a for _, a in driver.applied] == \
            ["worker-roll", "rrl-flood"]


class TestRollingOps:
    """ISSUE 19 tentpole: zero-downtime drain-and-replace.  The
    incumbent keeps serving until the replacement is snapshot-caught-up
    and reuseport-bound; only then is it drained."""

    def test_roll_shard_drain_and_replace(self, tmp_path):
        async def run():
            sup = await boot(str(tmp_path), 2)
            try:
                port = sup.udp_port
                pid0 = sup._pid(0)
                old_proc = sup.links[0].proc
                assert await sup.roll_shard(0)
                assert sup._pid(0) not in (None, pid0)
                # the incumbent exited AND was reaped
                assert old_proc.poll() is not None
                assert sup.rolls[0] == 1 and sup.roll_aborts == 0
                assert sup.respawns[0] == 0     # a roll is not a crash
                snap = sup.snapshot()
                assert snap["shards"]["rolls_total"] == 1
                assert snap["shards"]["rolling_shard"] is None
                # a mutation AFTER the roll: the replacement's delta
                # feed is live, not just its snapshot
                sup.store.put_json(
                    "/test/shard/w1",
                    {"type": "host", "host": {"address": "10.50.7.7"}})

                async def converged():
                    for s in range(8):
                        data = await ask_fresh(port, f"w1.{DOMAIN}",
                                               Type.A, qid=700 + s)
                        msg = Message.decode(data)
                        if not msg.answers or \
                                msg.answers[0].address != "10.50.7.7":
                            return False
                    return True

                deadline = time.monotonic() + 10
                while not await converged():
                    assert time.monotonic() < deadline, \
                        "rolled group never converged on the " \
                        "post-roll mutation"
                    await asyncio.sleep(0.2)
            finally:
                await sup.drain()

        asyncio.run(run())

    def test_request_roll_group_and_busy_absorbed(self, tmp_path):
        async def run():
            sup = await boot(str(tmp_path), 2)
            try:
                pids = {i: sup._pid(i) for i in range(2)}
                task = sup.request_roll()
                assert task is not None
                # an overlapping request is absorbed, not interleaved
                # (two rolls racing promotions for one shard slot)
                assert sup.request_roll() is None
                assert await task
                for i in range(2):
                    assert sup._pid(i) not in (None, pids[i])
                assert sup.rolls == {0: 1, 1: 1}
                assert sup.roll_aborts == 0
                # answers still flow from the new incarnation
                data = await ask_fresh(sup.udp_port, f"w0.{DOMAIN}",
                                       Type.A, qid=41)
                assert Message.decode(data).answers
            finally:
                await sup.drain()

        asyncio.run(run())

    def test_roll_abort_keeps_incumbent_serving(self, tmp_path):
        """A replacement that never reports hello aborts the roll with
        the incumbent untouched — a bad build or config must not take
        down a serving shard."""
        async def run():
            sup = await boot(str(tmp_path), 1)
            try:
                pid0 = sup._pid(0)

                async def no_hello(i, timeout=0.0, link=None):
                    raise asyncio.TimeoutError

                sup._wait_hello = no_hello
                assert not await sup.roll_shard(0)
                assert sup.roll_aborts == 1 and sup.rolls[0] == 0
                assert sup._pid(0) == pid0
                data = await ask_fresh(sup.udp_port, f"w0.{DOMAIN}",
                                       Type.A, qid=51)
                assert Message.decode(data).answers
            finally:
                await sup.drain()

        asyncio.run(run())


class TestDcsFanout:
    """ISSUE 19 satellite: the ``/dcs`` subtree fans through the
    owner->worker mutation log (``pnode``/``pgone`` frames), so a
    worker's DcRegistry sees membership changes that happen AFTER it
    attached — pre-attach state rides the snapshot, post-attach joins
    and leaves ride the delta feed."""

    def test_worker_sees_dc_join_after_attach(self):
        from binder_tpu.federation.registry import DcRegistry
        from binder_tpu.metrics.collector import MetricsCollector
        from binder_tpu.shard import ReplicaStore
        from binder_tpu.shard.supervisor import ShardLink, ShardSupervisor
        from binder_tpu.store import FakeStore, MirrorCache

        async def run():
            store = FakeStore()
            for path, data in FIXTURE.items():
                store.put_json(path, data)
            # dc1 joins BEFORE the worker attaches: snapshot path
            store.put_json("/dcs/dc1", {"zones": ["east"],
                                        "peers": ["10.9.9.1:53"]})
            cache = MirrorCache(store, DOMAIN)
            store.start_session()

            sup = ShardSupervisor(
                options={"shards": 1, "host": "127.0.0.1", "port": 0,
                         "dnsDomain": DOMAIN},
                store=store, cache=cache, collector=MetricsCollector())
            loop = asyncio.get_running_loop()
            sup._loop = loop
            sup_end, worker_end = socket.socketpair()
            sup_end.setblocking(False)
            link = ShardLink(0, _StubProc(), sup_end)
            sup.links[0] = link
            sup._send_snapshot(link)
            replica = ReplicaStore(worker_end, 0)
            fut = loop.run_in_executor(None, replica.read_snapshot, 30.0)
            while not fut.done():
                sup._tick()
                await asyncio.sleep(0.02)
            await fut

            # the worker's registry comes up with the pre-attach
            # membership — delivered by the snapshot, not a store read
            reg = DcRegistry(replica, self_name="dc0")
            reg.start()
            assert set(reg.records) == {"dc1"}
            assert reg.records["dc1"]["zones"] == ["east"]
            changes = []
            reg.on_change(lambda: changes.append(dict(reg.records)))

            replica.start(loop)     # non-blocking delta feed

            # a DC that joins AFTER attach must reach the worker
            store.put_json("/dcs/dc2", {"zones": ["west"],
                                        "peers": ["10.9.9.2:53"]})
            deadline = time.monotonic() + 5
            while "dc2" not in reg.records:
                assert time.monotonic() < deadline, \
                    "post-attach dc-join never reached the worker"
                await asyncio.sleep(0.02)
            assert reg.records["dc2"]["peers"] == ["10.9.9.2:53"]
            assert reg.joins >= 1 and changes

            # ... and so must a leave (pgone -> children watch fires)
            store.rmr("/dcs/dc2")
            deadline = time.monotonic() + 5
            while "dc2" in reg.records:
                assert time.monotonic() < deadline, \
                    "post-attach dc-leave never reached the worker"
                await asyncio.sleep(0.02)
            assert reg.leaves >= 1
            assert set(reg.records) == {"dc1"}

            replica.close()
            sup._close_link(link)

        asyncio.run(run())


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
