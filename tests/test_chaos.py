"""Fault-injection harness + graceful-degradation policy engine.

What this pins down end to end (docs/degradation.md):

- FaultPlan DSL parses (and rejects garbage loudly); ChaosUpstream
  applies loss / delay / duplication / truncation / dead-peer faults;
- circuit breakers: threshold opens, backoff + half-open probing
  closes on recovery, and — the satellite guarantee — a dead peer
  adds <100 ms per query once its breaker is open;
- hedged dispatch beats the serial timeout for a silent-but-unopened
  peer;
- the stale-serve state machine: fresh -> stale-serving (TTL clamp)
  -> stale-exhausted (withheld per config) -> fresh again, with cache
  flushes at every edge and binder_degraded_state tracking;
- overload admission: in-flight oldest-shed answers (REFUSED, never a
  hang, never double-metered) and per-client recursion token buckets;
- validate_degradation_metrics passes against a live scrape (and
  catches removals);
- the chaos soak: scripted ZK-session loss + upstream packet loss
  under continuous queries — answers stay correct-or-refused, nothing
  staler than the cap is served, and the system re-converges
  (binder_degraded_state back to 0, breakers closed, mirror advances).
"""
import asyncio
import time

import pytest

from binder_tpu.chaos import ChaosDriver, ChaosUpstream, FaultPlan
from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.policy import (AdmissionControl, CircuitBreaker,
                               DegradationPolicy, PeerBreakers)
from binder_tpu.introspect import FlightRecorder
from binder_tpu.recursion import Recursion, StaticResolverSource
from binder_tpu.recursion.client import DnsClient
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from tools.lint import (validate_degradation_metrics,
                        validate_status_snapshot)

DOMAIN = "foo.com"


def make_fixture(recorder=None, collector=None, hosts=None):
    store = FakeStore(recorder=recorder)
    cache = MirrorCache(store, DOMAIN, collector=collector,
                        recorder=recorder)
    for name, addr in (hosts or {"web": "10.0.0.1"}).items():
        store.put_json(f"/com/foo/{name}",
                       {"type": "host", "host": {"address": addr}})
    store.start_session()
    return store, cache


async def start_server(recorder=None, collector=None, recursion=None,
                       hosts=None, **kw):
    store, cache = make_fixture(recorder=recorder, collector=collector,
                                hosts=hosts)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1",
                          port=0, collector=collector or MetricsCollector(),
                          query_log=False, flight_recorder=recorder,
                          recursion=recursion, **kw)
    await server.start()
    return server, store


async def udp_ask(port, name, qtype, qid=1, rd=False, edns=1232,
                  timeout=5.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            transport.sendto(make_query(name, qtype, qid=qid, rd=rd,
                                        edns_payload=edns).encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return Message.decode(data)


# ---------------------------------------------------------------------------
# FaultPlan DSL + ChaosUpstream


class TestFaultPlan:
    def test_parse_full_spec(self):
        plan = FaultPlan.parse("""
            # chaos script
            at 0.5 lose-session
            at 1.0 watch-storm n=600
            at 1.5 loop-stall ms=120
            at 2.0 upstream loss=0.3 delay_ms=40 dup=0.05
            at 3.0 restore-session; at 4.0 upstream clear
        """)
        assert [a for _t, a, _k in plan.timeline] == [
            "lose-session", "watch-storm", "loop-stall", "upstream",
            "restore-session", "upstream"]
        assert plan.duration == 4.0
        t, action, kw = plan.timeline[3]
        assert (t, action) == (2.0, "upstream")
        assert kw == {"loss": 0.3, "delay_ms": 40, "dup": 0.05}

    def test_parse_rejects_garbage(self):
        for bad in ("lose-session", "at x lose-session",
                    "at 1 warp-core-breach", "at 1 upstream loss"):
            with pytest.raises(ValueError):
                FaultPlan.parse(bad)

    def test_same_seed_same_decisions(self):
        a, b = FaultPlan(seed=7), FaultPlan(seed=7)
        assert [a.rng.random() for _ in range(20)] \
            == [b.rng.random() for _ in range(20)]

    def test_driver_applies_session_and_storm(self):
        recorder = FlightRecorder()
        store, cache = make_fixture(recorder=recorder)
        writes = []
        drv = ChaosDriver(FaultPlan(), store=store,
                          mutate=lambda i: writes.append(i),
                          recorder=recorder)
        drv.apply("lose-session", {})
        assert store.session_state() == "degraded"
        drv.apply("watch-storm", {"n": 5})
        assert writes == [0, 1, 2, 3, 4]
        drv.apply("restore-session", {})
        assert store.session_state() == "connected"
        kinds = [e["type"] for e in recorder.events()]
        assert kinds.count("chaos-inject") == 3


class TestChaosUpstream:
    def run(self, coro):
        return asyncio.run(coro)

    def test_serves_then_faults(self):
        async def go():
            plan = FaultPlan(seed=1)
            up = ChaosUpstream(plan, hosts={"w.remote.foo.com":
                                            "10.9.0.1"})
            port = await up.start()
            client = DnsClient(timeout=0.3)
            try:
                # healthy: answers with the mapped address
                recs = await client.lookup("w.remote.foo.com", Type.A,
                                           [f"127.0.0.1:{port}"])
                assert [r.address for r in recs] == ["10.9.0.1"]
                # dead: every packet dropped -> UpstreamError
                plan.upstream.set(dead=1)
                from binder_tpu.recursion.client import UpstreamError
                with pytest.raises(UpstreamError):
                    await client.lookup("w.remote.foo.com", Type.A,
                                        [f"127.0.0.1:{port}"])
                assert up.dropped >= 1
                # truncation: UDP answers TC=1, TCP retry serves it
                plan.upstream.set(clear=True, truncate=1)
                recs = await client.lookup("w.remote.foo.com", Type.A,
                                           [f"127.0.0.1:{port}"])
                assert [r.address for r in recs] == ["10.9.0.1"]
                assert up.truncated >= 1
                # delay: the answer arrives, late
                plan.upstream.set(clear=True, delay_ms=80)
                t0 = time.monotonic()
                await client.lookup("w.remote.foo.com", Type.A,
                                    [f"127.0.0.1:{port}"])
                assert time.monotonic() - t0 >= 0.07
                assert up.delayed >= 1
            finally:
                client.close()
                await up.stop()

        self.run(go())


# ---------------------------------------------------------------------------
# circuit breakers + hedging


class TestCircuitBreaker:
    def test_threshold_backoff_halfopen_close(self):
        b = CircuitBreaker("p")
        now = 100.0
        for _ in range(CircuitBreaker.FAILURE_THRESHOLD - 1):
            b.record_failure(now)
        assert b.state == "closed"
        b.record_failure(now)
        assert b.state == "open"
        # jittered backoff within [0.5x, 1x] of the base
        assert now + 0.5 * b.BACKOFF_BASE <= b.open_until \
            <= now + b.BACKOFF_BASE
        assert not b.allow(now)
        # backoff elapsed: exactly one probe per interval
        t1 = b.open_until + 0.01
        assert b.allow(t1)
        assert b.state == "half-open"
        assert not b.allow(t1 + 0.1)
        # failed probe: re-opens with doubled backoff
        b.record_failure(t1)
        assert b.state == "open"
        assert b.open_until - t1 >= 0.5 * 2 * b.BACKOFF_BASE
        # successful probe closes and resets
        t2 = b.open_until + 0.01
        assert b.allow(t2)
        b.record_success(0.005)
        assert b.state == "closed"
        assert b.allow(t2)

    def test_registry_filter_and_metrics(self):
        collector = MetricsCollector()
        reg = PeerBreakers(collector=collector)
        for _ in range(3):
            reg.record("dead:53", False)
        reg.record("live:53", True, 0.004)
        assert reg.get("dead:53").state == "open"
        assert reg.filter(["dead:53", "live:53"]) == ["live:53"]
        assert reg.open_count() == 1
        g = collector.get("binder_breaker_state")
        assert g.value({"peer": "dead:53"}) == 2.0
        assert g.value({"peer": "(max)"}) == 2.0
        assert reg.hedge_delay("live:53") >= PeerBreakers.HEDGE_FLOOR

    def test_rcode_error_is_a_live_peer(self):
        reg = PeerBreakers()
        for _ in range(10):
            reg.record("p:53", True)    # REFUSED et al. = responses
        assert reg.get("p:53").state == "closed"


def _blackhole_upstream():
    """A ChaosUpstream with every packet dropped: silence, no ICMP —
    the worst-case dead peer."""
    plan = FaultPlan(seed=3)
    plan.upstream.set(dead=1)
    return ChaosUpstream(plan, hosts={})


class TestDeadPeerLatency:
    """The satellite pin: a dead first resolver must cost <100 ms per
    query once its breaker is open (it cost the full 3 s timeout per
    query in the reference)."""

    def test_open_breaker_bounds_dead_peer_cost(self):
        async def go():
            dead = _blackhole_upstream()
            dead_port = await dead.start()
            live = ChaosUpstream(FaultPlan(),
                                 hosts={"w.foo.com": "10.1.1.1"})
            live_port = await live.start()
            breakers = PeerBreakers()
            client = DnsClient(timeout=0.1, breakers=breakers)
            ups = [f"127.0.0.1:{dead_port}", f"127.0.0.1:{live_port}"]
            try:
                # warm-up queries: each one times the dead peer out
                # (recorded via the future's outcome callback even when
                # a hedged winner cancels the task) until its breaker
                # opens
                for _ in range(6):
                    recs = await client.lookup("w.foo.com", Type.A, ups)
                    assert [r.address for r in recs] == ["10.1.1.1"]
                    await asyncio.sleep(0.12)   # let the sweep settle
                    if breakers.get(ups[0]).state == "open":
                        break
                assert breakers.get(ups[0]).state == "open"
                # the pin: with the breaker open the dead peer adds
                # <100 ms (it is skipped outright)
                t0 = time.monotonic()
                recs = await client.lookup("w.foo.com", Type.A, ups)
                elapsed = time.monotonic() - t0
                assert [r.address for r in recs] == ["10.1.1.1"]
                assert elapsed < 0.1, f"dead peer cost {elapsed:.3f}s " \
                    "with its breaker open"
            finally:
                client.close()
                await dead.stop()
                await live.stop()

        asyncio.run(go())

    def test_all_open_fails_fast_not_hangs(self):
        async def go():
            breakers = PeerBreakers()
            for _ in range(3):
                breakers.record("192.0.2.1:53", False)
            client = DnsClient(timeout=3.0, breakers=breakers)
            from binder_tpu.recursion.client import UpstreamError
            t0 = time.monotonic()
            try:
                with pytest.raises(UpstreamError):
                    await client.lookup_raw("x.foo.com", Type.A,
                                            ["192.0.2.1:53"])
            finally:
                client.close()
            assert time.monotonic() - t0 < 0.1

        asyncio.run(go())

    def test_hedge_beats_slow_peer(self):
        """A silent (not yet broken) first peer costs one hedge
        stagger, not the full timeout."""
        async def go():
            slow_plan = FaultPlan()
            slow_plan.upstream.set(delay_ms=2000)
            slow = ChaosUpstream(slow_plan, hosts={"w.foo.com": "10.2.2.2"})
            slow_port = await slow.start()
            live = ChaosUpstream(FaultPlan(),
                                 hosts={"w.foo.com": "10.1.1.1"})
            live_port = await live.start()
            breakers = PeerBreakers()
            client = DnsClient(timeout=3.0, concurrency=1,
                               breakers=breakers)
            try:
                t0 = time.monotonic()
                recs = await client.lookup(
                    "w.foo.com", Type.A,
                    [f"127.0.0.1:{slow_port}", f"127.0.0.1:{live_port}"])
                elapsed = time.monotonic() - t0
                assert [r.address for r in recs] == ["10.1.1.1"]
                # hedge default 0.25s + scheduling; far under the 2s
                # the slow peer (or the 3s timeout) would cost
                assert elapsed < 1.0
            finally:
                client.close()
                await slow.stop()
                await live.stop()

        asyncio.run(go())


# ---------------------------------------------------------------------------
# stale-serve degradation policy


class TestDegradationPolicy:
    def test_state_machine_and_metrics(self):
        collector = MetricsCollector()
        recorder = FlightRecorder()
        store, cache = make_fixture(recorder=recorder)
        pol = DegradationPolicy(store=store, zk_cache=cache,
                                max_staleness_s=0.15,
                                collector=collector, recorder=recorder)
        seen = []
        pol.on_transition(lambda old, new: seen.append((old, new)))
        assert pol.mode() == "fresh"
        store.lose_session()
        assert pol.mode() == "stale-serving"
        time.sleep(0.2)
        assert pol.mode() == "stale-exhausted"
        store.start_session()
        assert pol.mode() == "fresh"
        assert seen == [("fresh", "stale-serving"),
                        ("stale-serving", "stale-exhausted"),
                        ("stale-exhausted", "fresh")]
        kinds = [e["type"] for e in recorder.events()]
        assert kinds.count("degraded-transition") == 3
        snap = pol.introspect()
        assert snap["state"] == "fresh"
        assert len(snap["transitions"]) == 3

    def test_stale_serving_clamps_ttl(self):
        async def go():
            server, store = await start_server(
                degradation={"maxStalenessSeconds": 30.0,
                             "staleTtlClampSeconds": 5})
            store.put_json("/com/foo/slow",
                           {"type": "host", "ttl": 3600,
                            "host": {"address": "10.0.0.9"}})
            try:
                msg = await udp_ask(server.udp_port, "slow.foo.com",
                                    Type.A)
                assert msg.answers[0].ttl == 3600
                epoch_before = server.zk_cache.epoch
                store.lose_session()
                msg = await udp_ask(server.udp_port, "slow.foo.com",
                                    Type.A)
                assert msg.rcode == Rcode.NOERROR
                assert msg.answers[0].address == "10.0.0.9"
                assert msg.answers[0].ttl == 5          # clamped
                # the transition flushed every cached lane
                assert server.zk_cache.epoch > epoch_before
                assert server._policy.stale_served >= 1
            finally:
                await server.stop()

        asyncio.run(go())

    def test_exhausted_servfail_and_nodata(self):
        async def go():
            for action, want in (("servfail", Rcode.SERVFAIL),
                                 ("nodata", Rcode.NOERROR)):
                server, store = await start_server(
                    degradation={"maxStalenessSeconds": 0.05,
                                 "exhaustedAction": action})
                try:
                    store.lose_session()
                    await asyncio.sleep(0.1)
                    msg = await udp_ask(server.udp_port, "web.foo.com",
                                        Type.A)
                    assert msg.rcode == want
                    assert msg.answers == []
                    if action == "nodata":
                        assert msg.authorities, "NODATA must carry SOA"
                    # recovery: session back -> fresh data served again
                    store.start_session()
                    msg = await udp_ask(server.udp_port, "web.foo.com",
                                        Type.A)
                    assert msg.rcode == Rcode.NOERROR
                    assert msg.answers[0].address == "10.0.0.1"
                finally:
                    await server.stop()

        asyncio.run(go())

    def test_cached_answers_do_not_outlive_the_cap(self):
        """The cap covers the cached lanes: an answer cached while
        fresh must not be served once the policy is exhausted."""
        async def go():
            server, store = await start_server(
                degradation={"maxStalenessSeconds": 0.05})
            try:
                # populate the per-key answer cache while fresh
                for _ in range(2):
                    msg = await udp_ask(server.udp_port, "web.foo.com",
                                        Type.A)
                    assert msg.rcode == Rcode.NOERROR
                store.lose_session()
                await asyncio.sleep(0.1)
                msg = await udp_ask(server.udp_port, "web.foo.com",
                                    Type.A)
                assert msg.rcode == Rcode.SERVFAIL
                assert msg.answers == []
            finally:
                await server.stop()

        asyncio.run(go())


# ---------------------------------------------------------------------------
# overload admission control


class TestAdmission:
    def test_inflight_oldest_shed(self):
        async def go():
            server, store = await start_server(
                admission={"maxInflight": 4})
            # park every query in a never-completing handler
            release = asyncio.Event()

            def slow_handle(query):
                async def wait():
                    await release.wait()
                    query.set_error(Rcode.REFUSED)
                    query.respond()
                return wait()

            server.resolver.handle = slow_handle
            server.engine.raw_lane = None
            server.engine.fastpath = None
            try:
                loop = asyncio.get_running_loop()
                answers = [loop.create_future() for _ in range(6)]

                class Proto(asyncio.DatagramProtocol):
                    def __init__(self, i):
                        self.i = i

                    def connection_made(self, transport):
                        transport.sendto(make_query(
                            f"q{self.i}.foo.com", Type.A,
                            qid=self.i + 1).encode())

                    def datagram_received(self, data, addr):
                        if not answers[self.i].done():
                            answers[self.i].set_result(data)

                transports = []
                for i in range(6):
                    tr, _ = await loop.create_datagram_endpoint(
                        lambda i=i: Proto(i),
                        remote_addr=("127.0.0.1", server.udp_port))
                    transports.append(tr)
                    await asyncio.sleep(0.01)
                # 6 in flight with cap 4: the two OLDEST were shed with
                # an immediate REFUSED; the newest 4 still hang
                shed = await asyncio.wait_for(
                    asyncio.gather(answers[0], answers[1]), 2.0)
                for wire in shed:
                    msg = Message.decode(wire)
                    assert msg.rcode == Rcode.REFUSED
                assert len(server.engine.inflight) == 4
                adm = server._admission
                assert adm.shed_counts["inflight-overflow"] == 2
                release.set()
                await asyncio.sleep(0.05)
                for tr in transports:
                    tr.close()
            finally:
                await server.stop()

        asyncio.run(go())

    def test_recursion_token_bucket(self):
        adm = AdmissionControl(recursion_rate=1000.0, recursion_burst=3)
        assert all(adm.allow_recursion("10.0.0.1") for _ in range(3))
        assert not adm.allow_recursion("10.0.0.1")
        # other clients are unaffected
        assert adm.allow_recursion("10.0.0.2")
        assert adm.shed_counts["recursion-ratelimit"] == 1

    def test_recursion_shed_is_wellformed_refused(self):
        async def go():
            # recursion configured, bucket of burst 1: the second RD
            # miss from one client is REFUSED without upstream work
            store, cache = make_fixture()
            recursion = Recursion(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="dc0",
                source=StaticResolverSource({"remote":
                                             ["192.0.2.9:53"]}))
            await recursion.wait_ready()
            server = BinderServer(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="dc0", host="127.0.0.1", port=0,
                collector=MetricsCollector(), query_log=False,
                recursion=recursion,
                admission={"recursionRate": 0.001, "recursionBurst": 1})
            await server.start()
            try:
                t0 = time.monotonic()
                # burst 1: first forward goes upstream (dead peer -> its
                # own slow path), so spend the token with a query that
                # can't linger — use a name in a DC we don't know
                msg = await udp_ask(server.udp_port,
                                    "w.nodc.foo.com", Type.A, rd=True)
                assert msg.rcode == Rcode.REFUSED
                msg = await udp_ask(server.udp_port,
                                    "w.nodc.foo.com", Type.A, rd=True)
                assert msg.rcode == Rcode.REFUSED
                assert time.monotonic() - t0 < 2.0
                assert server._admission.shed_counts[
                    "recursion-ratelimit"] >= 1
            finally:
                await server.stop()
                await recursion.close()

        asyncio.run(go())


# ---------------------------------------------------------------------------
# metrics + status pins


class TestDegradationMetrics:
    def _full_stack_scrape(self):
        async def go():
            collector = MetricsCollector()
            recorder = FlightRecorder()
            store, cache = make_fixture(recorder=recorder,
                                        collector=collector)
            recursion = Recursion(
                zk_cache=cache, dns_domain=DOMAIN,
                datacenter_name="dc0",
                source=StaticResolverSource({}),
                collector=collector, recorder=recorder)
            await recursion.wait_ready()
            server = BinderServer(
                zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
                host="127.0.0.1", port=0, collector=collector,
                query_log=False, flight_recorder=recorder,
                recursion=recursion,
                degradation={}, admission={})
            await server.start()
            try:
                return collector.expose(), server
            finally:
                await server.stop()
                await recursion.close()

        return asyncio.run(go())

    def test_scrape_passes_validator(self):
        text, _server = self._full_stack_scrape()
        assert validate_degradation_metrics(text) == []

    def test_validator_catches_removals(self):
        text, _server = self._full_stack_scrape()
        # strip one family entirely: must fail
        gutted = "\n".join(l for l in text.splitlines()
                           if "binder_degraded_state" not in l) + "\n"
        errs = validate_degradation_metrics(gutted)
        assert any("binder_degraded_state" in e for e in errs)
        # strip one pinned label series: must fail
        gutted = "\n".join(
            l for l in text.splitlines()
            if 'reason="inflight-overflow"' not in l) + "\n"
        errs = validate_degradation_metrics(gutted)
        assert any("inflight-overflow" in e for e in errs)

    def test_status_snapshot_carries_policy_section(self):
        async def go():
            from binder_tpu.introspect import Introspector
            collector = MetricsCollector()
            server, store = await start_server(
                collector=collector,
                degradation={}, admission={})
            try:
                intro = Introspector(server=server)
                snap = intro.snapshot()
                assert validate_status_snapshot(snap) == []
                pol = snap["policy"]
                assert pol["degradation"]["state"] == "fresh"
                assert pol["admission"]["max_inflight"] == 512
                store.lose_session()
                snap = intro.snapshot()
                assert snap["policy"]["degradation"]["state"] \
                    == "stale-serving"
            finally:
                await server.stop()

        asyncio.run(go())


# ---------------------------------------------------------------------------
# the chaos soak (acceptance criterion)


class TestChaosSoak:
    SOAK_SECONDS = 3.0

    def test_soak_under_session_loss_and_packet_loss(self):
        asyncio.run(self._soak())

    async def _soak(self):
        collector = MetricsCollector()
        recorder = FlightRecorder()
        store, cache = make_fixture(
            recorder=recorder, collector=collector,
            hosts={f"w{i}": f"10.0.1.{i + 1}" for i in range(8)})

        # recursion upstream with scripted packet loss
        plan = FaultPlan(seed=42)
        upstream = ChaosUpstream(
            plan, hosts={"w.remote.foo.com": "10.8.0.1"})
        up_port = await upstream.start()
        recursion = Recursion(
            zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
            source=StaticResolverSource(
                {"remote": [f"127.0.0.1:{up_port}"]}),
            client=DnsClient(timeout=0.25),
            collector=collector, recorder=recorder)
        await recursion.wait_ready()

        max_staleness = 0.8
        server = BinderServer(
            zk_cache=cache, dns_domain=DOMAIN, datacenter_name="dc0",
            host="127.0.0.1", port=0, collector=collector,
            query_log=False, flight_recorder=recorder,
            recursion=recursion,
            degradation={"maxStalenessSeconds": max_staleness,
                         "staleTtlClampSeconds": 3},
            admission={"maxInflight": 64})
        await server.start()

        # scripted faults: upstream loss early, session killed
        # mid-churn, both healed before the end
        soak_plan = FaultPlan(seed=7) \
            .at(0.3, "upstream", loss=0.4) \
            .at(0.6, "lose-session") \
            .at(0.7, "watch-storm", n=50) \
            .at(2.0, "restore-session") \
            .at(2.2, "upstream", clear=True)
        # the upstream faults must act on the UPSTREAM's plan
        soak_plan.upstream = plan.upstream

        def mutate(i):
            store.put_json(f"/com/foo/churn{i % 4}",
                           {"type": "host",
                            "host": {"address": f"10.7.0.{i % 200 + 1}"}})

        driver = ChaosDriver(soak_plan, store=store, mutate=mutate,
                             recorder=recorder)
        chaos_task = driver.start()

        pol = server._policy
        stats = {"ok": 0, "refused": 0, "servfail": 0, "stale": 0}
        t_end = asyncio.get_running_loop().time() + self.SOAK_SECONDS
        i = 0
        try:
            while asyncio.get_running_loop().time() < t_end:
                name = f"w{i % 8}.foo.com"
                rd = i % 5 == 0
                if rd:
                    name = "w.remote.foo.com"
                i += 1
                try:
                    msg = await udp_ask(server.udp_port, name, Type.A,
                                        qid=(i % 0xFFFF) + 1, rd=rd,
                                        timeout=1.0)
                except asyncio.TimeoutError:
                    # recursion forwards may legitimately exceed the
                    # ask window under 40% loss; local queries may not
                    assert rd, f"local query for {name} hung"
                    continue
                mode = pol.mode()
                if msg.rcode == Rcode.NOERROR and msg.answers:
                    # INVARIANT: data answers only while fresh or
                    # within the staleness cap — and stale answers are
                    # clamped
                    assert mode in ("fresh", "stale-serving")
                    if mode == "stale-serving" and not rd:
                        assert all(a.ttl <= 3 for a in msg.answers)
                        stats["stale"] += 1
                    ds = getattr(store, "disconnected_seconds")()
                    if ds is not None and not rd:
                        assert ds <= max_staleness + 0.5, \
                            "served staler than the cap"
                    stats["ok"] += 1
                elif msg.rcode == Rcode.REFUSED:
                    stats["refused"] += 1
                elif msg.rcode == Rcode.SERVFAIL:
                    # only legitimate while exhausted (or store down)
                    stats["servfail"] += 1
                await asyncio.sleep(0.01)

            await asyncio.wait_for(chaos_task, 5.0)
            # every phase actually exercised
            assert stats["ok"] > 0
            assert stats["stale"] > 0, "stale-serving window not observed"
            assert stats["servfail"] > 0, "exhausted window not observed"

            # RE-CONVERGENCE: session is back -> fresh, serving, and
            # every degradation signal returns to rest
            gen_before = cache.gen
            store.put_json("/com/foo/w0",
                           {"type": "host",
                            "host": {"address": "10.0.1.99"}})
            assert cache.gen > gen_before, "mirror gen must advance"
            for _ in range(50):
                if pol.mode() == "fresh":
                    break
                await asyncio.sleep(0.05)
            assert pol.mode() == "fresh"
            assert collector.get("binder_degraded_state").value() == 0.0
            msg = await udp_ask(server.udp_port, "w0.foo.com", Type.A,
                                qid=9999)
            assert msg.rcode == Rcode.NOERROR
            assert msg.answers[0].address == "10.0.1.99"
            assert recursion.breakers.open_count() == 0
            # the flight recorder kept the story
            kinds = {e["type"] for e in recorder.events()}
            assert "chaos-inject" in kinds
            assert "degraded-transition" in kinds
        finally:
            await server.stop()
            await recursion.close()
            await upstream.stop()


class TestChaosSmokeHarness:
    """`make chaos-smoke`'s harness, run short: tier-1 proves the
    EXACT script the 30 s make target runs (same invariants, same
    FaultPlan shape) — the smoke can never rot unnoticed."""

    def test_smoke_harness_short(self):
        import tools.chaos_smoke as cs
        stats = cs.run_smoke(duration=3.0)
        assert stats["ok"] > 0
        assert stats["stale"] > 0
        assert stats["servfail"] > 0
        assert stats["flight_events"].get("chaos-inject", 0) >= 6
        assert stats["flight_events"].get("degraded-transition", 0) >= 3


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-v"]))
