"""Integration tests: full server over real UDP/TCP/balancer-socket
transports.

The protocol-level replacement for the reference's dig(1)-scraping
integration suite (SURVEY §4) — same scenarios, but asserting on decoded
wire responses, and runnable without a live ZooKeeper thanks to the fake
store.
"""
import asyncio
import socket
import struct


from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.dns.server import pack_balancer_frame, unpack_balancer_frame
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import (
    METRIC_LATENCY_HISTOGRAM,
    METRIC_REQUEST_COUNTER,
    BinderServer,
)
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(40):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(cache, **kw):
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1", port=0,
                          collector=MetricsCollector(), **kw)
    await server.start()
    return server


async def udp_ask(port, name, qtype, payload=1232, timeout=2.0,
                  qid=4242):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport
            q = make_query(name, qtype, qid=qid, edns_payload=payload)
            transport.sendto(q.encode())

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        data = await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()
    return Message.decode(data)


async def tcp_ask(port, name, qtype, qid=7, edns_payload=1232):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    wire = make_query(name, qtype, qid=qid,
                      edns_payload=edns_payload).encode()
    writer.write(struct.pack(">H", len(wire)) + wire)
    await writer.drain()
    (length,) = struct.unpack(">H", await reader.readexactly(2))
    data = await reader.readexactly(length)
    writer.close()
    await writer.wait_closed()
    return Message.decode(data)


class TestUdp:
    def test_a_query(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            r = await udp_ask(server.udp_port, "web.foo.com", Type.A)
            await server.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR and r.aa
        assert r.answers[0].address == "192.168.0.1"

    def test_refused_unknown(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            r = await udp_ask(server.udp_port, "nope.foo.com", Type.A)
            await server.stop()
            return r

        assert asyncio.run(run()).rcode == Rcode.REFUSED

    def test_truncation_under_small_payload(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            r = await udp_ask(server.udp_port, "svc.foo.com", Type.A,
                              payload=None)  # classic 512-byte limit
            await server.stop()
            return r

        r = asyncio.run(run())
        # 30 answers don't fit in 512b: TC set, client should retry TCP
        assert r.tc and len(r.answers) == 0

    def test_formerr_on_garbage(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            loop = asyncio.get_running_loop()
            fut = loop.create_future()

            class Proto(asyncio.DatagramProtocol):
                def connection_made(self, transport):
                    transport.sendto(b"\xde\xad\xff\xff\xff\xff")

                def datagram_received(self, data, addr):
                    if not fut.done():
                        fut.set_result(data)

            transport, _ = await loop.create_datagram_endpoint(
                Proto, remote_addr=("127.0.0.1", server.udp_port))
            data = await asyncio.wait_for(fut, 2)
            transport.close()
            await server.stop()
            return Message.decode(data)

        r = asyncio.run(run())
        assert r.rcode == Rcode.FORMERR and r.id == 0xDEAD


class TestTcp:
    def test_tcp_full_answer_set(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            r = await tcp_ask(server.tcp_port, "svc.foo.com", Type.A)
            await server.stop()
            return r

        r = asyncio.run(run())
        assert not r.tc and len(r.answers) == 40

    def test_tcp_multiple_queries_one_connection(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            out = []
            for i, (name, qtype) in enumerate(
                    [("web.foo.com", Type.A),
                     ("_pg._tcp.svc.foo.com", Type.SRV)]):
                wire = make_query(name, qtype, qid=i + 1).encode()
                writer.write(struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                (ln,) = struct.unpack(">H", await reader.readexactly(2))
                out.append(Message.decode(await reader.readexactly(ln)))
            writer.close()
            await writer.wait_closed()
            await server.stop()
            return out

        r1, r2 = asyncio.run(run())
        assert r1.id == 1 and r1.answers[0].address == "192.168.0.1"
        assert r2.id == 2 and len(r2.answers) == 40


async def read_data_frame(reader):
    """Next non-control frame (backends announce their mirror generation
    with family-0 control frames, which a real balancer consumes)."""
    while True:
        (ln,) = struct.unpack(">I", await reader.readexactly(4))
        frame = await reader.readexactly(ln)
        if frame[1] != 0:   # family 0 == control
            return unpack_balancer_frame(frame)


class TestBalancerSocket:
    def test_query_via_balancer_frame(self, tmp_path):
        sock_path = str(tmp_path / "b.sock")

        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, balancer_socket=sock_path)
            reader, writer = await asyncio.open_unix_connection(sock_path)
            # pretend to be the balancer forwarding a client query
            q = make_query("web.foo.com", Type.A, qid=55).encode()
            writer.write(pack_balancer_frame(4, "203.0.113.9", 5353, q))
            await writer.drain()
            family, addr, port, transport, payload = \
                await read_data_frame(reader)
            writer.close()
            await writer.wait_closed()
            await server.stop()
            return family, addr, port, Message.decode(payload)

        family, addr, port, r = asyncio.run(run())
        # response frame echoes the original client address for routing
        assert (family, addr, port) == (4, "203.0.113.9", 5353)
        assert r.id == 55 and r.answers[0].address == "192.168.0.1"

    def test_bad_version_closes_connection(self, tmp_path):
        sock_path = str(tmp_path / "b.sock")

        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, balancer_socket=sock_path)
            reader, writer = await asyncio.open_unix_connection(sock_path)
            frame = bytearray(pack_balancer_frame(4, "1.2.3.4", 1,
                                                  b"\x00" * 12))
            frame[4] = 99  # bad version
            writer.write(bytes(frame))
            await writer.drain()
            data = await reader.read()
            writer.close()
            await writer.wait_closed()
            await server.stop()
            return data

        # the server may have sent its initial control frames (the
        # generation report, the direct-return announce) before
        # closing; nothing but control frames may precede the close
        data = asyncio.run(run())
        off = 0
        while off < len(data):
            (ln,) = struct.unpack(">I", data[off:off + 4])
            assert data[off + 4] == 1 and data[off + 5] == 0
            off += 4 + ln
        assert off == len(data)   # no partial trailing frame either


class TestMetrics:
    def test_counters_and_latency(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            await udp_ask(server.udp_port, "web.foo.com", Type.A)
            await udp_ask(server.udp_port, "web.foo.com", Type.A)
            await udp_ask(server.udp_port, "1.0.168.192.in-addr.arpa",
                          Type.PTR)
            # let 'after' hooks run
            await asyncio.sleep(0)
            counter = server.collector.get(METRIC_REQUEST_COUNTER)
            hist = server.collector.get(METRIC_LATENCY_HISTOGRAM)
            exposed = server.collector.expose()
            await server.stop()
            return counter, hist, exposed

        counter, hist, exposed = asyncio.run(run())
        assert counter.value({"type": "A"}) == 2
        assert counter.value({"type": "PTR"}) == 1
        assert hist.count({"type": "A"}) == 2
        assert 'binder_requests_completed{type="A"} 2' in exposed
        assert "binder_request_latency_seconds_bucket" in exposed

    def test_slow_query_promotes_log_to_warn(self, monkeypatch, caplog):
        """Latency > SLOW_QUERY_MS logs at warn even with the per-query
        log off (reference lib/server.js:511-514)."""
        import logging as _logging

        import binder_tpu.server as srv_mod

        async def run():
            store, cache = fixture_store()
            # zone-precompiled answers never surface to Python (no
            # latency stamp to promote); the warn path under test is the
            # raw-lane/generic one
            server = await start_server(cache, query_log=False,
                                        zone_precompile=False)
            monkeypatch.setattr(srv_mod, "SLOW_QUERY_MS", -1.0)
            with caplog.at_level(_logging.INFO, logger="binder.server"):
                await udp_ask(server.udp_port, "web.foo.com", Type.A)
                await asyncio.sleep(0)
            await server.stop()

        asyncio.run(run())
        warns = [r for r in caplog.records
                 if r.levelno == _logging.WARNING and "DNS query" in
                 r.getMessage()]
        assert warns, [r.getMessage() for r in caplog.records]


class TestReviewRegressions:
    """Regressions from the second code-review pass."""

    def test_async_handler_path_works(self):
        """A handler that returns a real awaitable (the recursion shape)
        must complete, not die with a half-driven coroutine."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)

            orig = server.resolver.handle

            def handle(query):
                async def delayed():
                    await asyncio.sleep(0.01)  # real suspension
                    pending = orig(query)
                    if pending is not None:
                        await pending
                return delayed()

            server.resolver.handle = handle
            server.engine.on_query = lambda q: server.resolver.handle(q)
            r = await udp_ask(server.udp_port, "web.foo.com", Type.A)
            await server.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "192.168.0.1"

    def test_unencodable_record_yields_servfail(self):
        """host record without an address: client must get SERVFAIL, not
        silence."""
        async def run():
            store, cache = fixture_store()
            store.put_json("/com/foo/noaddr", {"type": "host", "host": {}})
            server = await start_server(cache)
            r = await udp_ask(server.udp_port, "noaddr.foo.com", Type.A)
            await server.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.SERVFAIL and not r.answers

    def test_balancer_udp_transport_truncates(self, tmp_path):
        sock_path = str(tmp_path / "b.sock")

        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, balancer_socket=sock_path)
            reader, writer = await asyncio.open_unix_connection(sock_path)
            q = make_query("svc.foo.com", Type.A, qid=9,
                           edns_payload=None).encode()
            from binder_tpu.dns.server import TRANSPORT_TCP, TRANSPORT_UDP
            writer.write(pack_balancer_frame(4, "203.0.113.9", 5353, q,
                                             transport=TRANSPORT_UDP))
            await writer.drain()
            *_, payload_udp = await read_data_frame(reader)
            writer.write(pack_balancer_frame(4, "203.0.113.9", 5353, q,
                                             transport=TRANSPORT_TCP))
            await writer.drain()
            *_, payload_tcp = await read_data_frame(reader)
            writer.close()
            await writer.wait_closed()
            await server.stop()
            return Message.decode(payload_udp), Message.decode(payload_tcp)

        udp_r, tcp_r = asyncio.run(run())
        # UDP-origin (no EDNS): truncated at 512; TCP-origin: full answers
        assert udp_r.tc and not udp_r.answers
        assert not tcp_r.tc and len(tcp_r.answers) == 40

    def test_short_form_store_address_servfail(self):
        """inet_aton would map '10.1' -> 10.0.0.1; must SERVFAIL instead."""
        async def run():
            store, cache = fixture_store()
            store.put_json("/com/foo/shorty",
                           {"type": "host", "host": {"address": "10.1"}})
            server = await start_server(cache)
            r = await udp_ask(server.udp_port, "shorty.foo.com", Type.A)
            await server.stop()
            return r

        r = asyncio.run(run())
        assert r.rcode == Rcode.SERVFAIL and not r.answers


class TestTcpBounds:
    """The TCP front must survive misbehaving peers with bounded
    resources: idle holders, connection floods, and clients that ask
    but never read (VERDICT r1: no idle timeout or cap anywhere)."""

    def test_idle_connection_evicted(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=0.3)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            # hold the connection without sending a complete frame
            writer.write(b"\x00")
            await writer.drain()
            got = await asyncio.wait_for(reader.read(16), 5)
            writer.close()
            await server.stop()
            return got

        assert asyncio.run(run()) == b""   # server closed on us

    def test_slow_frame_gets_same_deadline(self):
        """A slowloris trickling bytes within one frame must be cut off
        by the same idle clock, not kept alive per-byte."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=0.4)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            wire = make_query("web.foo.com", Type.A, qid=5).encode()
            framed = struct.pack(">H", len(wire)) + wire
            start = asyncio.get_running_loop().time()
            closed_at = None
            try:
                for b in framed:          # one byte per 150 ms
                    writer.write(bytes([b]))
                    await writer.drain()
                    data = await asyncio.wait_for(
                        reader.read(64), 0.15)
                    if data == b"":
                        closed_at = asyncio.get_running_loop().time()
                        break
            except asyncio.TimeoutError:
                pass
            if closed_at is None:
                got = await asyncio.wait_for(reader.read(64), 5)
                assert got == b""
                closed_at = asyncio.get_running_loop().time()
            writer.close()
            await server.stop()
            return closed_at - start

        elapsed = asyncio.run(run())
        # cut off by the whole-frame deadline (0.4 s), well before the
        # ~2.5 s the full trickle would take
        assert elapsed < 2.0

    def test_connection_cap_refuses_newcomers(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, max_tcp_conns=2,
                                        tcp_idle_timeout=30.0)
            conns = []
            for _ in range(2):
                conns.append(await asyncio.open_connection(
                    "127.0.0.1", server.tcp_port))
            # give the handlers a turn to register
            await asyncio.sleep(0.1)
            r3, w3 = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            refused = await asyncio.wait_for(r3.read(16), 5)
            # the earlier connections still work
            wire = make_query("web.foo.com", Type.A, qid=8).encode()
            r1, w1 = conns[0]
            w1.write(struct.pack(">H", len(wire)) + wire)
            await w1.drain()
            (ln,) = struct.unpack(">H", await asyncio.wait_for(
                r1.readexactly(2), 5))
            reply = Message.decode(await r1.readexactly(ln))
            for r, w in conns + [(r3, w3)]:
                w.close()
            await server.stop()
            return refused, reply

        refused, reply = asyncio.run(run())
        assert refused == b""
        assert reply.rcode == Rcode.NOERROR

    def test_cap_slot_recycles_after_close(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, max_tcp_conns=1)
            r1, w1 = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            await asyncio.sleep(0.1)
            w1.close()
            await w1.wait_closed()
            await asyncio.sleep(0.1)   # give the handler a turn to exit
            r2, w2 = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            wire = make_query("web.foo.com", Type.A, qid=3).encode()
            w2.write(struct.pack(">H", len(wire)) + wire)
            await w2.drain()
            (ln,) = struct.unpack(">H", await asyncio.wait_for(
                r2.readexactly(2), 5))
            reply = Message.decode(await r2.readexactly(ln))
            w2.close()
            await server.stop()
            return reply

        reply = asyncio.run(run())
        assert reply.rcode == Rcode.NOERROR

    def test_client_not_reading_responses_aborted(self):
        """Pipelines queries, never reads answers: the write buffer must
        hit its cap and the connection must be aborted, not grow."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=30.0,
                                        max_tcp_write_buffer=4096)
            # tiny receive window so the kernel can't absorb much
            raw = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            raw.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
            raw.setblocking(False)
            loop = asyncio.get_running_loop()
            await loop.sock_connect(raw, ("127.0.0.1", server.tcp_port))
            # SRV answer for svc.foo.com is large (40 targets)
            wire = make_query("svc.foo.com", Type.A, qid=1,
                              edns_payload=4096).encode()
            frame = struct.pack(">H", len(wire)) + wire
            aborted = False
            try:
                # the kernel absorbs up to ~tcp_wmem max (4 MB) before
                # the transport buffer grows, so pump well past that
                # (~700 B per response x 20k queries = ~14 MB)
                for i in range(20000):
                    await loop.sock_sendall(raw, frame)
                    if i % 64 == 0:
                        await asyncio.sleep(0)
            except (ConnectionResetError, BrokenPipeError, OSError):
                aborted = True
            # the server process itself must still serve other clients
            r = await udp_ask(server.udp_port, "web.foo.com", Type.A)
            raw.close()
            await server.stop()
            return aborted, r

        aborted, r = asyncio.run(run())
        assert aborted
        assert r.rcode == Rcode.NOERROR


class TestPairBind:
    """Ephemeral-port UDP/TCP pairing (the r4 CI flake): with port=0 the
    kernel picks the UDP port and TCP must bind the same number, which
    any unrelated socket may hold — start() must redraw, not die."""

    def test_tcp_collision_redraws(self):
        async def run():
            store, cache = fixture_store()
            # occupy a TCP port the first UDP draw will be forced onto
            blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            blocker.bind(("127.0.0.1", 0))
            blocker.listen(1)
            taken = blocker.getsockname()[1]

            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector())
            real_listen_udp = server.engine.listen_udp
            calls = []

            async def forced_listen_udp(host, port, announce=True,
                                        **kw):
                # first draw lands on the TCP-occupied port (what the
                # kernel did to CI); later draws are honest
                calls.append(port)
                if len(calls) == 1:
                    return await real_listen_udp(host, taken,
                                                 announce=announce, **kw)
                return await real_listen_udp(host, port,
                                             announce=announce, **kw)

            server.engine.listen_udp = forced_listen_udp
            await server.start()
            try:
                assert len(calls) >= 2          # it retried
                assert server.udp_port == server.tcp_port != taken
                # the failed draw was released: only ONE UDP listener
                assert len(server.engine._udp_socks) == 1
                r = await udp_ask(server.udp_port, "web.foo.com", Type.A)
                assert r.rcode == Rcode.NOERROR
                r = await tcp_ask(server.tcp_port, "web.foo.com", Type.A)
                assert r.rcode == Rcode.NOERROR
            finally:
                blocker.close()
                await server.stop()

        asyncio.run(run())

    def test_fixed_port_collision_raises(self):
        """A FIXED port that is TCP-occupied is a real error: no silent
        redraw to a different number, and the UDP draw is released."""
        async def run():
            store, cache = fixture_store()
            blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            blocker.bind(("127.0.0.1", 0))
            blocker.listen(1)
            taken = blocker.getsockname()[1]
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=taken,
                                  collector=MetricsCollector())
            try:
                await server.start()
            except OSError:
                assert server.engine._udp_socks == []
                return True
            finally:
                blocker.close()
                await server.stop()
            return False

        assert asyncio.run(run())

    def test_fixed_udp_port_taken_releases_balancer(self, tmp_path):
        """A fixed UDP port already bound: start() must raise AND
        release the balancer listener opened before the pair bind."""
        async def run():
            store, cache = fixture_store()
            blocker = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            blocker.bind(("127.0.0.1", 0))
            taken = blocker.getsockname()[1]
            sock_path = str(tmp_path / "b.sock")
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=taken,
                                  balancer_socket=sock_path,
                                  collector=MetricsCollector())
            try:
                await server.start()
            except OSError:
                assert server.engine._udp_socks == []
                assert server.engine._unix_servers == []
                return True
            finally:
                blocker.close()
                await server.stop()
            return False

        assert asyncio.run(run())

    def test_concurrent_ephemeral_startups(self):
        """Hammer: many port=0 servers starting concurrently while TCP
        churn occupies ephemeral ports.  Every start must succeed with
        udp_port == tcp_port (probabilistic companion to the
        deterministic collision test above)."""
        async def run():
            store, cache = fixture_store()

            async def one():
                s = await start_server(cache)
                assert s.udp_port == s.tcp_port
                return s

            for _ in range(4):
                servers = await asyncio.gather(*[one() for _ in range(8)])
                for s in servers:
                    r = await udp_ask(s.udp_port, "web.foo.com", Type.A)
                    assert r.rcode == Rcode.NOERROR
                    await s.stop()

        asyncio.run(run())


class TestTcpFrameDeadline:
    def test_byte_trickler_disconnected(self):
        """Slowloris: steady 1-byte-per-interval traffic must NOT reset
        the idle deadline — only a complete frame does (r5 regression
        guard for the bulk-reframe read loop)."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=0.6)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            try:
                t0 = asyncio.get_running_loop().time()
                closed_at = None
                # header promising a 100-byte frame, then 1 byte per
                # interval: bytes keep flowing, the frame never completes
                writer.write(b"\x00\x64")
                for _ in range(20):
                    writer.write(b"\x01")
                    await writer.drain()
                    try:
                        got = await asyncio.wait_for(reader.read(16), 0.25)
                    except (TimeoutError, asyncio.TimeoutError):
                        continue
                    except (ConnectionResetError, BrokenPipeError):
                        closed_at = asyncio.get_running_loop().time()
                        break
                    if got == b"":
                        closed_at = asyncio.get_running_loop().time()
                        break
                assert closed_at is not None, "trickler never disconnected"
                assert closed_at - t0 < 3.0
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass
                await server.stop()

        asyncio.run(run())

    def test_steady_frames_stay_connected(self):
        """Complete frames slower than the byte-level interval but
        faster than the idle deadline keep the connection alive."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache, tcp_idle_timeout=0.6)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            try:
                for qid in range(5):
                    wire = make_query("web.foo.com", Type.A,
                                      qid=qid).encode()
                    writer.write(struct.pack(">H", len(wire)) + wire)
                    await writer.drain()
                    (ln,) = struct.unpack(
                        ">H", await reader.readexactly(2))
                    m = Message.decode(await reader.readexactly(ln))
                    assert m.rcode == Rcode.NOERROR
                    await asyncio.sleep(0.4)   # < deadline per frame
            finally:
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass
                await server.stop()

        asyncio.run(run())


class TestPairBindAnnouncement:
    def test_redraw_announces_only_final_port(self, caplog):
        """The 'service started' lines are the port-discovery contract
        for harnesses (bench/systemd logs): a redrawn (released) draw
        must never be announced — only the secured pair, exactly once
        (the r5 CI failure: dnsblast latched a dead first-draw port)."""
        async def run():
            store, cache = fixture_store()
            blocker = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            blocker.bind(("127.0.0.1", 0))
            blocker.listen(1)
            taken = blocker.getsockname()[1]
            server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                                  datacenter_name="coal",
                                  host="127.0.0.1", port=0,
                                  collector=MetricsCollector())
            real_listen_udp = server.engine.listen_udp
            first = []

            async def forced(host, port, announce=True, **kw):
                if not first:
                    first.append(True)
                    return await real_listen_udp(host, taken,
                                                 announce=announce, **kw)
                return await real_listen_udp(host, port,
                                             announce=announce, **kw)

            server.engine.listen_udp = forced
            await server.start()
            try:
                udp_lines = [r.getMessage() for r in caplog.records
                             if "UDP DNS service started" in r.getMessage()]
                tcp_lines = [r.getMessage() for r in caplog.records
                             if "TCP DNS service started" in r.getMessage()]
                assert udp_lines == \
                    [f"UDP DNS service started on 127.0.0.1:"
                     f"{server.udp_port}"]
                assert tcp_lines == \
                    [f"TCP DNS service started on 127.0.0.1:"
                     f"{server.tcp_port}"]
                assert server.udp_port != taken
            finally:
                blocker.close()
                await server.stop()

        import logging as _logging
        caplog.set_level(_logging.INFO, logger="binder.server")
        asyncio.run(run())


class TestTcpBulkServe:
    def test_mixed_hit_miss_pipelined_chunk(self):
        """One write carrying interleaved zone-served and
        Python-resolved frames: every query must be answered correctly
        by id whatever path served it (the native bulk frame serve
        splits a chunk into C-served hits and surfaced misses)."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.tcp_port)
            names = [("web.foo.com", Type.A),        # zone hit
                     ("nope.example.org", Type.A),   # REFUSED via Python
                     ("web.foo.com", Type.A),        # zone hit
                     ("_pg._tcp.svc.foo.com", Type.SRV),  # zone SRV
                     ("nope2.example.org", Type.A)]  # Python again
            block = b""
            for qid, (name, qt) in enumerate(names, start=1):
                wire = make_query(name, qt, qid=qid).encode()
                block += struct.pack(">H", len(wire)) + wire
            writer.write(block)
            await writer.drain()
            got = {}
            buf = b""
            while len(got) < len(names):
                buf += await reader.read(65536)
                while len(buf) >= 2:
                    (ln,) = struct.unpack(">H", buf[:2])
                    if len(buf) - 2 < ln:
                        break
                    m = Message.decode(buf[2:2 + ln])
                    buf = buf[2 + ln:]
                    got[m.id] = m
            assert got[1].answers[0].address == "192.168.0.1"
            assert got[2].rcode == Rcode.REFUSED
            assert got[3].answers[0].address == "192.168.0.1"
            assert got[4].answers[0].port == 5432
            assert got[5].rcode == Rcode.REFUSED
            writer.close()
            await writer.wait_closed()
            await server.stop()

        asyncio.run(run())
