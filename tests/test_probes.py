"""Probe provider: the USDT analog (lib/server.js:24-29).

Key property under test: lazy argument evaluation — fire() must not
build its arguments when nothing listens (the dtrace .fire(function)
semantics the reference's hot path depends on).
"""
import asyncio

from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache
from binder_tpu.utils.probes import ProbeProvider


class TestProbeProvider:
    def test_disabled_probe_never_evaluates_args(self):
        p = ProbeProvider("t", backend="off")
        probe = p.probe("x")
        assert not probe.enabled
        calls = []
        probe.fire(lambda: calls.append(1))
        assert calls == []

    def test_subscriber_receives_args(self):
        p = ProbeProvider("t", backend="off")
        got = []
        p.subscribe(lambda name, args: got.append((name, args)))
        probe = p.probe("op-req-start")
        assert probe.enabled
        probe.fire(lambda: {"id": 7})
        assert got == [("op-req-start", {"id": 7})]
        p.unsubscribe(p._sinks[0])
        assert not probe.enabled

    def test_failing_argf_or_sink_is_swallowed(self):
        p = ProbeProvider("t", backend="off")
        got = []
        p.subscribe(lambda name, args: 1 / 0)
        p.subscribe(lambda name, args: got.append(args))
        probe = p.probe("x")
        probe.fire(lambda: 1 / 0)   # argf raises: nothing delivered
        probe.fire(lambda: "ok")    # first sink raises: second still runs
        assert got == ["ok"]

    def test_probe_identity(self):
        p = ProbeProvider("t", backend="off")
        assert p.probe("a") is p.probe("a")

    def test_server_fires_start_and_done(self):
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, "foo.com")
            store.put_json("/com/foo/web",
                           {"type": "host", "host": {"address": "10.0.0.1"}})
            store.start_session()
            provider = ProbeProvider("binder", backend="off")
            events = []
            provider.subscribe(lambda name, args: events.append((name, args)))
            server = BinderServer(zk_cache=cache, dns_domain="foo.com",
                                  datacenter_name="dc0", host="127.0.0.1",
                                  port=0, collector=MetricsCollector(),
                                  probes=provider)
            await server.start()

            from binder_tpu.dns import Type, make_query
            loop = asyncio.get_running_loop()
            fut = loop.create_future()

            class P(asyncio.DatagramProtocol):
                def connection_made(self, t):
                    t.sendto(make_query("web.foo.com", Type.A,
                                        qid=77).encode())

                def datagram_received(self, d, a):
                    if not fut.done():
                        fut.set_result(d)

            tr, _ = await loop.create_datagram_endpoint(
                P, remote_addr=("127.0.0.1", server.udp_port))
            try:
                await asyncio.wait_for(fut, 5)
            finally:
                tr.close()
            await server.stop()
            return events

        events = asyncio.run(run())
        names = [n for n, _ in events]
        assert "op-req-start" in names and "op-req-done" in names
        start = dict(events)["op-req-start"]
        done = dict(events)["op-req-done"]
        assert start["name"] == "web.foo.com" and start["id"] == 77
        assert done["rcode"] == "NOERROR" and done["latency_ms"] >= 0
