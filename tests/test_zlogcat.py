"""Tests for the native txnlog decoder (native/zklog/zlogcat).

The reference's zklog.c has zero tests (SURVEY §4: "C code tests: none").
Fixture txnlogs are generated here in the public ZooKeeper jute format:
FileHeader(magic ZKLG, v2, dbid) then [adler32][len][txn][0x42] records.
"""
import json
import os
import struct
import subprocess
import zlib

import pytest

ZLOGCAT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native", "build", "zlogcat")

pytestmark = pytest.mark.skipif(
    not os.path.exists(ZLOGCAT),
    reason="zlogcat not built (make -C native)")


# ---- jute serialization helpers (writer side of the fixture) ----

def jstr(s):
    b = s.encode() if isinstance(s, str) else s
    return struct.pack(">i", len(b)) + b


def txn_header(session, cxid, zxid, time_ms, txn_type):
    return struct.pack(">qiqqi", session, cxid, zxid, time_ms, txn_type)


def create_txn(path, data, ephemeral=False, parent_cversion=None):
    body = jstr(path) + jstr(data)
    body += struct.pack(">i", 1)                    # one ACL entry
    body += struct.pack(">i", 31) + jstr("world") + jstr("anyone")
    body += struct.pack(">?", ephemeral)
    if parent_cversion is not None:
        body += struct.pack(">i", parent_cversion)
    return body


def record(session, cxid, zxid, time_ms, txn_type, body, corrupt_crc=False):
    txn = txn_header(session, cxid, zxid, time_ms, txn_type) + body
    crc = zlib.adler32(txn)
    if corrupt_crc:
        crc ^= 0xFF
    return struct.pack(">qi", crc, len(txn)) + txn + b"\x42"


def write_log(path, records, dbid=7, magic=0x5A4B4C47, version=2,
              padding=64):
    with open(path, "wb") as f:
        f.write(struct.pack(">iiq", magic, version, dbid))
        for r in records:
            f.write(r)
        f.write(b"\x00" * padding)   # preallocated tail


def run(args):
    proc = subprocess.run([ZLOGCAT] + args, capture_output=True, text=True,
                          timeout=30)
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    return proc.returncode, lines, proc.stderr


SESSION_A = 0x100000123456789   # server id 1
SESSION_B = 0x200000123456789   # server id 2


def standard_log(path):
    recs = [
        record(SESSION_A, 1, 0x100000001, 1000, -10,
               struct.pack(">i", 30000)),                       # createSession
        record(SESSION_A, 2, 0x100000002, 1500, 1,
               create_txn("/com/foo/web", b'{"type":"host"}')),  # create
        record(SESSION_A, 3, 0x100000003, 2000, 5,
               jstr("/com/foo/web") + jstr(b'{"type":"host","v":2}')
               + struct.pack(">i", 1)),                          # setData
        record(SESSION_B, 1, 0x100000004, 2500, -10,
               struct.pack(">i", 40000)),                       # createSession
        record(SESSION_A, 4, 0x100000005, 3000, 2,
               jstr("/com/foo/web")),                            # delete
        record(SESSION_A, 5, 0x100000006, 9000, -11, b""),      # closeSession
    ]
    write_log(path, recs)


class TestDecode:
    def test_basic_walk(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        rc, lines, err = run([log])
        assert rc == 0
        assert lines[0]["dbid"] == 7
        types = [l["type"] for l in lines[1:]]
        assert types == ["createSession", "create", "setData",
                         "createSession", "delete", "closeSession"]
        assert "6 txns decoded, 0 bad" in err

    def test_create_fields(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run([log])
        create = lines[2]
        assert create["path"] == "/com/foo/web"
        assert create["ephemeral"] is False
        assert create["data"].startswith('{"type":"host"}')

    def test_session_duration(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run([log])
        close = lines[-1]
        assert close["type"] == "closeSession"
        assert close["sessionDurationMs"] == 8000   # 9000 - 1000

    def test_open_session_dump(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run(["-S", log])
        opens = [l for l in lines if "openSession" in l]
        assert len(opens) == 1
        assert opens[0]["openSession"] == f"0x{SESSION_B:x}"
        assert opens[0]["serverId"] == 2

    def test_time_filter(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run(["-t", "1400-2600", log])
        types = [l["type"] for l in lines[1:]]
        assert types == ["create", "setData", "createSession"]

    def test_session_filter(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run(["-s", str(SESSION_B), log])
        assert [l["type"] for l in lines[1:]] == ["createSession"]

    def test_server_id_filter(self, tmp_path):
        log = str(tmp_path / "log.1")
        standard_log(log)
        _, lines, _ = run(["-z", "2", log])
        assert [l["type"] for l in lines[1:]] == ["createSession"]

    def test_multi_txn(self, tmp_path):
        sub1 = txn_header(0, 0, 0, 0, 0)[:0]  # multi sub-txns have no hdr
        inner_create = create_txn("/a", b"x", parent_cversion=1)
        inner_delete = jstr("/b")
        body = struct.pack(">i", 2)
        body += struct.pack(">i", 1) + jstr(inner_create)
        body += struct.pack(">i", 2) + jstr(inner_delete)
        log = str(tmp_path / "log.m")
        write_log(log, [record(SESSION_A, 1, 1, 100, 14, body)])
        _, lines, err = run([log])
        multi = lines[1]
        assert multi["type"] == "multi"
        assert [op["type"] for op in multi["ops"]] == ["create", "delete"]
        assert multi["ops"][1]["path"] == "/b"


class TestRobustness:
    def test_bad_magic_rejected(self, tmp_path):
        log = str(tmp_path / "bad")
        write_log(log, [], magic=0x41424344)
        rc, lines, err = run([log])
        assert rc == 1 and "bad file header" in err

    def test_corrupt_crc_counted(self, tmp_path):
        log = str(tmp_path / "log.c")
        write_log(log, [
            record(SESSION_A, 1, 1, 100, 2, jstr("/a"), corrupt_crc=True),
            record(SESSION_A, 2, 2, 200, 2, jstr("/b")),
        ])
        rc, lines, err = run([log])
        # corrupt record skipped, good one still decoded
        assert [l["type"] for l in lines[1:]] == ["delete"]
        assert "1 bad" in err

    def test_truncated_record_does_not_overread(self, tmp_path):
        log = str(tmp_path / "log.t")
        good = record(SESSION_A, 1, 1, 100, 2, jstr("/a"))
        # claim a huge length with a short file
        bogus = struct.pack(">qi", 123, 99999) + b"\x01\x02"
        with open(log, "wb") as f:
            f.write(struct.pack(">iiq", 0x5A4B4C47, 2, 1))
            f.write(good)
            f.write(bogus)
        rc, lines, err = run([log])
        assert [l["type"] for l in lines[1:]] == ["delete"]
        assert "overruns" in err

    def test_zero_padding_terminates(self, tmp_path):
        log = str(tmp_path / "log.p")
        write_log(log, [record(SESSION_A, 1, 1, 100, 2, jstr("/a"))],
                  padding=4096)
        rc, lines, err = run([log])
        assert rc == 0
        assert "1 txns decoded, 0 bad" in err

    def test_corrupt_multi_still_valid_json(self, tmp_path):
        # second sub-op buffer truncated mid-record
        body = struct.pack(">i", 2)
        body += struct.pack(">i", 2) + jstr("/ok")
        body += struct.pack(">i", 2) + struct.pack(">i", 50) + b"short"
        log = str(tmp_path / "log.cm")
        write_log(log, [record(SESSION_A, 1, 1, 100, 14, body)])
        rc, lines, err = run([log])
        # every emitted line parsed as JSON (run() would have thrown) and
        # the broken record is flagged
        assert any(l.get("decodeError") for l in lines)

    def test_negative_session_filter(self, tmp_path):
        neg_session = -0x00FFFFFFFFFFFF00  # sign bit set in high byte
        log = str(tmp_path / "log.ns")
        write_log(log, [
            record(neg_session, 1, 1, 100, 2, jstr("/a")),
            record(SESSION_A, 1, 2, 200, 2, jstr("/b")),
        ])
        _, lines, _ = run(["-s", str(neg_session), log])
        assert len(lines) == 2 and lines[1]["path"] == "/a"
