"""Tests for zone precompilation (fpcore.h zone table).

The zone table serves finished answers for the dominant record shapes
(host A, PTR) inside the C UDP drain, filled from the store mirror at
server start and on every mutation — so even the FIRST query for a name
never surfaces to Python.  The reference resolves every cold name per
query (lib/server.js:136).

Layers here:
- differential: every zone-served response must be byte-identical to
  the same server's generic-path response (zonePrecompile off), id
  aside — the zone can never answer differently, only faster;
- coherence: store mutations re-point zone answers through the same
  tag-invalidation path as the caches; deletions fall back to Python;
- policy: shapes the raw lane declines (service records, doubled
  dnsDomain suffixes, non-IN classes) are never zone-served.
"""
import asyncio

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

fastio = pytest.importorskip(
    "binder_tpu._binderfastio",
    reason="fastio extension not built (make -C native)")
if not hasattr(fastio, "fastpath_zone_put"):
    pytest.skip("fastio extension predates the zone table; rebuild",
                allow_module_level=True)

DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/ttlhost",
                   {"type": "host", "ttl": 120,
                    "host": {"address": "10.9.9.9", "ttl": 77}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(2):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(cache, **kw):
    kw.setdefault("query_log", False)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1",
                          port=0, collector=MetricsCollector(), **kw)
    await server.start()
    return server


async def udp_ask_raw(port, wire, timeout=2.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport
            transport.sendto(wire)

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()


def zone_stats(server):
    return fastio.fastpath_stats(server._fastpath)


def _mixed_case(wire: bytes, lower: bytes, mixed: bytes) -> bytes:
    """Patch a query wire with true mixed-case qname bytes — make_query
    normalizes to lowercase, so dns0x20 shapes must be crafted at the
    wire level or the probe is vacuous."""
    assert lower in wire and lower.lower() == mixed.lower()
    return wire.replace(lower, mixed)


PROBES = [
    ("A no-edns", make_query("web.foo.com", Type.A, qid=1,
                             edns_payload=None).encode()),
    ("A rd", make_query("web.foo.com", Type.A, qid=2, rd=True,
                        edns_payload=None).encode()),
    ("A edns", make_query("web.foo.com", Type.A, qid=3,
                          edns_payload=1400).encode()),
    ("A 0x20", _mixed_case(
        make_query("web.foo.com", Type.A, qid=4).encode(),
        b"\x03web\x03foo\x03com", b"\x03WeB\x03fOo\x03CoM")),
    ("A ttl precedence", make_query("ttlhost.foo.com", Type.A,
                                    qid=5).encode()),
    ("PTR", make_query("1.0.168.192.in-addr.arpa", Type.PTR,
                       qid=6).encode()),
    ("PTR 0x20", _mixed_case(
        make_query("9.9.9.10.in-addr.arpa", Type.PTR, qid=7).encode(),
        b"\x07in-addr\x04arpa", b"\x07IN-aDdR\x04ArPa")),
]


class TestZoneDifferential:
    def test_zone_answers_equal_generic_and_never_reach_python(self):
        """Byte-differential: for every probe shape the zone-enabled
        server's FIRST response equals the zone-disabled server's, and
        it really came from the zone (zone_hits advanced, no Python
        resolve counted)."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                for label, wire in PROBES:
                    before = zone_stats(on)["zone_hits"]
                    got = await udp_ask_raw(on.udp_port, wire)
                    want = await udp_ask_raw(off.udp_port, wire)
                    assert got == want, label
                    assert zone_stats(on)["zone_hits"] == before + 1, \
                        (label, "expected a zone serve")
                    if "0x20" in label:
                        # the requester's exact mixed-case bytes echo
                        assert wire[12:24] in got, label
                # and the decoded answer is actually right
                r = Message.decode(
                    await udp_ask_raw(
                        on.udp_port,
                        make_query("web.foo.com", Type.A, qid=9).encode()))
                assert r.rcode == Rcode.NOERROR
                assert r.answers[0].address == "192.168.0.1"
                # deepest-object-wins TTL precedence baked in at push
                r = Message.decode(
                    await udp_ask_raw(
                        on.udp_port,
                        make_query("ttlhost.foo.com", Type.A,
                                   qid=10).encode()))
                assert r.answers[0].ttl == 77
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_shapes_the_zone_declines_are_not_zone_served(self):
        """Negative SRV shapes, missing names, and non-precompiled
        qtypes go through Python; the zone table must not touch them."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                probes = (
                    # SRV with the WRONG srvce/proto: NXDOMAIN (engine)
                    make_query("_wrong._tcp.svc.foo.com", Type.SRV,
                               qid=21),
                    # SRV on a non-service name we own: NODATA + SOA
                    make_query("_pg._tcp.web.foo.com", Type.SRV, qid=22),
                    make_query("absent.foo.com", Type.A, qid=23),
                    make_query("web.foo.com", Type.AAAA, qid=24),
                )
                for q in probes:
                    before = zone_stats(server)["zone_hits"]
                    resp = Message.decode(
                        await udp_ask_raw(server.udp_port, q.encode()))
                    assert zone_stats(server)["zone_hits"] == before, \
                        q.questions[0]
                    assert resp.id == q.id
            finally:
                await server.stop()

        asyncio.run(run())

    def test_srv_zone_served_content_equals_generic(self):
        """The registered SRV qname is precompiled — answers (per member
        per port, service TTL) and A additionals (member TTL) equal the
        generic path's in content; EDNS queries get the OPT appended as
        the last additional."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                def shape(r):
                    srv = sorted((a.name, a.ttl, a.priority, a.weight,
                                  a.port, a.target) for a in r.answers)
                    add = sorted((a.name, a.ttl, a.address)
                                 for a in r.additionals
                                 if hasattr(a, "address"))
                    return r.rcode, srv, add

                for qid, kw in ((31, {"edns_payload": None}),
                                (32, {"edns_payload": 1400})):
                    q = make_query("_pg._tcp.svc.foo.com", Type.SRV,
                                   qid=qid, **kw)
                    before = zone_stats(on)["zone_hits"]
                    got = Message.decode(
                        await udp_ask_raw(on.udp_port, q.encode()))
                    want = Message.decode(
                        await udp_ask_raw(off.udp_port, q.encode()))
                    assert zone_stats(on)["zone_hits"] == before + 1, kw
                    assert shape(got) == shape(want), kw
                    assert len(got.answers) == 2
                    assert {a.target for a in got.answers} == \
                        {"lb0.svc.foo.com", "lb1.svc.foo.com"}
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_srv_member_mutation_repoints_through_alien_table(self):
        """SRV entries are tagged with the service NODE name (not their
        qname): a member mutation's parent tag must drop and re-push
        them through the C side's alien-table scan."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=41).encode()))
                assert len(r.answers) == 2
                store.put_json("/com/foo/svc/lb9",
                               {"type": "load_balancer",
                                "load_balancer": {"address": "10.0.1.9",
                                                  "ports": [100, 200]}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=42).encode()))
                assert zone_stats(server)["zone_hits"] == before + 1
                # 2 original members (1 port each) + new member x2 ports
                assert len(r.answers) == 4
                ports = {a.port for a in r.answers
                         if a.target == "lb9.svc.foo.com"}
                assert ports == {100, 200}
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_a_rotation_zone_served(self):
        """Service plain-A answers come precompiled: full member set per
        answer (content equal to the generic path's), served natively,
        rotating so every member leads over repeated queries."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                def addrsets(r):
                    return sorted((a.address, a.ttl) for a in r.answers)

                want = Message.decode(await udp_ask_raw(
                    off.udp_port,
                    make_query("svc.foo.com", Type.A, qid=90).encode()))
                leads = set()
                for i in range(6):
                    before = zone_stats(on)["zone_hits"]
                    got = Message.decode(await udp_ask_raw(
                        on.udp_port,
                        make_query("svc.foo.com", Type.A,
                                   qid=91 + i).encode()))
                    assert zone_stats(on)["zone_hits"] == before + 1
                    assert got.rcode == Rcode.NOERROR
                    assert addrsets(got) == addrsets(want)
                    leads.add(got.answers[0].address)
                # both members lead at least once (cyclic rotation)
                assert leads == {"10.0.1.1", "10.0.1.2"}
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_service_member_mutation_repoints_rotation(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=95).encode()))
                assert {a.address for a in r.answers} == \
                    {"10.0.1.1", "10.0.1.2"}
                store.put_json("/com/foo/svc/lb2",
                               {"type": "load_balancer",
                                "load_balancer": {"address": "10.0.1.3"}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=96).encode()))
                assert {a.address for a in r.answers} == \
                    {"10.0.1.1", "10.0.1.2", "10.0.1.3"}
                assert zone_stats(server)["zone_hits"] == before + 1
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_min_ttl_matches_generic(self):
        """min(service-ttl, member-ttl) parity (lib/server.js:403-414)
        must be baked into the precompiled bodies."""
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/tsvc", {
                "type": "service", "ttl": 100,
                "service": {"srvce": "_x", "proto": "_tcp", "port": 1}})
            store.put_json("/com/foo/tsvc/m0",
                           {"type": "load_balancer", "ttl": 40,
                            "load_balancer": {"address": "10.3.0.1"}})
            store.put_json("/com/foo/tsvc/m1",
                           {"type": "load_balancer",
                            "load_balancer": {"address": "10.3.0.2"}})
            store.start_session()
            server = await start_server(cache)
            try:
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("tsvc.foo.com", Type.A, qid=97).encode()))
                assert zone_stats(server)["zone_hits"] == before + 1
                ttls = {a.address: a.ttl for a in r.answers}
                assert ttls == {"10.3.0.1": 40, "10.3.0.2": 100}
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_with_invalid_member_declines_to_python(self):
        """A structurally invalid member makes the generic path SERVFAIL
        mid-set; the zone must decline rather than answer differently."""
        async def run():
            store, cache = fixture_store()
            store.put_json("/com/foo/svc/bad",
                           {"type": "load_balancer",
                            "load_balancer": "not-a-dict"})
            server = await start_server(cache)
            try:
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=98).encode()))
                assert zone_stats(server)["zone_hits"] == before
                assert r.rcode == Rcode.SERVFAIL
            finally:
                await server.stop()

        asyncio.run(run())

    def test_doubled_suffix_policy_not_pushed(self):
        """Names the resolver REFUSES by suffix policy (doubled
        dnsDomain) must never be precompiled even if a store node
        exists at that domain."""
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            # a real znode whose domain is foo.com.foo.com
            store.put_json("/com/foo/com/foo",
                           {"type": "host",
                            "host": {"address": "10.1.2.3"}})
            store.start_session()
            server = await start_server(cache)
            try:
                q = make_query("foo.com.foo.com", Type.A, qid=31)
                resp = Message.decode(
                    await udp_ask_raw(server.udp_port, q.encode()))
                assert resp.rcode == Rcode.REFUSED
                assert zone_stats(server)["zone_hits"] == 0
            finally:
                await server.stop()

        asyncio.run(run())


class TestZoneCoherence:
    def test_mutation_repoints_zone_answer(self):
        """A store mutation must re-point the precompiled answer (drop
        via tag invalidation + fresh push from the same event) — and the
        NEW answer is still zone-served, not a Python fallback."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=41).encode()))
                assert r.answers[0].address == "192.168.0.1"

                store.put_json("/com/foo/web",
                               {"type": "host",
                                "host": {"address": "192.168.0.99"}})
                await asyncio.sleep(0)   # watch delivery (sync store)

                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=42).encode()))
                assert r.answers[0].address == "192.168.0.99"
                assert zone_stats(server)["zone_hits"] == before + 1

                # the reverse tree re-pointed too: old PTR gone, new live
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("99.0.168.192.in-addr.arpa", Type.PTR,
                               qid=43).encode()))
                assert r.rcode == Rcode.NOERROR
                assert r.answers[0].target == "web.foo.com"
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("1.0.168.192.in-addr.arpa", Type.PTR,
                               qid=44).encode()))
                assert r.rcode == Rcode.REFUSED
            finally:
                await server.stop()

        asyncio.run(run())

    def test_mutation_burst_bounded_drain_stays_fresh(self):
        """A burst of mutations larger than the zone drain batch (r5
        churn coalescing): answers must be FRESH immediately (raw-lane /
        generic fallback while the name's re-push is still queued in the
        dirty set) and zone-served again once the bounded drain catches
        up — never stale in between."""
        async def run():
            store, cache = fixture_store()
            n = BinderServer._ZONE_DRAIN_BATCH * 2 + 10
            for i in range(n):
                store.put_json(f"/com/foo/h{i}",
                               {"type": "host",
                                "host": {"address": f"10.7.{i // 250}.{i % 250 + 1}"}})
            server = await start_server(cache)
            try:
                # mutate every host in one synchronous burst
                for i in range(n):
                    store.put_json(f"/com/foo/h{i}",
                                   {"type": "host",
                                    "host": {"address":
                                             f"10.8.{i // 250}.{i % 250 + 1}"}})
                assert len(server._zone_dirty) >= n
                # immediately (zero loop turns for the drain to run a
                # full catch-up): every answer must already be the NEW
                # address, whatever path serves it
                for i in (0, n // 2, n - 1):
                    r = Message.decode(await udp_ask_raw(
                        server.udp_port,
                        make_query(f"h{i}.foo.com", Type.A,
                                   qid=i).encode()))
                    assert r.answers[0].address == \
                        f"10.8.{i // 250}.{i % 250 + 1}"
                # let the bounded drain finish, then everything is
                # zone-served again
                for _ in range(10):
                    if not server._zone_dirty:
                        break
                    await asyncio.sleep(0)
                assert not server._zone_dirty
                assert not server._zone_drain_pending
                before = zone_stats(server)["zone_hits"]
                for i in (1, n - 2):
                    r = Message.decode(await udp_ask_raw(
                        server.udp_port,
                        make_query(f"h{i}.foo.com", Type.A,
                                   qid=1000 + i).encode()))
                    assert r.answers[0].address == \
                        f"10.8.{i // 250}.{i % 250 + 1}"
                assert zone_stats(server)["zone_hits"] == before + 2
            finally:
                await server.stop()

        asyncio.run(run())

    def test_deleted_node_falls_back_to_python_refused(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                store.delete("/com/foo/web")
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=51).encode()))
                assert r.rcode == Rcode.REFUSED
                assert zone_stats(server)["zone_hits"] == before
            finally:
                await server.stop()

        asyncio.run(run())

    def test_type_change_host_to_service_drops_zone_entry(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                store.put_json("/com/foo/web", {
                    "type": "service",
                    "service": {"srvce": "_x", "proto": "_tcp",
                                "port": 1}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=61).encode()))
                # service with no children: NODATA-ish per engine policy;
                # what matters here is the zone did NOT serve stale host
                assert zone_stats(server)["zone_hits"] == before
                assert not r.answers or \
                    r.answers[0].address != "192.168.0.1"
            finally:
                await server.stop()

        asyncio.run(run())

    def test_zone_precompile_off_serves_nothing_from_zone(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache, zone_precompile=False)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=71).encode()))
                assert r.answers[0].address == "192.168.0.1"
                assert zone_stats(server)["zone_hits"] == 0
                assert zone_stats(server)["zone_entries"] == 0
            finally:
                await server.stop()

        asyncio.run(run())

    def test_zone_serves_fold_into_metrics(self):
        """Zone serves surface in the Prometheus scrape: the per-qtype
        request counter advances and binder_zone_serves counts them."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                for i in range(3):
                    await udp_ask_raw(
                        server.udp_port,
                        make_query("web.foo.com", Type.A,
                                   qid=80 + i).encode())
                text = server.collector.expose()
                assert 'binder_zone_serves_total 3' in text.replace(
                    "binder_zone_serves 3", "binder_zone_serves_total 3")
                # residency gauges expose the native tables' state:
                # fixture has web + ttlhost (A), their PTRs, svc A, SRV
                import re as _re
                m = _re.search(r"binder_zone_entries (\d+)", text)
                assert m and int(m.group(1)) >= 6, text[:400]
                assert _re.search(r"binder_zone_bytes [1-9]", text)
            finally:
                await server.stop()

        asyncio.run(run())


class TestZoneChurnSoak:
    def test_randomized_churn_read_your_writes(self):
        """Randomized mutation soak over the live UDP stack with the
        native path fully engaged: after every store mutation the next
        query for the touched name must reflect it — whether it is
        served from the zone, the caches, or Python.  Pins the
        drop-then-repush coherence of _on_store_invalidate under
        arbitrary interleavings (the single-shot repoint tests cannot
        reach orderings a random walk does)."""
        import random as _random

        async def run():
            rng = _random.Random(0x5A)
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            hosts = {f"h{i}": f"10.50.0.{i + 1}" for i in range(8)}
            for h, ip in hosts.items():
                store.put_json(f"/com/foo/{h}",
                               {"type": "host", "host": {"address": ip}})
            svc_members = {f"m{i}": f"10.51.0.{i + 1}" for i in range(3)}
            store.put_json("/com/foo/zsvc", {
                "type": "service",
                "service": {"srvce": "_z", "proto": "_tcp", "port": 9}})
            for m, ip in svc_members.items():
                store.put_json(f"/com/foo/zsvc/{m}",
                               {"type": "load_balancer",
                                "load_balancer": {"address": ip}})
            store.start_session()
            server = await start_server(cache)
            try:
                for step in range(120):
                    op = rng.randrange(4)
                    if op == 0:         # re-address a host
                        h = rng.choice(sorted(hosts))
                        hosts[h] = f"10.50.{rng.randrange(1, 200)}." \
                                   f"{rng.randrange(1, 200)}"
                        store.put_json(f"/com/foo/{h}",
                                       {"type": "host",
                                        "host": {"address": hosts[h]}})
                    elif op == 1 and len(hosts) > 2:   # delete a host
                        h = rng.choice(sorted(hosts))
                        del hosts[h]
                        store.delete(f"/com/foo/{h}")
                    elif op == 2:       # (re-)add a host
                        h = f"h{rng.randrange(12)}"
                        hosts[h] = f"10.50.{rng.randrange(1, 200)}." \
                                   f"{rng.randrange(1, 200)}"
                        store.put_json(f"/com/foo/{h}",
                                       {"type": "host",
                                        "host": {"address": hosts[h]}})
                    else:               # churn a service member
                        m = rng.choice(sorted(svc_members))
                        svc_members[m] = f"10.51.{rng.randrange(1, 200)}" \
                                         f".{rng.randrange(1, 200)}"
                        store.put_json(f"/com/foo/zsvc/{m}",
                                       {"type": "load_balancer",
                                        "load_balancer":
                                        {"address": svc_members[m]}})
                    await asyncio.sleep(0)   # watch delivery

                    # read-your-writes on a random live host
                    if hosts:
                        h = rng.choice(sorted(hosts))
                        r = Message.decode(await udp_ask_raw(
                            server.udp_port,
                            make_query(f"{h}.foo.com", Type.A,
                                       qid=step + 1).encode()))
                        assert r.rcode == Rcode.NOERROR, (step, h)
                        assert r.answers[0].address == hosts[h], (step, h)
                    # service plain-A and SRV reflect the member set
                    r = Message.decode(await udp_ask_raw(
                        server.udp_port,
                        make_query("zsvc.foo.com", Type.A,
                                   qid=1000 + step).encode()))
                    assert {a.address for a in r.answers} == \
                        set(svc_members.values()), step
                    r = Message.decode(await udp_ask_raw(
                        server.udp_port,
                        make_query("_z._tcp.zsvc.foo.com", Type.SRV,
                                   qid=2000 + step).encode()))
                    assert {a.address for a in r.additionals
                            if hasattr(a, "address")} == \
                        set(svc_members.values()), step

                # the soak must have exercised the native zone path
                # heavily, not just Python fallbacks
                assert zone_stats(server)["zone_hits"] > 200
            finally:
                await server.stop()

        asyncio.run(run())


class TestServeWireLanes:
    def test_tcp_lane_served_natively(self):
        """TCP queries for precompiled shapes are answered by
        fastpath_serve_wire without entering the Python resolver, with
        content equal to the zone-disabled server's TCP answer."""
        import struct as _struct

        async def tcp_ask_raw(port, wire):
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", port)
            writer.write(_struct.pack(">H", len(wire)) + wire)
            await writer.drain()
            (length,) = _struct.unpack(">H",
                                       await reader.readexactly(2))
            data = await reader.readexactly(length)
            writer.close()
            await writer.wait_closed()
            return data

        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                q = make_query("web.foo.com", Type.A, qid=61).encode()
                before = zone_stats(on)["zone_hits"]
                got = await tcp_ask_raw(on.tcp_port, q)
                want = await tcp_ask_raw(off.tcp_port, q)
                assert got == want
                assert zone_stats(on)["zone_hits"] == before + 1
                # SRV over TCP too (alien-table lookup through the
                # wire entry point)
                q = make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=62).encode()
                before = zone_stats(on)["zone_hits"]
                r = Message.decode(await tcp_ask_raw(on.tcp_port, q))
                assert r.rcode == Rcode.NOERROR and len(r.answers) == 2
                assert zone_stats(on)["zone_hits"] == before + 1
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_udp_lane_does_not_double_lookup(self):
        """Direct-UDP misses already checked the native path inside the
        drain; _handle_raw must not consult it again (lookups would
        double and skew the hit-rate metric)."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                before = zone_stats(server)["lookups"]
                await udp_ask_raw(
                    server.udp_port,
                    make_query("absent.foo.com", Type.A, qid=71).encode())
                after = zone_stats(server)["lookups"]
                assert after == before + 1, (before, after)
            finally:
                await server.stop()

        asyncio.run(run())

    def test_tcp_gate_closed_stays_python(self):
        """With per-query logging on (the fastpath gate), TCP queries
        must surface to Python like everything else."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache, query_log=True)
            try:
                import struct as _struct
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", server.tcp_port)
                wire = make_query("web.foo.com", Type.A, qid=81).encode()
                writer.write(_struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                (length,) = _struct.unpack(
                    ">H", await reader.readexactly(2))
                r = Message.decode(await reader.readexactly(length))
                writer.close()
                await writer.wait_closed()
                assert r.answers[0].address == "192.168.0.1"
                assert zone_stats(server)["zone_hits"] == 0
                assert zone_stats(server)["lookups"] == 0
            finally:
                await server.stop()

        asyncio.run(run())


class TestTruncationNotReplayedOverTcp:
    def test_tc_cached_udp_response_not_served_to_tcp(self):
        """An oversize answer set truncates for a no-EDNS UDP client
        (TC=1, answers emptied) and that TC wire lands in the native
        answer cache — correct for UDP repeats.  A TCP client asking
        the byte-identical question must still get the FULL answer set:
        the wire-serve entry declines truncated wires and Python (whose
        cache keys carry transport semantics) answers."""
        import struct as _struct

        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/big", {
                "type": "service",
                "service": {"srvce": "_b", "proto": "_tcp", "port": 1}})
            n_members = 40          # 40 x 16B answers ≈ 640B > 512
            for i in range(n_members):
                store.put_json(f"/com/foo/big/m{i:02d}",
                               {"type": "load_balancer",
                                "load_balancer":
                                {"address": f"10.60.{i // 250}.{i + 1}"}})
            store.start_session()
            server = await start_server(cache)
            try:
                wire = make_query("big.foo.com", Type.A, qid=90,
                                  edns_payload=None).encode()
                # UDP: truncated (no EDNS ceiling), TC wire now cached
                # rotatable entries only complete (and push natively)
                # after the full variant set is collected — resolve
                # enough times for the TC wire to reach the C cache
                # one extra repeat: promotion to the C cache happens on
                # the completed entry's first hit (r5)
                for _ in range(9):
                    u = Message.decode(
                        await udp_ask_raw(server.udp_port, wire))
                    assert u.tc and not u.answers
                # the TC wire really is native-cached: the repeat UDP
                # query is a C hit and still TC (correct for UDP) —
                # without this, the TCP assertion below passes vacuously
                before = zone_stats(server)["hits"]
                u2 = Message.decode(
                    await udp_ask_raw(server.udp_port, wire))
                assert u2.tc
                assert zone_stats(server)["hits"] == before + 1
                # byte-identical question over TCP: full answer set
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", server.tcp_port)
                writer.write(_struct.pack(">H", len(wire)) + wire)
                await writer.drain()
                (length,) = _struct.unpack(
                    ">H", await reader.readexactly(2))
                t = Message.decode(await reader.readexactly(length))
                writer.close()
                await writer.wait_closed()
                assert not t.tc
                assert len(t.answers) == n_members, len(t.answers)
            finally:
                await server.stop()

        asyncio.run(run())


class TestZoneEpochRebuild:
    def test_session_rebuild_repoints_zone_via_epoch(self):
        """A (re)session rebuild bumps the mirror epoch: pre-rebuild
        zone entries must never serve again (lazy epoch drop), and the
        re-fired watch deliveries re-push fresh entries under the new
        epoch — queries stay correct across the whole transition, and
        post-rebuild serves are native again."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=11).encode()))
                assert r.answers[0].address == "192.168.0.1"
                old_epoch = cache.epoch

                # mutate + rebuild back-to-back: the rebuild's re-fired
                # data deliveries must repopulate with CURRENT data
                store.put_json("/com/foo/web",
                               {"type": "host",
                                "host": {"address": "192.168.0.55"}})
                cache.rebuild()
                await asyncio.sleep(0)   # watch re-delivery (sync store)
                assert cache.epoch == old_epoch + 1

                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=12).encode()))
                assert r.answers[0].address == "192.168.0.55"
                # served natively under the NEW epoch, not via Python
                assert zone_stats(server)["zone_hits"] == before + 1
                # SRV (alien table) survived the transition too
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=13).encode()))
                assert r.rcode == Rcode.NOERROR and len(r.answers) == 2
            finally:
                await server.stop()

        asyncio.run(run())


class TestZoneDatabaseAndBalancerLane:
    def test_database_record_zone_served_differentially(self):
        """Database records (A from the primary URL's hostname,
        engine.resolve's database branch) precompile when the hostname
        is a canonical IPv4; a hostname that is NOT an address stays in
        Python (whatever it does there, the zone must not differ)."""
        async def run():
            def stores():
                store = FakeStore()
                cache = MirrorCache(store, DOMAIN)
                store.put_json("/com/foo/pg", {
                    "type": "database", "ttl": 45,
                    "database": {"primary":
                                 "tcp://10.4.4.4:5432/moray"}})
                store.put_json("/com/foo/pgname", {
                    "type": "database",
                    "database": {"primary":
                                 "tcp://pg.example.net:5432/moray"}})
                # non-string primary: must decline quietly, not
                # traceback through the mutation path (urlparse raises
                # AttributeError on non-str)
                store.put_json("/com/foo/pgbad", {
                    "type": "database", "database": {"primary": 45}})
                store.start_session()
                return cache

            on = await start_server(stores())
            off = await start_server(stores(), zone_precompile=False)
            try:
                wire = make_query("pg.foo.com", Type.A, qid=51).encode()
                before = zone_stats(on)["zone_hits"]
                got = await udp_ask_raw(on.udp_port, wire)
                want = await udp_ask_raw(off.udp_port, wire)
                assert got == want
                assert zone_stats(on)["zone_hits"] == before + 1
                r = Message.decode(got)
                assert r.answers[0].address == "10.4.4.4"
                assert r.answers[0].ttl == 45

                # non-IP primary hostname and non-string primary: never
                # precompiled; responses still agree with the generic
                # path
                for qid, name in ((52, "pgname.foo.com"),
                                  (53, "pgbad.foo.com")):
                    wire = make_query(name, Type.A, qid=qid).encode()
                    before = zone_stats(on)["zone_hits"]
                    got = await udp_ask_raw(on.udp_port, wire)
                    want = await udp_ask_raw(off.udp_port, wire)
                    assert got == want, name
                    assert zone_stats(on)["zone_hits"] == before, name
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_balancer_lane_zone_served(self):
        """Queries arriving over the balancer socket protocol (a
        balancer-fronted backend's only lane) are zone-served through
        the wire entry point without touching the Python resolver."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                out = []
                wire = make_query("web.foo.com", Type.A, qid=61).encode()
                before = zone_stats(server)["zone_hits"]
                server.engine._handle_raw(
                    wire, ("10.0.0.9", 5353), "balancer", out.append,
                    client_transport="udp")
                assert out, "no response emitted"
                assert zone_stats(server)["zone_hits"] == before + 1
                r = Message.decode(out[0])
                assert r.id == 61
                assert r.answers[0].address == "192.168.0.1"
            finally:
                await server.stop()

        asyncio.run(run())
