"""Tests for zone precompilation (fpcore.h zone table).

The zone table serves finished answers for the dominant record shapes
(host A, PTR) inside the C UDP drain, filled from the store mirror at
server start and on every mutation — so even the FIRST query for a name
never surfaces to Python.  The reference resolves every cold name per
query (lib/server.js:136).

Layers here:
- differential: every zone-served response must be byte-identical to
  the same server's generic-path response (zonePrecompile off), id
  aside — the zone can never answer differently, only faster;
- coherence: store mutations re-point zone answers through the same
  tag-invalidation path as the caches; deletions fall back to Python;
- policy: shapes the raw lane declines (service records, doubled
  dnsDomain suffixes, non-IN classes) are never zone-served.
"""
import asyncio

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

fastio = pytest.importorskip(
    "binder_tpu._binderfastio",
    reason="fastio extension not built (make -C native)")
if not hasattr(fastio, "fastpath_zone_put"):
    pytest.skip("fastio extension predates the zone table; rebuild",
                allow_module_level=True)

DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/ttlhost",
                   {"type": "host", "ttl": 120,
                    "host": {"address": "10.9.9.9", "ttl": 77}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(2):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(cache, **kw):
    kw.setdefault("query_log", False)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1",
                          port=0, collector=MetricsCollector(), **kw)
    await server.start()
    return server


async def udp_ask_raw(port, wire, timeout=2.0):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class Proto(asyncio.DatagramProtocol):
        def connection_made(self, transport):
            self.transport = transport
            transport.sendto(wire)

        def datagram_received(self, data, addr):
            if not fut.done():
                fut.set_result(data)

    transport, _ = await loop.create_datagram_endpoint(
        Proto, remote_addr=("127.0.0.1", port))
    try:
        return await asyncio.wait_for(fut, timeout)
    finally:
        transport.close()


def zone_stats(server):
    return fastio.fastpath_stats(server._fastpath)


def _mixed_case(wire: bytes, lower: bytes, mixed: bytes) -> bytes:
    """Patch a query wire with true mixed-case qname bytes — make_query
    normalizes to lowercase, so dns0x20 shapes must be crafted at the
    wire level or the probe is vacuous."""
    assert lower in wire and lower.lower() == mixed.lower()
    return wire.replace(lower, mixed)


PROBES = [
    ("A no-edns", make_query("web.foo.com", Type.A, qid=1,
                             edns_payload=None).encode()),
    ("A rd", make_query("web.foo.com", Type.A, qid=2, rd=True,
                        edns_payload=None).encode()),
    ("A edns", make_query("web.foo.com", Type.A, qid=3,
                          edns_payload=1400).encode()),
    ("A 0x20", _mixed_case(
        make_query("web.foo.com", Type.A, qid=4).encode(),
        b"\x03web\x03foo\x03com", b"\x03WeB\x03fOo\x03CoM")),
    ("A ttl precedence", make_query("ttlhost.foo.com", Type.A,
                                    qid=5).encode()),
    ("PTR", make_query("1.0.168.192.in-addr.arpa", Type.PTR,
                       qid=6).encode()),
    ("PTR 0x20", _mixed_case(
        make_query("9.9.9.10.in-addr.arpa", Type.PTR, qid=7).encode(),
        b"\x07in-addr\x04arpa", b"\x07IN-aDdR\x04ArPa")),
]


class TestZoneDifferential:
    def test_zone_answers_equal_generic_and_never_reach_python(self):
        """Byte-differential: for every probe shape the zone-enabled
        server's FIRST response equals the zone-disabled server's, and
        it really came from the zone (zone_hits advanced, no Python
        resolve counted)."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                for label, wire in PROBES:
                    before = zone_stats(on)["zone_hits"]
                    got = await udp_ask_raw(on.udp_port, wire)
                    want = await udp_ask_raw(off.udp_port, wire)
                    assert got == want, label
                    assert zone_stats(on)["zone_hits"] == before + 1, \
                        (label, "expected a zone serve")
                    if "0x20" in label:
                        # the requester's exact mixed-case bytes echo
                        assert wire[12:24] in got, label
                # and the decoded answer is actually right
                r = Message.decode(
                    await udp_ask_raw(
                        on.udp_port,
                        make_query("web.foo.com", Type.A, qid=9).encode()))
                assert r.rcode == Rcode.NOERROR
                assert r.answers[0].address == "192.168.0.1"
                # deepest-object-wins TTL precedence baked in at push
                r = Message.decode(
                    await udp_ask_raw(
                        on.udp_port,
                        make_query("ttlhost.foo.com", Type.A,
                                   qid=10).encode()))
                assert r.answers[0].ttl == 77
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_shapes_the_zone_declines_are_not_zone_served(self):
        """Negative SRV shapes, missing names, and non-precompiled
        qtypes go through Python; the zone table must not touch them."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                probes = (
                    # SRV with the WRONG srvce/proto: NXDOMAIN (engine)
                    make_query("_wrong._tcp.svc.foo.com", Type.SRV,
                               qid=21),
                    # SRV on a non-service name we own: NODATA + SOA
                    make_query("_pg._tcp.web.foo.com", Type.SRV, qid=22),
                    make_query("absent.foo.com", Type.A, qid=23),
                    make_query("web.foo.com", Type.AAAA, qid=24),
                )
                for q in probes:
                    before = zone_stats(server)["zone_hits"]
                    resp = Message.decode(
                        await udp_ask_raw(server.udp_port, q.encode()))
                    assert zone_stats(server)["zone_hits"] == before, \
                        q.questions[0]
                    assert resp.id == q.id
            finally:
                await server.stop()

        asyncio.run(run())

    def test_srv_zone_served_content_equals_generic(self):
        """The registered SRV qname is precompiled — answers (per member
        per port, service TTL) and A additionals (member TTL) equal the
        generic path's in content; EDNS queries get the OPT appended as
        the last additional."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                def shape(r):
                    srv = sorted((a.name, a.ttl, a.priority, a.weight,
                                  a.port, a.target) for a in r.answers)
                    add = sorted((a.name, a.ttl, a.address)
                                 for a in r.additionals
                                 if hasattr(a, "address"))
                    return r.rcode, srv, add

                for qid, kw in ((31, {"edns_payload": None}),
                                (32, {"edns_payload": 1400})):
                    q = make_query("_pg._tcp.svc.foo.com", Type.SRV,
                                   qid=qid, **kw)
                    before = zone_stats(on)["zone_hits"]
                    got = Message.decode(
                        await udp_ask_raw(on.udp_port, q.encode()))
                    want = Message.decode(
                        await udp_ask_raw(off.udp_port, q.encode()))
                    assert zone_stats(on)["zone_hits"] == before + 1, kw
                    assert shape(got) == shape(want), kw
                    assert len(got.answers) == 2
                    assert {a.target for a in got.answers} == \
                        {"lb0.svc.foo.com", "lb1.svc.foo.com"}
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_srv_member_mutation_repoints_through_alien_table(self):
        """SRV entries are tagged with the service NODE name (not their
        qname): a member mutation's parent tag must drop and re-push
        them through the C side's alien-table scan."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=41).encode()))
                assert len(r.answers) == 2
                store.put_json("/com/foo/svc/lb9",
                               {"type": "load_balancer",
                                "load_balancer": {"address": "10.0.1.9",
                                                  "ports": [100, 200]}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("_pg._tcp.svc.foo.com", Type.SRV,
                               qid=42).encode()))
                assert zone_stats(server)["zone_hits"] == before + 1
                # 2 original members (1 port each) + new member x2 ports
                assert len(r.answers) == 4
                ports = {a.port for a in r.answers
                         if a.target == "lb9.svc.foo.com"}
                assert ports == {100, 200}
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_a_rotation_zone_served(self):
        """Service plain-A answers come precompiled: full member set per
        answer (content equal to the generic path's), served natively,
        rotating so every member leads over repeated queries."""
        async def run():
            _, cache_on = fixture_store()
            _, cache_off = fixture_store()
            on = await start_server(cache_on)
            off = await start_server(cache_off, zone_precompile=False)
            try:
                def addrsets(r):
                    return sorted((a.address, a.ttl) for a in r.answers)

                want = Message.decode(await udp_ask_raw(
                    off.udp_port,
                    make_query("svc.foo.com", Type.A, qid=90).encode()))
                leads = set()
                for i in range(6):
                    before = zone_stats(on)["zone_hits"]
                    got = Message.decode(await udp_ask_raw(
                        on.udp_port,
                        make_query("svc.foo.com", Type.A,
                                   qid=91 + i).encode()))
                    assert zone_stats(on)["zone_hits"] == before + 1
                    assert got.rcode == Rcode.NOERROR
                    assert addrsets(got) == addrsets(want)
                    leads.add(got.answers[0].address)
                # both members lead at least once (cyclic rotation)
                assert leads == {"10.0.1.1", "10.0.1.2"}
            finally:
                await on.stop()
                await off.stop()

        asyncio.run(run())

    def test_service_member_mutation_repoints_rotation(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=95).encode()))
                assert {a.address for a in r.answers} == \
                    {"10.0.1.1", "10.0.1.2"}
                store.put_json("/com/foo/svc/lb2",
                               {"type": "load_balancer",
                                "load_balancer": {"address": "10.0.1.3"}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=96).encode()))
                assert {a.address for a in r.answers} == \
                    {"10.0.1.1", "10.0.1.2", "10.0.1.3"}
                assert zone_stats(server)["zone_hits"] == before + 1
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_min_ttl_matches_generic(self):
        """min(service-ttl, member-ttl) parity (lib/server.js:403-414)
        must be baked into the precompiled bodies."""
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            store.put_json("/com/foo/tsvc", {
                "type": "service", "ttl": 100,
                "service": {"srvce": "_x", "proto": "_tcp", "port": 1}})
            store.put_json("/com/foo/tsvc/m0",
                           {"type": "load_balancer", "ttl": 40,
                            "load_balancer": {"address": "10.3.0.1"}})
            store.put_json("/com/foo/tsvc/m1",
                           {"type": "load_balancer",
                            "load_balancer": {"address": "10.3.0.2"}})
            store.start_session()
            server = await start_server(cache)
            try:
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("tsvc.foo.com", Type.A, qid=97).encode()))
                assert zone_stats(server)["zone_hits"] == before + 1
                ttls = {a.address: a.ttl for a in r.answers}
                assert ttls == {"10.3.0.1": 40, "10.3.0.2": 100}
            finally:
                await server.stop()

        asyncio.run(run())

    def test_service_with_invalid_member_declines_to_python(self):
        """A structurally invalid member makes the generic path SERVFAIL
        mid-set; the zone must decline rather than answer differently."""
        async def run():
            store, cache = fixture_store()
            store.put_json("/com/foo/svc/bad",
                           {"type": "load_balancer",
                            "load_balancer": "not-a-dict"})
            server = await start_server(cache)
            try:
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("svc.foo.com", Type.A, qid=98).encode()))
                assert zone_stats(server)["zone_hits"] == before
                assert r.rcode == Rcode.SERVFAIL
            finally:
                await server.stop()

        asyncio.run(run())

    def test_doubled_suffix_policy_not_pushed(self):
        """Names the resolver REFUSES by suffix policy (doubled
        dnsDomain) must never be precompiled even if a store node
        exists at that domain."""
        async def run():
            store = FakeStore()
            cache = MirrorCache(store, DOMAIN)
            # a real znode whose domain is foo.com.foo.com
            store.put_json("/com/foo/com/foo",
                           {"type": "host",
                            "host": {"address": "10.1.2.3"}})
            store.start_session()
            server = await start_server(cache)
            try:
                q = make_query("foo.com.foo.com", Type.A, qid=31)
                resp = Message.decode(
                    await udp_ask_raw(server.udp_port, q.encode()))
                assert resp.rcode == Rcode.REFUSED
                assert zone_stats(server)["zone_hits"] == 0
            finally:
                await server.stop()

        asyncio.run(run())


class TestZoneCoherence:
    def test_mutation_repoints_zone_answer(self):
        """A store mutation must re-point the precompiled answer (drop
        via tag invalidation + fresh push from the same event) — and the
        NEW answer is still zone-served, not a Python fallback."""
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=41).encode()))
                assert r.answers[0].address == "192.168.0.1"

                store.put_json("/com/foo/web",
                               {"type": "host",
                                "host": {"address": "192.168.0.99"}})
                await asyncio.sleep(0)   # watch delivery (sync store)

                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=42).encode()))
                assert r.answers[0].address == "192.168.0.99"
                assert zone_stats(server)["zone_hits"] == before + 1

                # the reverse tree re-pointed too: old PTR gone, new live
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("99.0.168.192.in-addr.arpa", Type.PTR,
                               qid=43).encode()))
                assert r.rcode == Rcode.NOERROR
                assert r.answers[0].target == "web.foo.com"
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("1.0.168.192.in-addr.arpa", Type.PTR,
                               qid=44).encode()))
                assert r.rcode == Rcode.REFUSED
            finally:
                await server.stop()

        asyncio.run(run())

    def test_deleted_node_falls_back_to_python_refused(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                store.delete("/com/foo/web")
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=51).encode()))
                assert r.rcode == Rcode.REFUSED
                assert zone_stats(server)["zone_hits"] == before
            finally:
                await server.stop()

        asyncio.run(run())

    def test_type_change_host_to_service_drops_zone_entry(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                store.put_json("/com/foo/web", {
                    "type": "service",
                    "service": {"srvce": "_x", "proto": "_tcp",
                                "port": 1}})
                await asyncio.sleep(0)
                before = zone_stats(server)["zone_hits"]
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=61).encode()))
                # service with no children: NODATA-ish per engine policy;
                # what matters here is the zone did NOT serve stale host
                assert zone_stats(server)["zone_hits"] == before
                assert not r.answers or \
                    r.answers[0].address != "192.168.0.1"
            finally:
                await server.stop()

        asyncio.run(run())

    def test_zone_precompile_off_serves_nothing_from_zone(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache, zone_precompile=False)
            try:
                r = Message.decode(await udp_ask_raw(
                    server.udp_port,
                    make_query("web.foo.com", Type.A, qid=71).encode()))
                assert r.answers[0].address == "192.168.0.1"
                assert zone_stats(server)["zone_hits"] == 0
                assert zone_stats(server)["zone_entries"] == 0
            finally:
                await server.stop()

        asyncio.run(run())

    def test_zone_serves_fold_into_metrics(self):
        """Zone serves surface in the Prometheus scrape: the per-qtype
        request counter advances and binder_zone_serves counts them."""
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                for i in range(3):
                    await udp_ask_raw(
                        server.udp_port,
                        make_query("web.foo.com", Type.A,
                                   qid=80 + i).encode())
                text = server.collector.expose()
                assert 'binder_zone_serves_total 3' in text.replace(
                    "binder_zone_serves 3", "binder_zone_serves_total 3")
            finally:
                await server.stop()

        asyncio.run(run())
