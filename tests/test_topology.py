"""End-to-end zone topology test: binder-topology (setup.sh analog) drives
instance_adjust + mbalancer + real binder processes."""
import json
import os
import socket
import subprocess
import time

import pytest

sys_path_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOPOLOGY = os.path.join(sys_path_root, "bin", "binder-topology")
ADJUST = os.path.join(sys_path_root, "native", "build", "instance_adjust")
BALANCER = os.path.join(sys_path_root, "native", "build", "mbalancer")

from binder_tpu.dns import Message, Rcode, Type, make_query

pytestmark = pytest.mark.skipif(
    not (os.path.exists(ADJUST) and os.path.exists(BALANCER)),
    reason="native binaries not built (make -C native)")


def udp_ask(port, name, qtype, qid=1, timeout=5.0):
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.settimeout(timeout)
    s.sendto(make_query(name, qtype, qid=qid).encode(), ("127.0.0.1", port))
    try:
        return Message.decode(s.recv(4096))
    finally:
        s.close()


@pytest.fixture()
def zone(tmp_path):
    config = tmp_path / "config.json"
    fixture = tmp_path / "fixture.json"
    fixture.write_text(json.dumps({
        "/com/foo/web": {"type": "host", "host": {"address": "10.7.7.7"}},
    }))
    config.write_text(json.dumps({
        "dnsDomain": "foo.com", "datacenterName": "dc0",
        "host": "127.0.0.1",
        "store": {"backend": "fake", "fixture": str(fixture)},
    }))
    rundir = str(tmp_path / "run")
    yield rundir, str(config)
    subprocess.run([TOPOLOGY, "stop", "-D", rundir], timeout=60,
                   capture_output=True)


def start(rundir, config, n, baseport):
    proc = subprocess.run(
        [TOPOLOGY, "start", "-n", str(n), "-c", config, "-D", rundir,
         "-p", "0", "-B", str(baseport), "--bind", "127.0.0.1"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr + proc.stdout
    return int(open(os.path.join(rundir, "balancer.port")).read())


class TestZoneTopology:
    def test_full_zone_up_scale_down(self, zone):
        rundir, config = zone
        port = start(rundir, config, 2, 25301)
        time.sleep(0.8)  # balancer scan connects backends

        r = udp_ask(port, "web.foo.com", Type.A)
        assert r.rcode == Rcode.NOERROR
        assert r.answers[0].address == "10.7.7.7"

        # metric-ports file published (port+1000 convention)
        ports = open(os.path.join(rundir, "metric_ports")).read().split()
        assert ports == ["26301", "26302"]

        # status shows both instances + balancer online
        out = subprocess.run([TOPOLOGY, "status", "-D", rundir],
                             capture_output=True, text=True,
                             timeout=30).stdout
        assert out.count("online") == 3

        # scale down to 1: reconciler removes the surplus instance
        start(rundir, config, 1, 25301)
        time.sleep(1.2)  # balancer notices the socket left
        r = udp_ask(port, "web.foo.com", Type.A, qid=2)
        assert r.rcode == Rcode.NOERROR

        state = os.path.join(rundir, "state")
        assert not os.path.exists(
            os.path.join(state, "binder-25302.props"))
