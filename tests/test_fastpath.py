"""Tests for the native fast-path answer cache (native/fastio/fastpath.c).

Two layers:
- C-unit: drive ``fastpath_new/put/drain/stats`` directly over a real UDP
  socket pair, asserting on key gating, id/case patching, rotation,
  generation invalidation, and expiry;
- integration: a full BinderServer with ``query_log=False`` (the gate
  condition), asserting that repeat queries are served natively with
  byte-correct answers, that store mutations invalidate, and that
  natively counted queries fold into the Prometheus scrape.
"""
import asyncio
import socket
import time

import pytest

from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

fastio = pytest.importorskip(
    "binder_tpu._binderfastio",
    reason="fastio extension not built (make -C native)")
if not hasattr(fastio, "fastpath_new"):
    pytest.skip("fastio extension predates the fast path; rebuild",
                allow_module_level=True)

LAT_BUCKETS = (0.001, 0.01, 0.1, 1.0)
SIZE_BUCKETS = (64.0, 512.0, 4096.0)

QNAME = b"\x03web\x05bench\x03com\x00"  # web.bench.com


def make_cache(size=100, expiry_ms=60000):
    return fastio.fastpath_new(size, expiry_ms, LAT_BUCKETS, SIZE_BUCKETS)


def udp_pair():
    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.setblocking(False)
    cli = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    cli.bind(("127.0.0.1", 0))
    cli.settimeout(2)
    return srv, cli, srv.getsockname()[1]


def ckey(qname=QNAME, rd=0, edns=0, payload=512, qtype=1, qclass=1):
    return (bytes([(1 if rd else 0) | (2 if edns else 0)])
            + payload.to_bytes(2, "big") + qtype.to_bytes(2, "big")
            + qclass.to_bytes(2, "big") + qname.lower())


def response_wire(qname=QNAME, tag=b"TAG0"):
    """Header + question + opaque trailing bytes standing in for answers."""
    return (bytes.fromhex("000084000001000100000000") + qname.lower()
            + b"\x00\x01\x00\x01" + tag)


def query_pkt(qid=0x1111, qname=QNAME, rd=0, qtype=1, opcode=0, qd=1,
              tail=b""):
    flags = (opcode << 11) | (0x0100 if rd else 0)
    return (qid.to_bytes(2, "big") + flags.to_bytes(2, "big")
            + qd.to_bytes(2, "big") + b"\x00\x00\x00\x00"
            + len(tail and b"x").to_bytes(2, "big")  # arcount 1 iff tail
            + qname + qtype.to_bytes(2, "big") + b"\x00\x01" + tail)


def edns_tail(payload=1232, options=b""):
    return (b"\x00" + (41).to_bytes(2, "big") + payload.to_bytes(2, "big")
            + b"\x00\x00\x00\x00" + len(options).to_bytes(2, "big")
            + options)


class TestFastpathUnit:
    def drain(self, cache, srv, gen=1):
        return fastio.fastpath_drain(cache, srv.fileno(), gen)

    def test_miss_surfaces_packet(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        pkt = query_pkt()
        cli.sendto(pkt, ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert served == 0
        assert len(misses) == 1
        data, addr = misses[0]
        assert data == pkt
        assert addr[0] == "127.0.0.1"

    def test_hit_patches_id_and_case(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        assert fastio.fastpath_put(cache, ckey(), 1, 1,
                                   [response_wire(tag=b"ANSW")])
        mixed = b"\x03WeB\x05BeNCH\x03CoM\x00"
        cli.sendto(query_pkt(qid=0xBEEF, qname=mixed), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (0, 1)
        data, _ = cli.recvfrom(4096)
        assert data[:2] == b"\xbe\xef"
        assert mixed in data          # 0x20 case echo
        assert data.endswith(b"ANSW")

    def test_rd_and_edns_key_separation(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(rd=0), 1, 1,
                            [response_wire(tag=b"NORD")])
        # same name with RD set → different key → miss
        cli.sendto(query_pkt(rd=1), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (1, 0)
        # EDNS variant needs its own entry keyed by payload ceiling
        fastio.fastpath_put(cache, ckey(edns=1, payload=1232), 1, 1,
                            [response_wire(tag=b"EDNS")])
        cli.sendto(query_pkt(tail=edns_tail(1232)), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (0, 1)
        data, _ = cli.recvfrom(4096)
        assert data.endswith(b"EDNS")
        # EDNS option bytes (cookies) must NOT mint new keys
        cli.sendto(query_pkt(tail=edns_tail(1232, options=b"\x00\x0a\x00"
                                            b"\x02ab")),
                   ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (0, 1)
        cli.recvfrom(4096)

    def test_payload_ceiling_below_512_is_classic(self):
        # wire.py max_udp_payload: EDNS sizes under 512 behave as 512
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(edns=1, payload=512), 1, 1,
                            [response_wire(tag=b"X512")])
        cli.sendto(query_pkt(tail=edns_tail(100)), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (0, 1)

    def test_generation_invalidates(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(), 1, 7, [response_wire()])
        cli.sendto(query_pkt(), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv, gen=8)
        assert (len(misses), served) == (1, 0)
        # entry was dropped, not just skipped
        assert fastio.fastpath_stats(cache)["entries"] == 0

    def test_expiry(self):
        srv, cli, port = udp_pair()
        cache = make_cache(expiry_ms=1)
        fastio.fastpath_put(cache, ckey(), 1, 1, [response_wire()])
        time.sleep(0.02)
        cli.sendto(query_pkt(), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (1, 0)

    def test_rotation_cycles_variants(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(), 1, 1,
                            [response_wire(tag=b"VAR0"),
                             response_wire(tag=b"VAR1"),
                             response_wire(tag=b"VAR2")])
        seen = []
        for i in range(6):
            cli.sendto(query_pkt(qid=0x2000 + i), ("127.0.0.1", port))
            misses, served = self.drain(cache, srv)
            assert served == 1
            data, _ = cli.recvfrom(4096)
            seen.append(data[-4:])
        assert seen == [b"VAR0", b"VAR1", b"VAR2"] * 2

    def test_ineligible_shapes_fall_through(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(), 1, 1, [response_wire()])
        bad = [
            query_pkt(opcode=1),                      # not QUERY
            query_pkt(qd=2),                          # multi-question
            query_pkt(qname=b"\xc0\x0c"),             # compressed qname
            query_pkt(qname=b"\x04w.b!\x03com\x00"),  # charset
            query_pkt() + b"junk",                    # trailing bytes
            b"\x12\x34\x00",                          # truncated header
        ]
        for pkt in bad:
            cli.sendto(pkt, ("127.0.0.1", port))
            misses, served = self.drain(cache, srv)
            assert served == 0, pkt
            assert len(misses) == 1

    def test_put_rejects_oversize_and_replaces(self):
        cache = make_cache()
        assert not fastio.fastpath_put(cache, ckey(), 1, 1,
                                       [b"\x00" * 5000])
        assert fastio.fastpath_put(cache, ckey(), 1, 1,
                                   [response_wire(tag=b"OLD0")])
        assert fastio.fastpath_put(cache, ckey(), 1, 1,
                                   [response_wire(tag=b"NEW0")])
        assert fastio.fastpath_stats(cache)["entries"] == 1

    def test_put_with_remaining_ttl_overrides_cache_expiry(self):
        srv, cli, port = udp_pair()
        cache = make_cache(expiry_ms=60000)
        # an entry completed late in its Python-cache life carries only
        # its remaining lifetime — not a fresh full window
        fastio.fastpath_put(cache, ckey(), 1, 1, [response_wire()], 1)
        time.sleep(0.02)
        cli.sendto(query_pkt(), ("127.0.0.1", port))
        misses, served = self.drain(cache, srv)
        assert (len(misses), served) == (1, 0)

    def test_qtype_stats_overflow_uses_catchall(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        # 20 distinct qtypes: the first 15 get their own stats slot, the
        # rest must fold into the 0xFFFF catch-all, never a real qtype
        for qt in range(1, 21):
            fastio.fastpath_put(cache, ckey(qtype=qt), qt, 1,
                                [response_wire()])
        for i, qt in enumerate(range(1, 21)):
            cli.sendto(query_pkt(qid=0x3000 + i, qtype=qt),
                       ("127.0.0.1", port))
            misses, served = self.drain(cache, srv)
            assert served == 1, qt
            cli.recvfrom(4096)
        per = fastio.fastpath_stats(cache)["per_qtype"]
        assert all(per[qt]["count"] == 1 for qt in range(1, 16))
        assert per[0xFFFF]["count"] == 5
        assert not any(qt in per for qt in range(16, 21))

    def test_stats_shape(self):
        srv, cli, port = udp_pair()
        cache = make_cache()
        fastio.fastpath_put(cache, ckey(), 1, 1, [response_wire()])
        cli.sendto(query_pkt(), ("127.0.0.1", port))
        self.drain(cache, srv)
        cli.recvfrom(4096)
        s = fastio.fastpath_stats(cache)
        assert s["hits"] == 1 and s["lookups"] == 1
        q = s["per_qtype"][1]
        assert q["count"] == 1
        assert len(q["lat_cells"]) == len(LAT_BUCKETS) + 1
        assert len(q["size_cells"]) == len(SIZE_BUCKETS) + 1
        assert sum(q["lat_cells"]) == 1 and sum(q["size_cells"]) == 1
        assert q["size_sum"] == len(response_wire())


DOMAIN = "foo.com"


def fixture_store():
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432},
    })
    for i in range(4):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    return store, cache


async def start_server(cache, **kw):
    kw.setdefault("query_log", False)
    # this module tests the answer-cache fill/hit flow, which the zone
    # table would short-circuit (a precompiled host answer means the
    # first query never surfaces to Python); tests/test_zone.py covers
    # the zone path itself
    kw.setdefault("zone_precompile", False)
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="coal", host="127.0.0.1", port=0,
                          collector=MetricsCollector(), **kw)
    await server.start()
    return server


from tests.test_zone import udp_ask_raw  # shared raw-ask helper


async def udp_ask(port, name, qtype, qid=4242):
    data = await udp_ask_raw(
        port, make_query(name, qtype, qid=qid).encode())
    return Message.decode(data)


def fp_hits(server):
    return fastio.fastpath_stats(server._fastpath)["hits"]


class TestFastpathIntegration:
    def test_second_query_served_natively(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                first = await udp_ask(server.udp_port, "web.foo.com",
                                      Type.A)
                assert fp_hits(server) == 0     # miss populated the cache
                # promote-on-first-hit (r5): the first repeat serves from
                # the Python answer cache AND promotes; the next repeat
                # is native
                await udp_ask(server.udp_port, "web.foo.com", Type.A,
                              qid=776)
                assert fp_hits(server) == 0
                second = await udp_ask(server.udp_port, "web.foo.com",
                                       Type.A, qid=777)
                assert fp_hits(server) == 1
                assert second.id == 777
                assert second.rcode == Rcode.NOERROR
                assert [a.address for a in second.answers] == \
                    [a.address for a in first.answers]
                assert second.answers[0].address == "192.168.0.1"
            finally:
                await server.stop()
        asyncio.run(run())

    def test_rotation_after_variant_collection(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                # rotatable entry completes after variants_cap resolves
                cap = server.answer_cache.variants_cap
                for i in range(cap):
                    await udp_ask(server.udp_port, "svc.foo.com", Type.A,
                                  qid=i + 1)
                assert fp_hits(server) == 0
                # first hit promotes (r5 promote-on-first-hit)
                await udp_ask(server.udp_port, "svc.foo.com", Type.A,
                              qid=99)
                orderings = []
                for i in range(cap):
                    m = await udp_ask(server.udp_port, "svc.foo.com",
                                      Type.A, qid=100 + i)
                    assert len(m.answers) == 4
                    orderings.append(tuple(a.address for a in m.answers))
                assert fp_hits(server) == cap
                # round-robin rotation: the full variant cycle presents
                # different orderings (8 independent shuffles of 4 lbs are
                # all identical with p = (1/24)^7 — not flake territory)
                assert len(set(orderings)) > 1
            finally:
                await server.stop()
        asyncio.run(run())

    def test_store_mutation_invalidates(self):
        async def run():
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                await udp_ask(server.udp_port, "web.foo.com", Type.A)
                await udp_ask(server.udp_port, "web.foo.com", Type.A)
                await udp_ask(server.udp_port, "web.foo.com", Type.A)
                assert fp_hits(server) == 1
                store.put_json(
                    "/com/foo/web",
                    {"type": "host", "host": {"address": "10.9.9.9"}})
                await asyncio.sleep(0.05)   # watch delivery
                m = await udp_ask(server.udp_port, "web.foo.com", Type.A)
                assert m.answers[0].address == "10.9.9.9"
            finally:
                await server.stop()
        asyncio.run(run())

    def test_query_log_gates_fast_path(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache, query_log=True)
            try:
                for i in range(3):
                    await udp_ask(server.udp_port, "web.foo.com", Type.A,
                                  qid=i + 1)
                assert fp_hits(server) == 0
            finally:
                await server.stop()
        asyncio.run(run())

    def test_native_counts_fold_into_scrape(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                for i in range(5):
                    await udp_ask(server.udp_port, "web.foo.com", Type.A,
                                  qid=i + 1)
                # r5 promote-on-first-hit: resolve, Python hit (promotes),
                # then 3 native hits
                assert fp_hits(server) == 3
                text = server.collector.expose()
                assert ('binder_requests_completed{type="A"} 5' in text)
                assert ('binder_request_latency_seconds_count{type="A"} 5'
                        in text)
                assert ('binder_response_size_bytes_count{type="A"} 5'
                        in text)
                assert 'binder_answer_cache_hits 4' in text
                # folding is delta-based: a second scrape must not
                # double-count
                text = server.collector.expose()
                assert ('binder_requests_completed{type="A"} 5' in text)
            finally:
                await server.stop()
        asyncio.run(run())

    def test_mixed_case_query_case_echo(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                lower = b"\x03web\x03foo\x03com\x00"
                prime = (b"\x00\x01\x00\x00\x00\x01\x00\x00\x00\x00"
                         b"\x00\x00" + lower + b"\x00\x01\x00\x01")
                await udp_ask_raw(server.udp_port, prime)
                # second ask promotes (r5 promote-on-first-hit)
                await udp_ask_raw(server.udp_port, b"\x00\x02" + prime[2:])
                mixed = b"\x03wEb\x03FoO\x03cOm\x00"
                pkt = (b"\x77\x77\x00\x00\x00\x01\x00\x00\x00\x00\x00\x00"
                       + mixed + b"\x00\x01\x00\x01")
                data = await udp_ask_raw(server.udp_port, pkt)
                assert fp_hits(server) == 1
                assert mixed in data
                m = Message.decode(data)
                assert m.answers[0].address == "192.168.0.1"
            finally:
                await server.stop()
        asyncio.run(run())

    def test_read_your_writes_under_churn(self):
        """Mutate-then-query loop through the full UDP stack with the
        fast path active: the fake store applies mutations to the
        mirror synchronously, so every query after a mutation MUST see
        the new value — any stale answer means a cache (Python or C)
        survived a generation bump."""
        async def run():
            import random
            rng = random.Random(1234)
            store, cache = fixture_store()
            server = await start_server(cache)
            try:
                addr = None
                for i in range(60):
                    addr = f"10.7.{rng.randrange(256)}.{rng.randrange(1, 255)}"
                    store.put_json(
                        "/com/foo/web",
                        {"type": "host", "host": {"address": addr}})
                    # a few queries per mutation: the first re-resolves,
                    # the rest exercise both cache layers
                    for j in range(3):
                        m = await udp_ask(server.udp_port, "web.foo.com",
                                          Type.A, qid=(i * 4 + j) % 65536)
                        assert m.answers[0].address == addr, \
                            (i, j, m.answers[0].address, addr)
                assert fp_hits(server) > 0   # the C path did serve
            finally:
                await server.stop()
        asyncio.run(run())

    def test_refused_responses_cached_and_served(self):
        async def run():
            _, cache = fixture_store()
            server = await start_server(cache)
            try:
                for i in range(3):
                    m = await udp_ask(server.udp_port, "nope.foo.com",
                                      Type.A, qid=i + 1)
                    assert m.rcode == Rcode.REFUSED
                # r5 promote-on-first-hit: third repeat is the native one
                assert fp_hits(server) == 1
            finally:
                await server.stop()
        asyncio.run(run())
