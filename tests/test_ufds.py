"""UFDS/LDAP resolver discovery: BER codec, LDAP client ↔ in-process
server, and the full recursion bootstrap through the ZK mirror.

The reference's UFDS integration (lib/recursion.js:129-148,202-249) has
zero automated tests (SURVEY §4); this suite covers the re-derived
protocol path end to end, including CA-verified ldaps (the reference's
ldapjs setup trusts any certificate, lib/recursion.js:129-148).
"""
import asyncio
import datetime
import ipaddress
import ssl

import pytest

from binder_tpu.recursion import ber
from binder_tpu.recursion.ldap_server import LdapTestServer
from binder_tpu.recursion.recursion import Recursion
from binder_tpu.recursion.ufds import (
    LdapClient,
    LdapError,
    UfdsResolverSource,
    encode_filter,
    eval_filter,
    parse_filter,
    parse_ldap_url,
)
from binder_tpu.store import FakeStore, MirrorCache

# -- in-test PKI for the CA-verification knob -------------------------------


def _make_key_and_cert(cn, *, issuer=None, issuer_key=None, ca=False,
                       san_dns=None):
    """Self-signed CA (issuer=None) or a leaf signed by one."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (x509.CertificateBuilder()
         .subject_name(subject)
         .issuer_name(issuer.subject if issuer is not None else subject)
         .public_key(key.public_key())
         .serial_number(x509.random_serial_number())
         .not_valid_before(now - datetime.timedelta(days=1))
         .not_valid_after(now + datetime.timedelta(days=30))
         .add_extension(x509.BasicConstraints(ca=ca, path_length=None),
                        critical=True))
    if san_dns:
        b = b.add_extension(x509.SubjectAlternativeName(
            [x509.DNSName(san_dns),
             x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
            critical=False)
    cert = b.sign(issuer_key if issuer_key is not None else key,
                  hashes.SHA256())
    return key, cert


class _Pki:
    pass


@pytest.fixture(scope="module")
def tls_pki(tmp_path_factory):
    """CA + server cert for ufds.foo.com/127.0.0.1, plus an unrelated
    'rogue' CA for the negative test."""
    serialization = pytest.importorskip(
        "cryptography.hazmat.primitives.serialization",
        reason="in-test PKI needs the cryptography package")

    d = tmp_path_factory.mktemp("ufds-pki")

    def pem(path, obj, private=False):
        if private:
            data = obj.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption())
        else:
            data = obj.public_bytes(serialization.Encoding.PEM)
        path.write_bytes(data)
        return str(path)

    ca_key, ca_cert = _make_key_and_cert("binder-test-ca", ca=True)
    srv_key, srv_cert = _make_key_and_cert(
        "ufds.foo.com", issuer=ca_cert, issuer_key=ca_key,
        san_dns="ufds.foo.com")
    _, rogue_ca_cert = _make_key_and_cert("rogue-ca", ca=True)

    pki = _Pki()
    pki.ca_pem = pem(d / "ca.pem", ca_cert)
    pki.rogue_ca_pem = pem(d / "rogue_ca.pem", rogue_ca_cert)
    cert_pem = pem(d / "server.pem", srv_cert)
    key_pem = pem(d / "server.key", srv_key, private=True)
    pki.server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    pki.server_ctx.load_cert_chain(cert_pem, key_pem)
    return pki


RESOLVER_ENTRIES = {
    "uuid=r1, datacenter=east-1, region=home, o=smartdc": {
        "objectclass": ["resolver"],
        "datacenter": ["east-1"], "ip": ["10.99.99.38"],
    },
    "uuid=r2, datacenter=east-1, region=home, o=smartdc": {
        "objectclass": ["resolver"],
        "datacenter": ["east-1"], "ip": ["10.99.99.39"],
    },
    "uuid=r3, datacenter=west-1, region=home, o=smartdc": {
        "objectclass": ["resolver"],
        "datacenter": ["west-1"], "ip": ["10.77.77.10"],
    },
    "uuid=x1, datacenter=east-1, region=home, o=smartdc": {
        "objectclass": ["vm"], "ip": ["10.99.99.99"],
    },
    "uuid=r9, datacenter=far-1, region=other, o=smartdc": {
        "objectclass": ["resolver"],
        "datacenter": ["far-1"], "ip": ["10.1.1.1"],
    },
}


class TestBer:
    def test_int_roundtrip(self):
        for v in (0, 1, 127, 128, 255, 256, 65535, -1, -128, 2**31 - 1):
            tag, content, off = ber.decode_tlv(ber.encode_int(v))
            assert tag == ber.INTEGER
            assert ber.decode_int(content) == v
            assert off == len(ber.encode_int(v))

    def test_long_form_length(self):
        payload = b"x" * 300
        enc = ber.encode_str(payload)
        tag, content, _ = ber.decode_tlv(enc)
        assert content == payload

    def test_frame_length_incremental(self):
        msg = ber.encode_seq([ber.encode_int(7), ber.encode_str("y" * 200)])
        for cut in range(len(msg)):
            assert ber.frame_length(msg[:cut]) == 0
        assert ber.frame_length(msg) == len(msg)
        assert ber.frame_length(msg + b"extra") == len(msg)

    def test_truncated_tlv_raises(self):
        with pytest.raises(ber.BerError):
            ber.decode_tlv(b"\x04\x05ab")


class TestFilters:
    def test_parse_shapes(self):
        assert parse_filter("(objectclass=resolver)") == \
            ("eq", "objectclass", "resolver")
        assert parse_filter("objectclass=resolver") == \
            ("eq", "objectclass", "resolver")
        assert parse_filter("(cn=*)") == ("present", "cn")
        node = parse_filter("(&(a=1)(|(b=2)(!(c=3))))")
        assert node[0] == "and" and node[1][1][0] == "or"

    def test_parse_errors(self):
        for bad in ("(a=b", "(&(a=b)", "(a)", "(a=b*c)", "(a=b))"):
            with pytest.raises(LdapError):
                parse_filter(bad)

    def test_eval(self):
        attrs = {"objectclass": ["resolver"], "ip": ["10.0.0.1"]}
        assert eval_filter(parse_filter("(objectclass=Resolver)"), attrs)
        assert eval_filter(parse_filter("(ip=*)"), attrs)
        assert not eval_filter(parse_filter("(ip=10.0.0.2)"), attrs)
        assert eval_filter(
            parse_filter("(&(objectclass=resolver)(!(ip=9.9.9.9)))"), attrs)

    def test_encode_decodes_on_server(self):
        # exercised in the client/server tests below; here just check the
        # encoder emits the right context tags
        assert encode_filter(("present", "cn"))[0] == 0x87
        assert encode_filter(("eq", "a", "b"))[0] == 0xA3
        assert encode_filter(("and", []))[0] == 0xA0

    def test_url_parse(self):
        assert parse_ldap_url("ldaps://ufds.foo.com") == \
            ("ldaps", "ufds.foo.com", None)
        assert parse_ldap_url("ldap://10.0.0.5:1389") == \
            ("ldap", "10.0.0.5", 1389)
        assert parse_ldap_url("ldaps://[fd00::5]:636") == \
            ("ldaps", "fd00::5", 636)
        assert parse_ldap_url("ldap://[fd00::5]") == \
            ("ldap", "fd00::5", None)
        with pytest.raises(LdapError):
            parse_ldap_url("ldap://[fd00::5")
        with pytest.raises(LdapError):
            parse_ldap_url("ldap://host:notaport")


class TestLdapClientServer:
    def run(self, coro):
        return asyncio.run(coro)

    def test_bind_and_search(self):
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES) as srv:
                c = LdapClient("127.0.0.1", srv.port)
                await c.connect()
                await c.bind("cn=root", "secret")
                entries = await c.search(
                    "region=home, o=smartdc", "(objectclass=resolver)",
                    attributes=("datacenter", "ip"))
                await c.close()
                return entries

        entries = self.run(go())
        assert len(entries) == 3
        by_ip = {a["ip"][0]: a["datacenter"][0] for _, a in entries}
        assert by_ip == {"10.99.99.38": "east-1", "10.99.99.39": "east-1",
                         "10.77.77.10": "west-1"}

    def test_bad_credentials(self):
        async def go():
            async with LdapTestServer() as srv:
                c = LdapClient("127.0.0.1", srv.port)
                await c.connect()
                with pytest.raises(LdapError) as ei:
                    await c.bind("cn=root", "wrong")
                await c.close()
                return ei.value

        assert self.run(go()).result_code == 49

    def test_search_requires_bind(self):
        async def go():
            async with LdapTestServer() as srv:
                c = LdapClient("127.0.0.1", srv.port)
                await c.connect()
                with pytest.raises(LdapError):
                    await c.search("o=smartdc", "(objectclass=*)")
                await c.close()

        self.run(go())

    def test_presence_and_scope(self):
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES) as srv:
                c = LdapClient("127.0.0.1", srv.port)
                await c.connect()
                await c.bind("cn=root", "secret")
                all_sub = await c.search("o=smartdc", "(objectclass=*)")
                base_only = await c.search(
                    "uuid=r1, datacenter=east-1, region=home, o=smartdc",
                    "(objectclass=*)", scope=0)
                other_region = await c.search(
                    "region=other, o=smartdc", "(objectclass=resolver)")
                await c.close()
                return all_sub, base_only, other_region

        all_sub, base_only, other_region = self.run(go())
        assert len(all_sub) == 5
        assert len(base_only) == 1 and base_only[0][0].startswith("uuid=r1")
        assert len(other_region) == 1
        assert other_region[0][1]["datacenter"] == ["far-1"]


def ufds_zk_fixture(addr):
    """ZK mirror with a ufds 'service' node whose first child carries the
    directory address — the shape lib/recursion.js:105-127 requires."""
    store = FakeStore()
    cache = MirrorCache(store, "foo.com")
    store.put_json("/com/foo/ufds", {"type": "service",
                                     "service": {"port": 636}})
    store.put_json("/com/foo/ufds/inst0",
                   {"type": "load_balancer",
                    "load_balancer": {"address": addr}})
    store.start_session()
    return cache


class TestUfdsResolverSource:
    def test_bootstrap_via_zk_and_list(self):
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES) as srv:
                cache = ufds_zk_fixture("127.0.0.1")
                src = UfdsResolverSource({
                    "url": f"ldap://ufds.foo.com:{srv.port}",
                    "bindDN": "cn=root", "bindPassword": "secret"})
                await src.init(cache)
                res = await src.list_resolvers("home")
                await src.close()
                return res

        res = asyncio.run(go())
        assert {(r["datacenter"], r["ip"]) for r in res} == {
            ("east-1", "10.99.99.38"), ("east-1", "10.99.99.39"),
            ("west-1", "10.77.77.10")}

    def test_init_fails_until_zk_resolves(self):
        async def go():
            store = FakeStore()
            cache = MirrorCache(store, "foo.com")
            store.start_session()   # session up, but no ufds node yet
            src = UfdsResolverSource({"url": "ldap://ufds.foo.com",
                                      "bindDN": "cn=root",
                                      "bindPassword": "secret"})
            with pytest.raises(LdapError):
                await src.init(cache)

        asyncio.run(go())

    def test_reconnects_after_connection_loss(self):
        async def go():
            srv = LdapTestServer(entries=RESOLVER_ENTRIES)
            await srv.start()
            src = UfdsResolverSource({
                "url": f"ldap://127.0.0.1:{srv.port}",
                "bindDN": "cn=root", "bindPassword": "secret"})
            await src.init(ufds_zk_fixture("127.0.0.1"))
            first = await src.list_resolvers("home")
            # sever: client's next search fails, connection is dropped
            await src.client.close()
            second = await src.list_resolvers("home")   # reconnects
            binds = srv.bind_count
            await src.close()
            await srv.stop()
            return first, second, binds

        first, second, binds = asyncio.run(go())
        assert len(first) == len(second) == 3
        assert binds >= 2

    def test_verified_tls_happy_path(self, tls_pki):
        # ca knob set: chain verified against the test CA and the cert
        # identity checked against the url's DNS name, while the dial
        # target is the ZK-resolved 127.0.0.1
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES,
                                      ssl_context=tls_pki.server_ctx) as srv:
                cache = ufds_zk_fixture("127.0.0.1")
                src = UfdsResolverSource({
                    "url": f"ldaps://ufds.foo.com:{srv.port}",
                    "bindDN": "cn=root", "bindPassword": "secret",
                    "ca": tls_pki.ca_pem})
                await src.init(cache)
                res = await src.list_resolvers("home")
                await src.close()
                return res

        assert len(asyncio.run(go())) == 3

    def test_verified_tls_rejects_untrusted_ca(self, tls_pki):
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES,
                                      ssl_context=tls_pki.server_ctx) as srv:
                src = UfdsResolverSource({
                    "url": f"ldaps://ufds.foo.com:{srv.port}",
                    "bindDN": "cn=root", "bindPassword": "secret",
                    "ca": tls_pki.rogue_ca_pem})
                with pytest.raises(ssl.SSLError):
                    await src.init(ufds_zk_fixture("127.0.0.1"))

        asyncio.run(go())

    def test_verified_tls_rejects_name_mismatch(self, tls_pki):
        # tlsServerName pins the identity; a name the certificate does
        # not carry must fail even though the chain verifies
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES,
                                      ssl_context=tls_pki.server_ctx) as srv:
                src = UfdsResolverSource({
                    "url": f"ldaps://ufds.foo.com:{srv.port}",
                    "bindDN": "cn=root", "bindPassword": "secret",
                    "ca": tls_pki.ca_pem,
                    "tlsServerName": "evil.example.com"})
                with pytest.raises(ssl.SSLCertVerificationError):
                    await src.init(ufds_zk_fixture("127.0.0.1"))

        asyncio.run(go())

    def test_server_name_without_ca_is_a_config_error(self):
        # identity pinning without a trust root would silently fall back
        # to the trust-anything context — must refuse at construction
        with pytest.raises(LdapError):
            UfdsResolverSource({"url": "ldaps://ufds.foo.com",
                                "tlsServerName": "ufds.foo.com"})

    def test_bad_ca_path_is_an_immediate_config_error(self):
        with pytest.raises(LdapError):
            UfdsResolverSource({"url": "ldaps://ufds.foo.com",
                                "ca": "/nonexistent/ca.pem"})

    def test_default_tls_still_trusts_anything(self, tls_pki):
        # no ca knob: reference-compatible posture — the self-signed-ish
        # server is accepted without verification
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES,
                                      ssl_context=tls_pki.server_ctx) as srv:
                src = UfdsResolverSource({
                    "url": f"ldaps://ufds.foo.com:{srv.port}",
                    "bindDN": "cn=root", "bindPassword": "secret"})
                await src.init(ufds_zk_fixture("127.0.0.1"))
                res = await src.list_resolvers("home")
                await src.close()
                return res

        assert len(asyncio.run(go())) == 3

    def test_recursion_populates_dcs_from_ufds(self):
        async def go():
            async with LdapTestServer(entries=RESOLVER_ENTRIES) as srv:
                cache = ufds_zk_fixture("127.0.0.1")
                rec = Recursion(
                    zk_cache=cache, dns_domain="foo.com",
                    datacenter_name="east-1", region_name="home",
                    ufds={"url": f"ldap://ufds.foo.com:{srv.port}",
                          "bindDN": "cn=root", "bindPassword": "secret"},
                    nic_provider=lambda: [])
                await rec.wait_ready()
                dcs = dict(rec.dcs)
                await rec.close()
                return dcs

        dcs = asyncio.run(go())
        assert dcs == {"east-1": ["10.99.99.38", "10.99.99.39"],
                       "west-1": ["10.77.77.10"]}
