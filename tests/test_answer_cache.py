"""Answer-cache behavior: correctness under mutation, rotation, expiry.

The modern form of the reference's legacy -s/-a cache flags
(main.js:34-38); invalidation is generation-based so a hit can never
serve pre-mutation data.
"""
import asyncio


from binder_tpu.dns import Message, Rcode, Type, make_query
from binder_tpu.metrics.collector import MetricsCollector
from binder_tpu.server import BinderServer
from binder_tpu.store import FakeStore, MirrorCache

DOMAIN = "foo.com"


def build(cache_size=10000, **kw):
    store = FakeStore()
    cache = MirrorCache(store, DOMAIN)
    store.put_json("/com/foo/web",
                   {"type": "host", "host": {"address": "192.168.0.1"}})
    store.put_json("/com/foo/svc", {
        "type": "service",
        "service": {"srvce": "_pg", "proto": "_tcp", "port": 5432}})
    for i in range(4):
        store.put_json(f"/com/foo/svc/lb{i}",
                       {"type": "load_balancer",
                        "load_balancer": {"address": f"10.0.1.{i + 1}"}})
    store.start_session()
    server = BinderServer(zk_cache=cache, dns_domain=DOMAIN,
                          datacenter_name="dc0", host="127.0.0.1", port=0,
                          collector=MetricsCollector(),
                          cache_size=cache_size, **kw)
    return store, cache, server


async def udp_ask(port, name, qtype, qid=1):
    loop = asyncio.get_running_loop()
    fut = loop.create_future()

    class P(asyncio.DatagramProtocol):
        def connection_made(self, t):
            t.sendto(make_query(name, qtype, qid=qid).encode())

        def datagram_received(self, d, a):
            if not fut.done():
                fut.set_result(d)

    tr, _ = await loop.create_datagram_endpoint(
        P, remote_addr=("127.0.0.1", port))
    try:
        return Message.decode(await asyncio.wait_for(fut, 5))
    finally:
        tr.close()


class TestAnswerCache:
    def test_hits_serve_same_answer_with_new_id(self):
        async def run():
            store, cache, server = build()
            await server.start()
            r1 = await udp_ask(server.udp_port, "web.foo.com", Type.A, 10)
            r2 = await udp_ask(server.udp_port, "web.foo.com", Type.A, 20)
            hits = server.answer_cache.hits
            await server.stop()
            return r1, r2, hits

        r1, r2, hits = asyncio.run(run())
        assert r1.id == 10 and r2.id == 20
        assert r1.answers[0].address == r2.answers[0].address
        assert hits >= 1

    def test_store_mutation_invalidates(self):
        async def run():
            store, cache, server = build()
            await server.start()
            r1 = await udp_ask(server.udp_port, "web.foo.com", Type.A, 1)
            await udp_ask(server.udp_port, "web.foo.com", Type.A, 2)  # hit
            store.put_json("/com/foo/web",
                           {"type": "host",
                            "host": {"address": "192.168.0.99"}})
            r3 = await udp_ask(server.udp_port, "web.foo.com", Type.A, 3)
            await server.stop()
            return r1, r3

        r1, r3 = asyncio.run(run())
        assert r1.answers[0].address == "192.168.0.1"
        assert r3.answers[0].address == "192.168.0.99"

    def test_rotation_preserved_for_service_answers(self):
        async def run():
            store, cache, server = build()
            await server.start()
            orders = []
            for i in range(30):
                r = await udp_ask(server.udp_port, "svc.foo.com", Type.A, i)
                orders.append(tuple(a.address for a in r.answers))
            hits = server.answer_cache.hits
            await server.stop()
            return orders, hits

        orders, hits = asyncio.run(run())
        # all answers always present...
        assert all(sorted(o) == ["10.0.1.1", "10.0.1.2", "10.0.1.3",
                                 "10.0.1.4"] for o in orders)
        # ...but the order rotates across responses (round-robin), and
        # the cache actually served most of them
        assert len(set(orders)) > 1
        assert hits >= 20

    def test_cache_disabled_with_size_zero(self):
        async def run():
            store, cache, server = build(cache_size=0)
            await server.start()
            for i in range(5):
                await udp_ask(server.udp_port, "web.foo.com", Type.A, i)
            hits = server.answer_cache.hits
            await server.stop()
            return hits

        assert asyncio.run(run()) == 0

    def test_refused_cached_but_invalidated_by_creation(self):
        async def run():
            store, cache, server = build()
            await server.start()
            r1 = await udp_ask(server.udp_port, "new.foo.com", Type.A, 1)
            await udp_ask(server.udp_port, "new.foo.com", Type.A, 2)
            store.put_json("/com/foo/new",
                           {"type": "host", "host": {"address": "10.2.2.2"}})
            r3 = await udp_ask(server.udp_port, "new.foo.com", Type.A, 3)
            await server.stop()
            return r1, r3

        r1, r3 = asyncio.run(run())
        assert r1.rcode == Rcode.REFUSED
        assert r3.rcode == Rcode.NOERROR
        assert r3.answers[0].address == "10.2.2.2"

    def test_padded_queries_collapse_to_one_cache_key(self):
        """Queries padded with varying bogus answer records must not mint
        one cache key per padding variation (memory pinning + eviction
        attack); the canonical key ignores padding, so every variant maps
        to the same entry and still gets correct answers."""
        from binder_tpu.dns.wire import ARecord

        async def run():
            store, cache, server = build()
            await server.start()
            loop = asyncio.get_running_loop()

            rcodes = []
            for i in range(4):
                padded = make_query("web.foo.com", Type.A, qid=5 + i)
                for j in range(30):
                    padded.answers.append(
                        ARecord(name=f"pad{i}x{j}.foo.com", ttl=1,
                                address=f"10.9.{i + 1}.{j + 1}"))
                wire = padded.encode()
                assert len(wire) > 320

                fut = loop.create_future()

                class P(asyncio.DatagramProtocol):
                    def connection_made(self, t):
                        t.sendto(wire)

                    def datagram_received(self, d, a):
                        if not fut.done():
                            fut.set_result(d)

                tr, _ = await loop.create_datagram_endpoint(
                    P, remote_addr=("127.0.0.1", server.udp_port))
                try:
                    rcodes.append(Message.decode(
                        await asyncio.wait_for(fut, 5)).rcode)
                finally:
                    tr.close()
            n_entries = len(server.answer_cache._entries)
            hits = server.answer_cache.hits
            await server.stop()
            return rcodes, n_entries, hits

        rcodes, n_entries, hits = asyncio.run(run())
        assert all(rc == Rcode.NOERROR for rc in rcodes)
        assert n_entries == 1
        assert hits >= 1

    def test_cache_hit_log_keeps_answer_summaries(self):
        """Query-log lines for cache hits must still carry the served
        records (stored alongside the cached wire)."""
        import logging

        async def run():
            store, cache, server = build()
            records = []

            class Capture(logging.Handler):
                def emit(self, rec):
                    records.append(rec)

            server.log.addHandler(Capture())
            server.log.setLevel(logging.INFO)
            await server.start()
            await udp_ask(server.udp_port, "web.foo.com", Type.A, 1)
            await udp_ask(server.udp_port, "web.foo.com", Type.A, 2)  # hit
            hits = server.answer_cache.hits
            await server.stop()
            return records, hits

        records, hits = asyncio.run(run())
        assert hits >= 1
        cached_logs = [r for r in records
                       if getattr(r, "binder", {}).get("cached")]
        assert cached_logs, "no cache-hit query log emitted"
        for r in cached_logs:
            assert r.binder.get("answers"), "cache-hit log lost its answers"

    def test_additional_padding_does_not_mint_cache_keys(self):
        """Queries varied only by bogus non-OPT additional records must
        all map to one canonical key (same eviction attack through the
        additionals section)."""
        from binder_tpu.dns.wire import ARecord

        async def run():
            store, cache, server = build()
            await server.start()
            loop = asyncio.get_running_loop()

            rcodes = []
            for i in range(3):
                q = make_query("web.foo.com", Type.A, qid=i)
                q.additionals.append(
                    ARecord(name=f"pad{i}.foo.com", ttl=1,
                            address=f"10.8.8.{i + 1}"))
                wire = q.encode()
                assert len(wire) <= 320

                fut = loop.create_future()

                class P(asyncio.DatagramProtocol):
                    def connection_made(self, t):
                        t.sendto(wire)

                    def datagram_received(self, d, a):
                        if not fut.done():
                            fut.set_result(d)

                tr, _ = await loop.create_datagram_endpoint(
                    P, remote_addr=("127.0.0.1", server.udp_port))
                try:
                    rcodes.append(
                        Message.decode(await asyncio.wait_for(fut, 5)).rcode)
                finally:
                    tr.close()
            n_entries = len(server.answer_cache._entries)
            await server.stop()
            return rcodes, n_entries

        rcodes, n_entries = asyncio.run(run())
        assert all(rc == Rcode.NOERROR for rc in rcodes)
        assert n_entries <= 1

    def test_edns_cookie_variants_share_one_key_and_hit(self):
        """Per-packet EDNS option bytes (DNS cookies, RFC 7873) must not
        mint distinct cache keys — cookie-sending resolvers are the
        common case and should get cache hits."""
        import os
        import struct

        async def run():
            store, cache, server = build()
            await server.start()
            loop = asyncio.get_running_loop()

            rcodes = []
            for i in range(5):
                base = make_query("web.foo.com", Type.A, qid=100 + i,
                                  edns_payload=1232).encode()
                # the bare OPT ends with rdlen=0; splice in a varying
                # 8-byte COOKIE option (code 10)
                assert base[-2:] == b"\x00\x00"
                cookie = os.urandom(8)
                wire = (base[:-2] + struct.pack(">HHH", 12, 10, 8) + cookie)

                fut = loop.create_future()

                class P(asyncio.DatagramProtocol):
                    def connection_made(self, t):
                        t.sendto(wire)

                    def datagram_received(self, d, a):
                        if not fut.done():
                            fut.set_result(d)

                tr, _ = await loop.create_datagram_endpoint(
                    P, remote_addr=("127.0.0.1", server.udp_port))
                try:
                    rcodes.append(Message.decode(
                        await asyncio.wait_for(fut, 5)).rcode)
                finally:
                    tr.close()
            n_entries = len(server.answer_cache._entries)
            hits = server.answer_cache.hits
            await server.stop()
            return rcodes, n_entries, hits

        rcodes, n_entries, hits = asyncio.run(run())
        assert all(rc == Rcode.NOERROR for rc in rcodes)
        assert n_entries == 1
        assert hits >= 4
