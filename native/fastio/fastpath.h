/*
 * Shared declarations between fastio.c (module definition, batched
 * recv/send) and fastpath.c (native answer cache).
 */
#ifndef BINDER_FASTPATH_H
#define BINDER_FASTPATH_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <sys/socket.h>

/* fastio.c */
PyObject *fastio_addr_to_tuple(const struct sockaddr_storage *ss);

/* fastpath.c */
PyObject *fastpath_new(PyObject *self, PyObject *args);
PyObject *fastpath_put(PyObject *self, PyObject *args);
PyObject *fastpath_drain(PyObject *self, PyObject *args);
PyObject *fastpath_stats(PyObject *self, PyObject *args);
PyObject *fastpath_clear(PyObject *self, PyObject *args);

#endif /* BINDER_FASTPATH_H */
