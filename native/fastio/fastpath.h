/*
 * Shared declarations between fastio.c (module definition, batched
 * recv/send) and fastpath.c (native answer cache).
 */
#ifndef BINDER_FASTPATH_H
#define BINDER_FASTPATH_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <sys/socket.h>

#define FASTIO_BATCH 64
#define FASTIO_DGRAM_MAX 65535

/* fastio.c */
PyObject *fastio_addr_to_tuple(const struct sockaddr_storage *ss);

/* receive arena shared by recv_batch and fastpath_drain — only one of
 * them runs at a time (both hold the GIL for the whole call), and a
 * process uses one or the other per readiness event; sharing saves ~4MB
 * RSS over two static copies */
extern unsigned char fastio_shared_bufs[FASTIO_BATCH][FASTIO_DGRAM_MAX];

/* fastpath.c */
PyObject *fastpath_new(PyObject *self, PyObject *args);
PyObject *fastpath_put(PyObject *self, PyObject *args);
PyObject *fastpath_zone_put(PyObject *self, PyObject *args);
PyObject *fastpath_serve_wire(PyObject *self, PyObject *args);
PyObject *fastpath_serve_frames(PyObject *self, PyObject *args);
PyObject *fastpath_drain(PyObject *self, PyObject *args);
PyObject *fastpath_stats(PyObject *self, PyObject *args);
PyObject *fastpath_clear(PyObject *self, PyObject *args);
PyObject *fastpath_zone_reserve(PyObject *self, PyObject *args);
PyObject *fastpath_invalidate(PyObject *self, PyObject *args);
PyObject *fastpath_invalidate_many(PyObject *self, PyObject *args);
PyObject *fastpath_log_enable(PyObject *self, PyObject *args);
PyObject *fastpath_log_drain(PyObject *self, PyObject *args);

#endif /* BINDER_FASTPATH_H */
