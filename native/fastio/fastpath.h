/*
 * Shared declarations between fastio.c (module definition, batched
 * recv/send) and fastpath.c (native answer cache).
 */
#ifndef BINDER_FASTPATH_H
#define BINDER_FASTPATH_H

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <sys/socket.h>

#define FASTIO_BATCH 64
#define FASTIO_DGRAM_MAX 65535

/* fastio.c */
PyObject *fastio_addr_to_tuple(const struct sockaddr_storage *ss);

/* Process-wide I/O accounting shared by every batched entry point
 * (recv_batch, send_batch, fastpath_drain, fastpath_serve_balancer).
 * The batch-size histogram is the observable for "sampling must not
 * defeat batching": if the duty-cycle sampler serialized the drain,
 * every cell above recv_cells[0] would empty out. */
#define FASTIO_IO_CELLS 8   /* log2 cells: 1, 2-3, 4-7, ..., >=128 */
typedef struct {
    unsigned long long recv_calls;   /* recvmmsg calls that returned >0 */
    unsigned long long recv_msgs;
    unsigned long long recv_cells[FASTIO_IO_CELLS];
    unsigned long long send_calls;   /* sendmmsg calls that sent >0 */
    unsigned long long send_msgs;
} fastio_io_t;
extern fastio_io_t fastio_io;

static inline void
fastio_io_note_recv(int n)
{
    if (n <= 0)
        return;
    fastio_io.recv_calls++;
    fastio_io.recv_msgs += (unsigned long long)n;
    int cell = 0;
    while (cell < FASTIO_IO_CELLS - 1 && (1 << (cell + 1)) <= n)
        cell++;
    fastio_io.recv_cells[cell]++;
}

static inline void
fastio_io_note_send(int n)
{
    if (n <= 0)
        return;
    fastio_io.send_calls++;
    fastio_io.send_msgs += (unsigned long long)n;
}

/* receive arena shared by recv_batch and fastpath_drain — only one of
 * them runs at a time (both hold the GIL for the whole call), and a
 * process uses one or the other per readiness event; sharing saves ~4MB
 * RSS over two static copies */
extern unsigned char fastio_shared_bufs[FASTIO_BATCH][FASTIO_DGRAM_MAX];

/* fastpath.c */
PyObject *fastpath_new(PyObject *self, PyObject *args);
PyObject *fastpath_put(PyObject *self, PyObject *args);
PyObject *fastpath_zone_put(PyObject *self, PyObject *args);
PyObject *fastpath_serve_wire(PyObject *self, PyObject *args);
PyObject *fastpath_serve_frames(PyObject *self, PyObject *args);
PyObject *fastpath_serve_balancer(PyObject *self, PyObject *args);
PyObject *fastpath_drain(PyObject *self, PyObject *args);
PyObject *fastpath_stats(PyObject *self, PyObject *args);
PyObject *fastpath_clear(PyObject *self, PyObject *args);
PyObject *fastpath_zone_reserve(PyObject *self, PyObject *args);
PyObject *fastpath_invalidate(PyObject *self, PyObject *args);
PyObject *fastpath_invalidate_many(PyObject *self, PyObject *args);
PyObject *fastpath_log_enable(PyObject *self, PyObject *args);
PyObject *fastpath_log_drain(PyObject *self, PyObject *args);

#endif /* BINDER_FASTPATH_H */
