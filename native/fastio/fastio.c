/*
 * _binderfastio — batched UDP syscalls for the DNS hot path.
 *
 * The reference's hot path is one recvfrom + one sendto per query inside
 * the Node event loop (via the mname engine); per-packet syscall and
 * event-loop costs are the floor of its throughput.  This extension
 * lowers that floor for the rebuild: recvmmsg(2)/sendmmsg(2) move up to
 * BATCH datagrams per kernel crossing, which matters on the single-core
 * deployment unit (reference scales by adding processes, not threads —
 * boot/setup.sh:145-149 — so per-process efficiency is the multiplier).
 *
 * API (IPv4 + IPv6 UDP sockets, non-blocking):
 *   recv_batch(fd, max_n)  -> list[(bytes payload, (str host, int port))]
 *                             empty list when the socket would block
 *   send_batch(fd, msgs)   -> int processed count; msgs is a sequence of
 *                             (bytes payload, addr) where addr is
 *                             (host, port) or, for IPv6, optionally
 *                             (host, port, flowinfo, scope_id).
 *                             Per-destination errors (EHOSTUNREACH,
 *                             EPERM, ...) skip that one datagram and
 *                             continue — one unreachable client must not
 *                             drop other clients' responses (same
 *                             tolerance as the per-packet sendto path,
 *                             reference lib/server.js:593-607).  Only
 *                             EAGAIN stops early; caller retries or
 *                             drops the remainder (UDP best effort).
 *
 * Pure CPython C API (no pybind11 in this image; see repo NOTES.md).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>

#include "fastpath.h"

unsigned char fastio_shared_bufs[FASTIO_BATCH][FASTIO_DGRAM_MAX];

fastio_io_t fastio_io;

PyObject *
fastio_addr_to_tuple(const struct sockaddr_storage *ss)
{
    char host[INET6_ADDRSTRLEN];

    if (ss->ss_family == AF_INET) {
        const struct sockaddr_in *sa = (const struct sockaddr_in *)ss;
        if (inet_ntop(AF_INET, &sa->sin_addr, host, sizeof(host)) == NULL)
            return NULL;
        return Py_BuildValue("(sI)", host, (unsigned)ntohs(sa->sin_port));
    }
    if (ss->ss_family == AF_INET6) {
        /* Python's 4-tuple form, keeping flowinfo and the scope id —
         * without the scope id, replies to link-local (fe80::) clients
         * cannot be routed */
        const struct sockaddr_in6 *sa6 = (const struct sockaddr_in6 *)ss;
        if (inet_ntop(AF_INET6, &sa6->sin6_addr, host, sizeof(host)) == NULL)
            return NULL;
        return Py_BuildValue("(sIII)", host,
                             (unsigned)ntohs(sa6->sin6_port),
                             (unsigned)ntohl(sa6->sin6_flowinfo),
                             (unsigned)sa6->sin6_scope_id);
    }
    PyErr_Format(PyExc_OSError, "unsupported address family %d",
                 (int)ss->ss_family);
    return NULL;
}

static int
tuple_to_addr(PyObject *addr, struct sockaddr_storage *ss, socklen_t *len)
{
    const char *host;
    unsigned port;
    unsigned flowinfo = 0, scope_id = 0;

    if (!PyTuple_Check(addr)) {
        PyErr_SetString(PyExc_TypeError,
                        "address must be (host, port[, flowinfo, scope_id])");
        return -1;
    }
    if (!PyArg_ParseTuple(addr, "sI|II;address must be (host, port"
                          "[, flowinfo, scope_id])",
                          &host, &port, &flowinfo, &scope_id))
        return -1;
    memset(ss, 0, sizeof(*ss));
    if (strchr(host, ':') != NULL) {
        struct sockaddr_in6 *sa6 = (struct sockaddr_in6 *)ss;
        sa6->sin6_family = AF_INET6;
        sa6->sin6_port = htons((uint16_t)port);
        sa6->sin6_flowinfo = htonl(flowinfo);
        sa6->sin6_scope_id = scope_id;
        if (inet_pton(AF_INET6, host, &sa6->sin6_addr) != 1) {
            PyErr_Format(PyExc_ValueError, "bad IPv6 address %s", host);
            return -1;
        }
        *len = sizeof(*sa6);
    } else {
        struct sockaddr_in *sa = (struct sockaddr_in *)ss;
        sa->sin_family = AF_INET;
        sa->sin_port = htons((uint16_t)port);
        if (inet_pton(AF_INET, host, &sa->sin_addr) != 1) {
            PyErr_Format(PyExc_ValueError, "bad IPv4 address %s", host);
            return -1;
        }
        *len = sizeof(*sa);
    }
    return 0;
}

static PyObject *
fastio_recv_batch(PyObject *self, PyObject *args)
{
    int fd;
    int max_n = FASTIO_BATCH;
    (void)self;

    if (!PyArg_ParseTuple(args, "i|i", &fd, &max_n))
        return NULL;
    if (max_n < 1) max_n = 1;
    if (max_n > FASTIO_BATCH) max_n = FASTIO_BATCH;

    /* shared payload arena reused across calls; safe because the GIL is
     * held for the whole call (MSG_DONTWAIT never blocks, so there is
     * nothing to gain from releasing it) */
    unsigned char (*bufs)[FASTIO_DGRAM_MAX] = fastio_shared_bufs;
    struct mmsghdr msgs[FASTIO_BATCH];
    struct iovec iovs[FASTIO_BATCH];
    struct sockaddr_storage addrs[FASTIO_BATCH];

    memset(msgs, 0, sizeof(struct mmsghdr) * (size_t)max_n);
    for (int i = 0; i < max_n; i++) {
        iovs[i].iov_base = bufs[i];
        iovs[i].iov_len = FASTIO_DGRAM_MAX;
        msgs[i].msg_hdr.msg_iov = &iovs[i];
        msgs[i].msg_hdr.msg_iovlen = 1;
        msgs[i].msg_hdr.msg_name = &addrs[i];
        msgs[i].msg_hdr.msg_namelen = sizeof(addrs[i]);
    }

    int n = recvmmsg(fd, msgs, (unsigned)max_n, MSG_DONTWAIT, NULL);

    if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
            return PyList_New(0);
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    fastio_io_note_recv(n);

    PyObject *out = PyList_New(n);
    if (out == NULL)
        return NULL;
    for (int i = 0; i < n; i++) {
        PyObject *payload = PyBytes_FromStringAndSize(
            (const char *)bufs[i], (Py_ssize_t)msgs[i].msg_len);
        PyObject *addr = payload ? fastio_addr_to_tuple(&addrs[i]) : NULL;
        if (payload == NULL || addr == NULL) {
            Py_XDECREF(payload);
            Py_XDECREF(addr);
            Py_DECREF(out);
            return NULL;
        }
        PyObject *item = PyTuple_Pack(2, payload, addr);
        Py_DECREF(payload);
        Py_DECREF(addr);
        if (item == NULL) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, item);
    }
    return out;
}

static PyObject *
fastio_send_batch(PyObject *self, PyObject *args)
{
    int fd;
    PyObject *seq;
    (void)self;

    if (!PyArg_ParseTuple(args, "iO", &fd, &seq))
        return NULL;
    PyObject *fast = PySequence_Fast(seq, "msgs must be a sequence");
    if (fast == NULL)
        return NULL;
    Py_ssize_t total = PySequence_Fast_GET_SIZE(fast);
    Py_ssize_t done = 0;

    while (done < total) {
        struct mmsghdr msgs[FASTIO_BATCH];
        struct iovec iovs[FASTIO_BATCH];
        struct sockaddr_storage addrs[FASTIO_BATCH];
        int n = 0;

        memset(msgs, 0, sizeof(msgs[0]) * FASTIO_BATCH);
        for (; n < FASTIO_BATCH && done + n < total; n++) {
            PyObject *item = PySequence_Fast_GET_ITEM(fast, done + n);
            PyObject *payload, *addr;
            char *data;
            Py_ssize_t dlen;

            if (!PyTuple_Check(item) || PyTuple_GET_SIZE(item) != 2) {
                PyErr_SetString(PyExc_TypeError,
                                "each msg must be (bytes, (host, port))");
                goto fail;
            }
            payload = PyTuple_GET_ITEM(item, 0);
            addr = PyTuple_GET_ITEM(item, 1);
            if (PyBytes_AsStringAndSize(payload, &data, &dlen) < 0)
                goto fail;
            socklen_t alen;
            if (tuple_to_addr(addr, &addrs[n], &alen) < 0)
                goto fail;
            iovs[n].iov_base = data;
            iovs[n].iov_len = (size_t)dlen;
            msgs[n].msg_hdr.msg_iov = &iovs[n];
            msgs[n].msg_hdr.msg_iovlen = 1;
            msgs[n].msg_hdr.msg_name = &addrs[n];
            msgs[n].msg_hdr.msg_namelen = alen;
        }

        /* drain this parsed chunk without rebuilding it: `off` advances
         * past sent and skipped datagrams so a run of failing
         * destinations costs one syscall each, not a chunk re-parse */
        int off = 0;
        int blocked = 0;
        while (off < n) {
            int sent;
            Py_BEGIN_ALLOW_THREADS
            sent = sendmmsg(fd, msgs + off, (unsigned)(n - off),
                            MSG_DONTWAIT);
            Py_END_ALLOW_THREADS
            if (sent >= 0) {
                /* a short count means msgs[off+sent] hit an error; the
                 * next pass re-sends from there and classifies it */
                fastio_io_note_send(sent);
                off += sent > 0 ? sent : 1;
                continue;
            }
            if (errno == EINTR)
                continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                blocked = 1;  /* buffer full: caller retries/drops rest */
                break;
            }
            if (errno == EBADF || errno == ENOTSOCK || errno == EFAULT ||
                errno == ENOMEM) {
                /* socket-fatal, not per-destination: surface it rather
                 * than mislabel the batch as delivered */
                Py_DECREF(fast);
                return PyErr_SetFromErrno(PyExc_OSError);
            }
            /* per-destination failure on the first datagram of the
             * remainder (EHOSTUNREACH/EPERM/EINVAL-bad-port/...): skip
             * that one datagram and carry on — one unreachable client
             * must not discard every other client's response */
            off += 1;
        }
        done += off;
        if (blocked)
            break;
    }
    Py_DECREF(fast);
    return PyLong_FromSsize_t(done);

fail:
    Py_DECREF(fast);
    return NULL;
}

static PyObject *
fastio_io_stats(PyObject *self, PyObject *args)
{
    int reset = 0;
    (void)self;

    if (!PyArg_ParseTuple(args, "|p", &reset))
        return NULL;
    PyObject *cells = PyList_New(FASTIO_IO_CELLS);
    if (cells == NULL)
        return NULL;
    for (int i = 0; i < FASTIO_IO_CELLS; i++) {
        PyObject *v = PyLong_FromUnsignedLongLong(fastio_io.recv_cells[i]);
        if (v == NULL) {
            Py_DECREF(cells);
            return NULL;
        }
        PyList_SET_ITEM(cells, i, v);
    }
    PyObject *d = Py_BuildValue(
        "{s:K,s:K,s:K,s:K,s:N}",
        "recv_calls", fastio_io.recv_calls,
        "recv_msgs", fastio_io.recv_msgs,
        "send_calls", fastio_io.send_calls,
        "send_msgs", fastio_io.send_msgs,
        "recv_cells", cells);
    if (d == NULL)
        return NULL;
    if (reset)
        memset(&fastio_io, 0, sizeof(fastio_io));
    return d;
}

static PyMethodDef fastio_methods[] = {
    {"recv_batch", fastio_recv_batch, METH_VARARGS,
     "recv_batch(fd, max_n=64) -> list[(bytes, (host, port))]"},
    {"send_batch", fastio_send_batch, METH_VARARGS,
     "send_batch(fd, msgs) -> int sent"},
    {"io_stats", fastio_io_stats, METH_VARARGS,
     "io_stats(reset=False) -> dict of process-wide batched-I/O "
     "counters (recvmmsg/sendmmsg calls, messages, and the recvmmsg "
     "batch-size log2 histogram)"},
    {"fastpath_new", fastpath_new, METH_VARARGS,
     "fastpath_new(size, expiry_ms, lat_buckets, size_buckets) -> capsule"},
    {"fastpath_put", fastpath_put, METH_VARARGS,
     "fastpath_put(cache, key, qtype, gen, wires) -> bool accepted"},
    {"fastpath_zone_put", fastpath_zone_put, METH_VARARGS,
     "fastpath_zone_put(cache, zkey, gen, ancount, bodies, tag"
     "[, arcount]) -> bool"},
    {"fastpath_serve_wire", fastpath_serve_wire, METH_VARARGS,
     "fastpath_serve_wire(cache, packet, gen) -> bytes | None"},
    {"fastpath_serve_frames", fastpath_serve_frames, METH_VARARGS,
     "fastpath_serve_frames(cache, framed, gen[, client, port, proto])"
     " -> (framed_responses, consumed, [miss_payload, ...])"},
    {"fastpath_serve_balancer", fastpath_serve_balancer, METH_VARARGS,
     "fastpath_serve_balancer(cache, chunk, gen, fd) -> "
     "(consumed, served, [raw_frame, ...]) — walk balancer frames in "
     "the chunk, answer UDP-transport hits directly on the passed "
     "(balancer-owned) fd via sendmmsg with explicit msg_name, and "
     "surface everything else as raw frames for the Python lane"},
    {"fastpath_drain", fastpath_drain, METH_VARARGS,
     "fastpath_drain(cache, fd, gen, max_n=64) -> (misses, served)"},
    {"fastpath_stats", fastpath_stats, METH_VARARGS,
     "fastpath_stats(cache) -> dict"},
    {"fastpath_clear", fastpath_clear, METH_VARARGS,
     "fastpath_clear(cache) -> None"},
    {"fastpath_zone_reserve", fastpath_zone_reserve, METH_VARARGS,
     "fastpath_zone_reserve(cache, expected_entries) -> None "
     "(presize the zone table so a bulk fill never rehashes "
     "mid-serving)"},
    {"fastpath_invalidate", fastpath_invalidate, METH_VARARGS,
     "fastpath_invalidate(cache, tag_qname_wire) -> dropped count"},
    {"fastpath_invalidate_many", fastpath_invalidate_many, METH_VARARGS,
     "fastpath_invalidate_many(cache, [tag_qname_wire, ...]) -> dropped"},
    {"fastpath_log_enable", fastpath_log_enable, METH_VARARGS,
     "fastpath_log_enable(cache, line_prefix, capacity=1MiB) -> None"},
    {"fastpath_log_drain", fastpath_log_drain, METH_VARARGS,
     "fastpath_log_drain(cache) -> bytes of complete log lines"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastio_module = {
    PyModuleDef_HEAD_INIT,
    "_binderfastio",
    "Batched UDP recvmmsg/sendmmsg for the DNS hot path",
    -1,
    fastio_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit__binderfastio(void)
{
    return PyModule_Create(&fastio_module);
}
