/*
 * fpcore.h — pure-C core of the fastpath answer cache.
 *
 * Everything below is Python-free: the cache table, key lookup, the
 * insert/replace/evict policy, and the per-packet serve path (variant
 * rotation + id/0x20 question patching).  fastio/fastpath.c wraps this
 * in CPython glue (capsule lifecycle, argument validation, recvmmsg/
 * sendmmsg batching); native/fuzz/fuzz_fastpath.cpp drives the same
 * code under ASan+UBSan with mutated inputs.
 *
 * The split exists so the sanitized fuzz target exercises the real
 * fill/serve/rotation code, not a re-implementation (VERDICT r2 weak 2).
 */
#ifndef BINDER_FPCORE_H
#define BINDER_FPCORE_H

#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#include "../common/dnskey.h"

#define FP_MAX_VARIANTS 8
#define FP_PROBE 8
#define FP_MAX_WIRE 4096          /* larger responses stay in Python */
#define FP_MAX_KEY DNSKEY_MAX
#define FP_MAX_QTYPES 16
#define FP_MAX_BUCKETS 24
#define FP_MAX_TOTAL_BYTES (64u << 20)
#define FP_QTYPE_OTHER 0xFFFF     /* stats catch-all past FP_MAX_QTYPES */

#define FP_MAX_TAG 256            /* a qname in wire label format */

/*
 * Query-log ring: lets the fast path serve while per-query logging is
 * on (the reference's always-on posture, lib/server.js:537-591) instead
 * of standing down.  Entries carry a pre-rendered JSON *fragment* (the
 * answer-dependent middle of the log line: query/cached/rcode/answers/
 * additional — rendered ONCE at push time by Python, not per query);
 * at serve time the C side appends one complete bunyan-style line to a
 * byte ring: constant prefix (name/hostname/pid/level/component/msg,
 * supplied by Python at enable time) + timestamp + per-query fields
 * (req id, client, port/proto, edns) + the fragment + latency.  Python
 * drains the ring in batches and writes it to the log stream — one
 * stream write per batch, not one formatting pass per query.
 *
 * Parity rule: a serve that CANNOT produce its log line (no fragment
 * pushed, ring full because Python is draining too slowly, no client
 * address available) must DECLINE to Python — which logs normally —
 * never serve-and-drop the line.  Logged-posture serving degrades to
 * the slow path under pressure; it never loses log records.
 */
#define FP_MAX_FRAG 4096          /* per-variant pre-rendered fragment */
#define FP_LOG_PREFIX_MAX 512    /* constant line head from Python */
#define FP_LOG_OVERHEAD 256      /* time+id+client+port+latency+glue */

typedef struct {                  /* per-serve source context */
    const char *client;           /* numeric address string, JSON-safe */
    unsigned port;
    const char *proto;            /* "udp" / "tcp" / "balancer" */
} fp_logsrc_t;

typedef struct {
    uint8_t *buf;
    size_t cap;
    size_t len;
    uint64_t lines;               /* lines appended since enable */
    uint64_t declines;            /* serves declined for log reasons */
    uint8_t prefix[FP_LOG_PREFIX_MAX];
    size_t prefix_len;
    int enabled;
    time_t cached_sec;            /* strftime result reused per second */
    char secbuf[24];
    int secbuf_len;
} fp_logring_t;

typedef struct {
    uint8_t key[FP_MAX_KEY];
    uint16_t keylen;
    /* dependency tag (hashed): the wire-format qname of the store name
     * this answer derives from (SRV answers are keyed by the full
     * _svc._proto.name qname but depend on the service node's domain) —
     * matched by fp_invalidate_tag when that name mutates.  Only the
     * 64-bit hash is kept: equality is the only operation, and a hash
     * collision merely drops an extra entry that then re-resolves, so
     * the always-resident slot table stays small */
    uint64_t taghash;
    uint8_t has_tag;
    uint64_t gen;
    double expire_at;
    double inserted_at;
    uint8_t n_variants;
    uint8_t next_variant;
    uint16_t qtype;
    uint8_t *wires[FP_MAX_VARIANTS];
    uint16_t wire_lens[FP_MAX_VARIANTS];
    /* pre-rendered per-variant log fragments (NULL when pushed in the
     * log-off posture; such entries decline when logging is on) */
    uint8_t *frags[FP_MAX_VARIANTS];
    uint16_t frag_lens[FP_MAX_VARIANTS];
    int used;
} fp_entry_t;

typedef struct {
    uint16_t qtype;
    uint64_t count;
    double lat_sum;
    double size_sum;
    uint64_t lat_cells[FP_MAX_BUCKETS + 1];
    uint64_t size_cells[FP_MAX_BUCKETS + 1];
} fp_qstat_t;

/*
 * Zone table: precompiled authoritative answers (NSD/Knot-style zone
 * compilation, re-designed for a live mirror).  Where the answer cache
 * above remembers what Python resolved, the zone table is filled from
 * the STORE MIRROR itself — on every node-data arrival the server
 * pushes the finished answer body for that name — so even the first
 * query for a name is served inside the C drain.  The reference
 * resolves every cold name per query (lib/server.js:136); precompiling
 * the dominant record shapes is the rebuild's cold-path answer to that.
 *
 * Keyed by qtype+qclass+lowercased-wire-qname only (the last keylen-3
 * bytes of the dnskey) — unlike cache entries, a zone answer does not
 * depend on RD/EDNS/payload: those are patched/echoed at serve time and
 * the payload ceiling is re-checked per packet (truncation declines to
 * Python).  Entries carry the mirror epoch (stale generations are
 * lazily dropped) and the same dependency-tag invalidation as the
 * cache, so the one store-mutation path keeps every layer coherent.
 */
typedef struct {
    uint8_t key[FP_MAX_KEY];  /* qtype BE16 + qclass BE16 + qname */
    uint16_t keylen;
    uint64_t taghash;
    uint8_t has_tag;
    uint64_t gen;
    uint16_t qtype;
    uint16_t ancount;
    uint16_t arcount;         /* additionals baked into the body (SRV) */
    uint8_t n_variants;
    uint8_t next_variant;
    /* answer(+additional) sections; compression ptrs target offset 12 */
    uint8_t *bodies[FP_MAX_VARIANTS];
    uint16_t body_lens[FP_MAX_VARIANTS];
    uint8_t *frags[FP_MAX_VARIANTS];
    uint16_t frag_lens[FP_MAX_VARIANTS];
    int used;
} fp_zentry_t;

/* One zone hash table (open-addressed, FP_PROBE window, grown by
 * rehash).  There are two instances: `zmain` for entries whose
 * dependency tag is their own qname (host A, PTR, service plain-A) —
 * invalidated by O(1) key drops — and `zalien` for entries whose tag
 * differs (SRV: qname _svc._proto.name, tag = the service name), which
 * are invalidated by scanning.  Keeping the alien entries in their own
 * small table (sized by service count, not host count) bounds that
 * scan, which matters during mirror-build storms of tens of thousands
 * of invalidation events. */
typedef struct {
    fp_zentry_t *slots;
    uint32_t mask;            /* slot count - 1; 0 when unallocated */
    uint32_t n;
} fp_ztab_t;

typedef struct {
    fp_entry_t *slots;
    uint32_t mask;            /* slot count - 1 (power of two) */
    uint32_t n_entries;
    uint64_t total_bytes;     /* wire bytes held */
    double expiry_s;
    double lat_buckets[FP_MAX_BUCKETS];
    int n_lat_buckets;
    double size_buckets[FP_MAX_BUCKETS];
    int n_size_buckets;
    fp_qstat_t qstats[FP_MAX_QTYPES];
    int n_qstats;
    uint64_t hits;
    uint64_t lookups;
    uint64_t invalidations;   /* entries dropped by fp_invalidate_tag */
    fp_ztab_t zmain;          /* tag == qname: O(1) invalidation */
    fp_ztab_t zalien;         /* tag != qname: scan invalidation */
    uint64_t ztotal_bytes;
    uint64_t zone_hits;
    fp_logring_t lr;
} fp_cache_t;

/* EDNS OPT echoed on zone serves: root name, type 41, payload 1232,
 * no flags/options — byte-for-byte server.py _OPT_ECHO_WIRE */
static const uint8_t fp_opt_echo[11] = {
    0x00, 0x00, 0x29, 0x04, 0xD0, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00
};

static inline double
fp_now(void)
{
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

static inline uint64_t
fp_hash(const uint8_t *key, size_t len)
{
    uint64_t h = 1469598103934665603ull;        /* FNV-1a 64 */
    for (size_t i = 0; i < len; i++) {
        h ^= key[i];
        h *= 1099511628211ull;
    }
    return h;
}

static inline void
fp_entry_free(fp_cache_t *c, fp_entry_t *e)
{
    for (int i = 0; i < e->n_variants; i++) {
        c->total_bytes -= e->wire_lens[i];
        free(e->wires[i]);
        e->wires[i] = NULL;
        if (e->frags[i] != NULL) {
            c->total_bytes -= e->frag_lens[i];
            free(e->frags[i]);
            e->frags[i] = NULL;
        }
    }
    e->n_variants = 0;
    if (e->used) {
        e->used = 0;
        c->n_entries--;
    }
}

/* allocate the slot table; returns 0 ok, -1 OOM */
static inline int
fp_core_init(fp_cache_t *c, long size, long expiry_ms)
{
    /* 2x capacity so the probe window rarely fills before `size`
     * distinct keys are live */
    uint64_t want = 64;
    while (want < (uint64_t)size * 2 && want < (1u << 24))
        want <<= 1;
    c->slots = (fp_entry_t *)calloc(want, sizeof(fp_entry_t));
    if (c->slots == NULL)
        return -1;
    c->mask = (uint32_t)(want - 1);
    c->expiry_s = (double)expiry_ms / 1000.0;
    return 0;
}

static inline void
fp_zentry_free(fp_cache_t *c, fp_ztab_t *t, fp_zentry_t *e)
{
    for (int i = 0; i < e->n_variants; i++) {
        c->ztotal_bytes -= e->body_lens[i];
        free(e->bodies[i]);
        e->bodies[i] = NULL;
        if (e->frags[i] != NULL) {
            c->ztotal_bytes -= e->frag_lens[i];
            free(e->frags[i]);
            e->frags[i] = NULL;
        }
    }
    e->n_variants = 0;
    if (e->used) {
        e->used = 0;
        t->n--;
    }
}

static inline void
fp_ztab_clear(fp_cache_t *c, fp_ztab_t *t)
{
    if (t->slots == NULL)
        return;
    for (uint32_t i = 0; i <= t->mask; i++) {
        if (t->slots[i].used)
            fp_zentry_free(c, t, &t->slots[i]);
    }
}

static inline void
fp_core_clear(fp_cache_t *c)
{
    for (uint32_t i = 0; i <= c->mask; i++) {
        if (c->slots[i].used)
            fp_entry_free(c, &c->slots[i]);
    }
    fp_ztab_clear(c, &c->zmain);
    fp_ztab_clear(c, &c->zalien);
}

static inline void
fp_core_free(fp_cache_t *c)
{
    if (c->slots != NULL) {
        fp_core_clear(c);
        free(c->slots);
        c->slots = NULL;
    }
    free(c->zmain.slots);
    c->zmain.slots = NULL;
    free(c->zalien.slots);
    c->zalien.slots = NULL;
    free(c->lr.buf);
    c->lr.buf = NULL;
    c->lr.enabled = 0;
}

/* ---------------- query-log ring ---------------- */

/* Arm the log ring: `prefix` is the constant head of every line, up to
 * and including `"time":"` (Python renders it once from its logger
 * identity).  Returns 0 ok, -1 on OOM/bad args. */
static inline int
fp_log_enable(fp_cache_t *c, const uint8_t *prefix, size_t plen,
              size_t cap)
{
    if (plen == 0 || plen > FP_LOG_PREFIX_MAX)
        return -1;
    if (cap < 4096)
        cap = 4096;
    uint8_t *buf = (uint8_t *)malloc(cap);
    if (buf == NULL)
        return -1;
    free(c->lr.buf);
    memset(&c->lr, 0, sizeof(c->lr));
    c->lr.buf = buf;
    c->lr.cap = cap;
    memcpy(c->lr.prefix, prefix, plen);
    c->lr.prefix_len = plen;
    c->lr.cached_sec = (time_t)-1;
    c->lr.enabled = 1;
    return 0;
}

static inline void
fp_log_disable(fp_cache_t *c)
{
    free(c->lr.buf);
    memset(&c->lr, 0, sizeof(c->lr));
}

/* room for one line with an `fraglen`-byte fragment?  (the decline
 * check run BEFORE a serve commits to answering natively) */
static inline int
fp_log_room(const fp_cache_t *c, size_t fraglen)
{
    return c->lr.len + c->lr.prefix_len + fraglen + FP_LOG_OVERHEAD
        <= c->lr.cap;
}

/* append the RFC3339 UTC timestamp; seconds part cached per second */
static inline int
fp_log_time(fp_logring_t *lr, char *p)
{
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    if (ts.tv_sec != lr->cached_sec) {
        struct tm tm;
        gmtime_r(&ts.tv_sec, &tm);
        lr->secbuf_len = (int)strftime(lr->secbuf, sizeof(lr->secbuf),
                                       "%Y-%m-%dT%H:%M:%S", &tm);
        lr->cached_sec = ts.tv_sec;
    }
    memcpy(p, lr->secbuf, (size_t)lr->secbuf_len);
    return lr->secbuf_len + sprintf(p + lr->secbuf_len, ".%03ldZ",
                                    ts.tv_nsec / 1000000L);
}

/* Append one complete log line.  The caller has already verified
 * fp_log_room for this fragment; src/frag are non-NULL. */
static inline void
fp_log_append(fp_cache_t *c, const uint8_t *pkt, int edns,
              const uint8_t *frag, size_t fraglen,
              const fp_logsrc_t *src, double lat_ms)
{
    fp_logring_t *lr = &c->lr;
    char *base = (char *)lr->buf;
    char *p = base + lr->len;
    memcpy(p, lr->prefix, lr->prefix_len);
    p += lr->prefix_len;
    p += fp_log_time(lr, p);
    p += sprintf(p,
                 "\",\"v\":0,\"req_id\":%u,\"client\":\"%s\","
                 "\"port\":\"%u/%s\",\"edns\":%s,",
                 (unsigned)((pkt[0] << 8) | pkt[1]), src->client,
                 src->port, src->proto, edns ? "true" : "false");
    memcpy(p, frag, fraglen);
    p += fraglen;
    p += sprintf(p, ",\"latency\":%.3f,\"timers\":{}}\n", lat_ms);
    lr->len = (size_t)(p - base);
    lr->lines++;
}

static inline int
fp_bucket_index(const double *buckets, int n, double v)
{
    /* first bucket with bound >= v; n == +Inf cell (matches Python's
     * bisect_left non-cumulative cells in metrics/collector.py) */
    int i = 0;
    while (i < n && buckets[i] < v)
        i++;
    return i;
}

static inline fp_qstat_t *
fp_qstat(fp_cache_t *c, uint16_t qtype)
{
    for (int i = 0; i < c->n_qstats; i++) {
        if (c->qstats[i].qtype == qtype)
            return &c->qstats[i];
    }
    if (c->n_qstats < FP_MAX_QTYPES - 1) {
        fp_qstat_t *s = &c->qstats[c->n_qstats++];
        memset(s, 0, sizeof(*s));
        s->qtype = qtype;
        return s;
    }
    /* overflow: the final slot is a dedicated catch-all labeled with the
     * sentinel qtype (folded as "other" by the server) — a client
     * cycling many qtypes must not misattribute counts to a real type */
    fp_qstat_t *s = &c->qstats[FP_MAX_QTYPES - 1];
    if (c->n_qstats < FP_MAX_QTYPES) {
        memset(s, 0, sizeof(*s));
        s->qtype = FP_QTYPE_OTHER;
        c->n_qstats = FP_MAX_QTYPES;
    }
    return s;
}

static inline fp_entry_t *
fp_find(fp_cache_t *c, const uint8_t *key, size_t keylen, uint64_t gen,
        double now)
{
    uint64_t h = fp_hash(key, keylen);
    for (int p = 0; p < FP_PROBE; p++) {
        fp_entry_t *e = &c->slots[(h + (uint64_t)p) & c->mask];
        if (!e->used)
            continue;
        if (e->keylen != keylen || memcmp(e->key, key, keylen) != 0)
            continue;
        if (e->gen != gen || now > e->expire_at) {
            fp_entry_free(c, e);        /* lazy invalidation */
            return NULL;
        }
        return e;
    }
    return NULL;
}

/*
 * Insert or replace an entry.  `expiry_s` is the effective lifetime for
 * THIS entry (the pusher may hand down a remaining lifetime shorter than
 * the cache-wide default).  `frags`/`frag_lens` (may be NULL) are the
 * per-variant pre-rendered log fragments for the logged posture; an
 * entry without them declines to Python whenever the log ring is on.
 * Returns 1 stored, 0 skipped (bounds/caps), -1 OOM (entry freed,
 * cache consistent).
 */
static inline int
fp_put_raw(fp_cache_t *c, const uint8_t *key, size_t keylen,
           uint16_t qtype, uint64_t gen, const uint8_t *const *wires,
           const uint16_t *wire_lens, int nw, double now, double expiry_s,
           const uint8_t *tag, size_t taglen,
           const uint8_t *const *frags, const uint16_t *frag_lens)
{
    if (keylen < 8 || keylen > FP_MAX_KEY)
        return 0;                       /* not representable: skip */
    if (taglen > FP_MAX_TAG)
        return 0;                       /* not invalidatable: skip */
    if (nw < 1 || nw > FP_MAX_VARIANTS)
        return 0;
    uint64_t add_bytes = 0;
    for (int i = 0; i < nw; i++) {
        if (wire_lens[i] < 12 || wire_lens[i] > FP_MAX_WIRE)
            return 0;                   /* oversize answers stay in Python */
        if (frags != NULL) {
            if (frags[i] == NULL || frag_lens[i] == 0
                    || frag_lens[i] > FP_MAX_FRAG)
                return 0;               /* unloggable: stays in Python */
            add_bytes += (uint64_t)frag_lens[i];
        }
        add_bytes += (uint64_t)wire_lens[i];
    }
    if (c->total_bytes + add_bytes > FP_MAX_TOTAL_BYTES)
        return 0;

    uint64_t h = fp_hash(key, keylen);
    fp_entry_t *target = NULL, *oldest = NULL;
    for (int p = 0; p < FP_PROBE; p++) {
        fp_entry_t *e = &c->slots[(h + (uint64_t)p) & c->mask];
        if (e->used && e->keylen == keylen &&
            memcmp(e->key, key, keylen) == 0) {
            target = e;                 /* replace in place */
            break;
        }
        if (!e->used) {
            if (target == NULL)
                target = e;
            continue;
        }
        if (oldest == NULL || e->inserted_at < oldest->inserted_at)
            oldest = e;
    }
    if (target == NULL)
        target = oldest;                /* probe window full: evict oldest */
    if (target->used)
        fp_entry_free(c, target);

    memcpy(target->key, key, keylen);
    target->keylen = (uint16_t)keylen;
    target->taghash = taglen > 0 ? fp_hash(tag, taglen) : 0;
    target->has_tag = taglen > 0;
    target->gen = gen;
    target->inserted_at = now;
    target->expire_at = now + expiry_s;
    target->next_variant = 0;
    target->qtype = qtype;
    target->n_variants = 0;
    for (int i = 0; i < nw; i++) {
        uint8_t *copy = (uint8_t *)malloc((size_t)wire_lens[i]);
        if (copy == NULL) {
            fp_entry_free(c, target);
            return -1;
        }
        memcpy(copy, wires[i], (size_t)wire_lens[i]);
        target->wires[i] = copy;
        target->wire_lens[i] = wire_lens[i];
        target->frags[i] = NULL;
        target->frag_lens[i] = 0;
        c->total_bytes += (uint64_t)wire_lens[i];
        if (frags != NULL) {
            uint8_t *fc = (uint8_t *)malloc((size_t)frag_lens[i]);
            if (fc == NULL) {
                target->n_variants = (uint8_t)(i + 1);
                fp_entry_free(c, target);
                return -1;
            }
            memcpy(fc, frags[i], (size_t)frag_lens[i]);
            target->frags[i] = fc;
            target->frag_lens[i] = frag_lens[i];
            c->total_bytes += (uint64_t)frag_lens[i];
        }
        target->n_variants = (uint8_t)(i + 1);
    }
    target->used = 1;
    c->n_entries++;
    return 1;
}

/* ---------------- zone table ---------------- */

#define FP_ZONE_MIN_SLOTS 1024
#define FP_ZONE_MAX_SLOTS (1u << 24)
#define FP_ZONE_MAX_BYTES (256u << 20)

/* Grow (or create) a zone slot table so a put can always find a free
 * probe slot at <=50% load.  Every live entry MUST stay findable
 * within the FP_PROBE lookup window — an entry displaced past it would
 * evade fp_ztab_find and therefore per-name invalidation, and could
 * later serve pre-mutation answers: a silent coherence violation.  So
 * the rehash reinserts under the same bound, retries at double size
 * when a probe cluster exceeds it, and as a last resort FREES the
 * unplaceable entry (those names fall back to the Python path until
 * their next push — slower, never stale).
 * Returns 0 ok, -1 OOM (table unchanged). */
static inline int
fp_zone_grow(fp_cache_t *c, fp_ztab_t *t, uint32_t want)
{
retry:
    if (want > FP_ZONE_MAX_SLOTS)
        return -1;
    fp_zentry_t *ns = (fp_zentry_t *)calloc(want, sizeof(fp_zentry_t));
    if (ns == NULL)
        return -1;
    fp_zentry_t *old = t->slots;
    uint32_t old_mask = t->mask;
    if (old != NULL) {
        for (uint32_t i = 0; i <= old_mask; i++) {
            fp_zentry_t *e = &old[i];
            if (!e->used)
                continue;
            uint64_t h = fp_hash(e->key, e->keylen);
            int placed = 0;
            for (uint32_t p = 0; p < FP_PROBE; p++) {
                fp_zentry_t *dst = &ns[(h + p) & (want - 1)];
                if (!dst->used) {
                    *dst = *e;
                    placed = 1;
                    break;
                }
            }
            if (!placed) {
                if (want * 2 <= FP_ZONE_MAX_SLOTS) {
                    free(ns);           /* cluster > window: go bigger */
                    want *= 2;
                    goto retry;
                }
                /* at the size cap: drop rather than displace */
                fp_zentry_free(c, t, e);
            }
        }
    }
    t->slots = ns;
    t->mask = want - 1;
    free(old);
    return 0;
}

static inline int
fp_zone_ensure(fp_cache_t *c, fp_ztab_t *t)
{
    if (t->slots != NULL && t->n * 2 <= t->mask)
        return 0;
    uint32_t want = t->slots == NULL ? FP_ZONE_MIN_SLOTS
                                     : (t->mask + 1) * 2;
    return fp_zone_grow(c, t, want);
}

/* Presize for an expected entry count so a bulk zone fill never
 * rehashes mid-serving: growth rehashes are O(table), and at
 * production zone scale the largest one measured ~370 ms on the dev
 * VM — an event-loop stall, not a hiccup.  The Python fill walk calls
 * this once with the mirror's name count before pushing. */
static inline int
fp_zone_reserve(fp_cache_t *c, fp_ztab_t *t, uint32_t entries)
{
    uint64_t want = FP_ZONE_MIN_SLOTS;
    while (want < (uint64_t)entries * 2)
        want <<= 1;
    if (want > FP_ZONE_MAX_SLOTS)
        want = FP_ZONE_MAX_SLOTS;
    if (t->slots != NULL && (uint64_t)t->mask + 1 >= want)
        return 0;
    return fp_zone_grow(c, t, (uint32_t)want);
}

static inline fp_zentry_t *
fp_ztab_find(fp_ztab_t *t, const uint8_t *zkey, size_t zklen)
{
    if (t->slots == NULL)
        return NULL;
    uint64_t h = fp_hash(zkey, zklen);
    for (int p = 0; p < FP_PROBE; p++) {
        fp_zentry_t *e = &t->slots[(h + (uint64_t)p) & t->mask];
        if (e->used && e->keylen == zklen &&
            memcmp(e->key, zkey, zklen) == 0)
            return e;
    }
    return NULL;
}

/*
 * Insert or replace a precompiled answer.  `zkey` is qtype+qclass+
 * lowercased wire qname (the dnskey minus its 3 request-dependent
 * lead bytes); bodies are finished answer(+additional) sections whose
 * compression pointers target offset 12; `arcount` additionals (SRV
 * target A records) are included at the tail of each body.  Routes to
 * zmain when the tag is the entry's own qname with a directly-probed
 * qtype/class (O(1) invalidation), zalien otherwise (scan).
 * Returns 1 stored, 0 skipped, -1 OOM.
 */
static inline int
fp_zone_put(fp_cache_t *c, const uint8_t *zkey, size_t zklen,
            uint64_t gen, uint16_t ancount, uint16_t arcount,
            const uint8_t *const *bodies, const uint16_t *body_lens,
            int nv, const uint8_t *tag, size_t taglen,
            const uint8_t *const *frags, const uint16_t *frag_lens)
{
    if (zklen < 5 || zklen > FP_MAX_KEY)
        return 0;
    if (taglen == 0 || taglen > FP_MAX_TAG)
        return 0;                   /* uninvalidatable: never stale-safe */
    if (nv < 1 || nv > FP_MAX_VARIANTS || ancount == 0)
        return 0;
    uint64_t add = 0;
    for (int i = 0; i < nv; i++) {
        if (body_lens[i] == 0 || body_lens[i] > FP_MAX_WIRE)
            return 0;
        if (frags != NULL) {
            if (frags[i] == NULL || frag_lens[i] == 0
                    || frag_lens[i] > FP_MAX_FRAG)
                return 0;           /* unloggable: stays in Python */
            add += frag_lens[i];
        }
        add += body_lens[i];
    }
    if (c->ztotal_bytes + add > FP_ZONE_MAX_BYTES)
        return 0;

    /* Table routing must be a function of the KEY alone (the serve
     * path has only the key): (A|PTR, IN) keys live in zmain — where
     * fp_invalidate_tag's O(1) drop rebuilds them as (qtype, IN, tag),
     * which is only correct when the tag IS the qname, so any other
     * tag on such a key is rejected outright — and every other key
     * lives in the scanned (small) alien table. */
    uint16_t zqtype = (uint16_t)((zkey[0] << 8) | zkey[1]);
    uint16_t zqclass = (uint16_t)((zkey[2] << 8) | zkey[3]);
    int main_table = (zqtype == 1 || zqtype == 12) && zqclass == 1;
    if (main_table && !(taglen == zklen - 4 &&
                        memcmp(tag, zkey + 4, taglen) == 0))
        return 0;
    fp_ztab_t *t = main_table ? &c->zmain : &c->zalien;
    if (fp_zone_ensure(c, t) < 0)
        return -1;

    uint64_t h = fp_hash(zkey, zklen);
    fp_zentry_t *target = NULL, *stale = NULL, *oldest = NULL;
    for (int p = 0; p < FP_PROBE; p++) {
        fp_zentry_t *e = &t->slots[(h + (uint64_t)p) & t->mask];
        if (e->used && e->keylen == zklen &&
            memcmp(e->key, zkey, zklen) == 0) {
            target = e;             /* replace in place */
            break;
        }
        if (!e->used) {
            if (target == NULL)
                target = e;
            continue;
        }
        if (e->gen != gen && stale == NULL)
            stale = e;              /* pre-rebuild leftover: evictable */
        if (oldest == NULL)
            oldest = e;
    }
    if (target == NULL)
        target = stale != NULL ? stale : oldest;
    if (target->used)
        fp_zentry_free(c, t, target);

    memcpy(target->key, zkey, zklen);
    target->keylen = (uint16_t)zklen;
    target->taghash = fp_hash(tag, taglen);
    target->has_tag = 1;
    target->gen = gen;
    target->qtype = zqtype;
    target->ancount = ancount;
    target->arcount = arcount;
    target->next_variant = 0;
    target->n_variants = 0;
    for (int i = 0; i < nv; i++) {
        uint8_t *copy = (uint8_t *)malloc((size_t)body_lens[i]);
        if (copy == NULL) {
            fp_zentry_free(c, t, target);
            return -1;
        }
        memcpy(copy, bodies[i], (size_t)body_lens[i]);
        target->bodies[i] = copy;
        target->body_lens[i] = body_lens[i];
        target->frags[i] = NULL;
        target->frag_lens[i] = 0;
        c->ztotal_bytes += (uint64_t)body_lens[i];
        if (frags != NULL) {
            uint8_t *fc = (uint8_t *)malloc((size_t)frag_lens[i]);
            if (fc == NULL) {
                target->n_variants = (uint8_t)(i + 1);
                fp_zentry_free(c, t, target);
                return -1;
            }
            memcpy(fc, frags[i], (size_t)frag_lens[i]);
            target->frags[i] = fc;
            target->frag_lens[i] = frag_lens[i];
            c->ztotal_bytes += (uint64_t)frag_lens[i];
        }
        target->n_variants = (uint8_t)(i + 1);
    }
    target->used = 1;
    t->n++;
    return 1;
}

/*
 * Drop every entry whose dependency tag equals `tag` (a mirrored store
 * mutation changed that name's answers) — in the answer cache AND the
 * zone table, so one store-mutation path keeps every layer coherent.
 * Cache: full-table scan (mutation rates ~hundreds/s times thousands of
 * slots is microseconds, and needs no auxiliary index).  Zone: entries
 * are tagged with their own qname by construction (A, PTR), so two
 * O(1) key drops replace the scan; a scan runs only while alien-tagged
 * entries exist.  The distinction matters at mirror-build time, when
 * tens of thousands of invalidation events arrive while the zone table
 * is large.  Returns the number of entries dropped.
 */
#define FP_INVAL_BATCH 32   /* tags per batched invalidation pass */

/* Batched spelling: ONE pass over each scanned table for up to
 * FP_INVAL_BATCH tags.  A single store mutation emits several tags
 * (name, parent service, old/new PTR qnames); per-tag scans would cost
 * one full cache-table walk each, and mutation storms multiply that —
 * the batch form keeps the churn path at one walk per event. */
static inline uint32_t
fp_invalidate_tags(fp_cache_t *c, const uint8_t *const *tags,
                   const size_t *taglens, int ntags)
{
    if (ntags > FP_INVAL_BATCH) {
        /* oversize batches recurse in chunks — truncating instead
         * would silently leave tags 33+ serving pre-mutation answers,
         * the exact coherence violation this path exists to prevent */
        uint32_t n = 0;
        for (int off = 0; off < ntags; off += FP_INVAL_BATCH) {
            int chunk = ntags - off;
            if (chunk > FP_INVAL_BATCH)
                chunk = FP_INVAL_BATCH;
            n += fp_invalidate_tags(c, tags + off, taglens + off, chunk);
        }
        return n;
    }
    uint64_t hashes[FP_INVAL_BATCH];
    int nh = 0;
    for (int t = 0; t < ntags; t++) {
        if (taglens[t] == 0 || taglens[t] > FP_MAX_TAG)
            continue;
        hashes[nh++] = fp_hash(tags[t], taglens[t]);
    }
    if (nh == 0)
        return 0;
    uint32_t n = 0;
    if (c->n_entries > 0) {
        for (uint32_t i = 0; i <= c->mask; i++) {
            fp_entry_t *e = &c->slots[i];
            if (!e->used || !e->has_tag)
                continue;
            for (int t = 0; t < nh; t++) {
                if (e->taghash == hashes[t]) {
                    fp_entry_free(c, e);
                    n++;
                    break;
                }
            }
        }
    }
    if (c->zmain.n > 0) {
        static const uint16_t qtypes[2] = {1, 12};   /* A, PTR */
        uint8_t zkey[FP_MAX_KEY];
        int hi = 0;
        for (int t = 0; t < ntags; t++) {
            size_t taglen = taglens[t];
            if (taglen == 0 || taglen > FP_MAX_TAG)
                continue;
            uint64_t h = hashes[hi++];
            if (taglen + 4 > FP_MAX_KEY)
                continue;
            zkey[2] = 0;
            zkey[3] = 1;                             /* class IN */
            memcpy(zkey + 4, tags[t], taglen);
            for (int q = 0; q < 2; q++) {
                zkey[0] = (uint8_t)(qtypes[q] >> 8);
                zkey[1] = (uint8_t)(qtypes[q] & 0xFF);
                fp_zentry_t *e = fp_ztab_find(&c->zmain, zkey,
                                              taglen + 4);
                if (e != NULL && e->has_tag && e->taghash == h) {
                    fp_zentry_free(c, &c->zmain, e);
                    n++;
                }
            }
        }
    }
    if (c->zalien.n > 0) {
        /* the scan is bounded by the alien table's size (services, not
         * hosts) — cheap even under mirror-build invalidation storms */
        for (uint32_t i = 0; i <= c->zalien.mask; i++) {
            fp_zentry_t *e = &c->zalien.slots[i];
            if (!e->used || !e->has_tag)
                continue;
            for (int t = 0; t < nh; t++) {
                if (e->taghash == hashes[t]) {
                    fp_zentry_free(c, &c->zalien, e);
                    n++;
                    break;
                }
            }
        }
    }
    c->invalidations += n;
    return n;
}

static inline uint32_t
fp_invalidate_tag(fp_cache_t *c, const uint8_t *tag, size_t taglen)
{
    return fp_invalidate_tags(c, &tag, &taglen, 1);
}

/*
 * Serve one packet from the zone table: assemble header + question echo
 * (original case) + precompiled body + optional OPT echo.  `key` is the
 * full dnskey (RD/EDNS/payload in its lead bytes), `out` must hold
 * FP_MAX_WIRE.  Returns response length, or 0 to decline to Python
 * (miss, stale generation, or would-truncate).
 */
static inline size_t
fp_zone_serve(fp_cache_t *c, const uint8_t *pkt, const uint8_t *key,
              size_t keylen, size_t qn_len, uint64_t gen, uint8_t *out,
              uint16_t *qtype_out, double now, const fp_logsrc_t *src)
{
    /* table routing mirrors fp_zone_put exactly: (A|PTR, IN) keys can
     * only live in zmain, everything else only in zalien — probing the
     * other table would be a guaranteed miss on every lookup */
    uint16_t zqtype = (uint16_t)((key[3] << 8) | key[4]);
    uint16_t zqclass = (uint16_t)((key[5] << 8) | key[6]);
    fp_ztab_t *t = ((zqtype == 1 || zqtype == 12) && zqclass == 1)
        ? &c->zmain : &c->zalien;
    fp_zentry_t *e = fp_ztab_find(t, key + 3, keylen - 3);
    if (e == NULL)
        return 0;
    if (e->gen != gen) {
        fp_zentry_free(c, t, e);        /* lazy epoch invalidation */
        return 0;
    }
    int rd = key[0] & 1;
    int edns = key[0] & 2;
    unsigned payload = ((unsigned)key[1] << 8) | key[2];

    uint8_t v = e->next_variant;
    if (c->lr.enabled) {
        /* logged posture: a serve whose log line cannot be produced
         * declines (BEFORE rotation/accounting) — Python logs it */
        if (src == NULL || e->frags[v] == NULL
                || !fp_log_room(c, e->frag_lens[v])) {
            c->lr.declines++;
            return 0;
        }
    }
    e->next_variant = (uint8_t)((v + 1) % e->n_variants);
    size_t blen = e->body_lens[v];
    size_t total = 12 + qn_len + 4 + blen + (edns ? sizeof(fp_opt_echo) : 0);
    if (total > payload || total > FP_MAX_WIRE)
        return 0;                       /* truncation semantics: Python */

    out[0] = pkt[0];                    /* request id */
    out[1] = pkt[1];
    out[2] = (uint8_t)(0x84 | (rd ? 0x01 : 0));   /* QR|AA, RD echo */
    out[3] = 0;                         /* RA=0, rcode NOERROR */
    out[4] = 0; out[5] = 1;             /* QD=1 */
    out[6] = (uint8_t)(e->ancount >> 8);
    out[7] = (uint8_t)(e->ancount & 0xFF);
    out[8] = 0; out[9] = 0;             /* NS=0 */
    /* additionals baked into the body, plus the OPT echo when the
     * query carried EDNS (the OPT is appended after the body, i.e.
     * last in the additionals section, where the generic encoder also
     * places it) */
    uint16_t ar = (uint16_t)(e->arcount + (edns ? 1 : 0));
    out[10] = (uint8_t)(ar >> 8);
    out[11] = (uint8_t)(ar & 0xFF);
    memcpy(out + 12, pkt + 12, qn_len + 4);       /* 0x20 case echo */
    memcpy(out + 12 + qn_len + 4, e->bodies[v], blen);
    if (edns)
        memcpy(out + 12 + qn_len + 4 + blen, fp_opt_echo,
               sizeof(fp_opt_echo));
    if (qtype_out != NULL)
        *qtype_out = e->qtype;
    c->zone_hits++;
    if (c->lr.enabled)
        fp_log_append(c, pkt, edns, e->frags[v], e->frag_lens[v], src,
                      (fp_now() - now) * 1e3);
    return total;
}

/*
 * Serve one packet from the cache: key build, lookup (with lazy gen/TTL
 * invalidation), variant rotation, id + 0x20 question patching.  `out`
 * must hold FP_MAX_WIRE bytes.  Returns the response length on hit, 0 on
 * miss (the caller surfaces the packet to the slow path).
 *
 * `decline_tc`: refuse to serve truncated cached wires — set by the
 * socket-free entry (fastpath_serve_wire) whose callers may be TCP;
 * the decline happens BEFORE hit accounting and rotation so refused
 * serves neither inflate the folded cache-hit counter nor burn a
 * rotation step.  The UDP drain passes 0 (TC wires are correct there).
 */
static inline size_t
fp_serve_one_lx(fp_cache_t *c, const uint8_t *pkt, size_t plen,
                uint64_t gen, double now, uint8_t *out,
                uint16_t *qtype_out, int decline_tc,
                const fp_logsrc_t *src)
{
    uint8_t key[FP_MAX_KEY];
    size_t qn_len = 0;
    uint16_t qtype = 0;

    c->lookups++;
    size_t keylen = dnskey_build(pkt, plen, key, &qn_len, &qtype);
    if (keylen == 0)
        return 0;
    fp_entry_t *e = fp_find(c, key, keylen, gen, now);
    if (e == NULL)
        /* not in the answer cache: a precompiled zone answer still
         * serves it natively (first query for a name included; zone
         * entries are never truncated, so decline_tc is moot there) */
        return fp_zone_serve(c, pkt, key, keylen, qn_len, gen, out,
                             qtype_out, now, src);

    /* hit: copy the variant, patch id + the client's question bytes
     * (same length by construction — key match implies identical
     * lowercased label structure) */
    uint8_t v = e->next_variant;
    if (decline_tc && e->wire_lens[v] >= 3 && (e->wires[v][2] & 0x02))
        return 0;
    if (c->lr.enabled) {
        /* logged posture: decline (before rotation/accounting) when the
         * line can't be produced — Python serves AND logs instead */
        if (src == NULL || e->frags[v] == NULL
                || !fp_log_room(c, e->frag_lens[v])) {
            c->lr.declines++;
            return 0;
        }
    }
    e->next_variant = (uint8_t)((v + 1) % e->n_variants);
    const uint8_t *wire = e->wires[v];
    size_t wlen = e->wire_lens[v];
    if (wlen < 12 + qn_len + 4) {
        /* defensive: a cached response must embed the question */
        fp_entry_free(c, e);
        return 0;
    }
    memcpy(out, wire, wlen);
    out[0] = pkt[0];
    out[1] = pkt[1];
    memcpy(out + 12, pkt + 12, qn_len + 4);
    if (qtype_out != NULL)
        *qtype_out = e->qtype;
    c->hits++;
    if (c->lr.enabled)
        fp_log_append(c, pkt, key[0] & 2, e->frags[v], e->frag_lens[v],
                      src, (fp_now() - now) * 1e3);
    return wlen;
}

static inline size_t
fp_serve_one_ex(fp_cache_t *c, const uint8_t *pkt, size_t plen,
                uint64_t gen, double now, uint8_t *out,
                uint16_t *qtype_out, int decline_tc)
{
    return fp_serve_one_lx(c, pkt, plen, gen, now, out, qtype_out,
                           decline_tc, NULL);
}

/* drain-path spelling: TC wires serve (UDP requesters asked for them) */
static inline size_t
fp_serve_one(fp_cache_t *c, const uint8_t *pkt, size_t plen, uint64_t gen,
             double now, uint8_t *out, uint16_t *qtype_out)
{
    return fp_serve_one_ex(c, pkt, plen, gen, now, out, qtype_out, 0);
}

#endif /* BINDER_FPCORE_H */
