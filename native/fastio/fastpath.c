/*
 * _binderfastio fast path — native encoded-answer cache for the UDP drain.
 *
 * The Python answer cache (binder_tpu/resolver/answer_cache.py) already
 * makes repeat queries cheap; this moves the *hit* path out of Python
 * entirely.  `fastpath_drain(fd)` replaces `recv_batch(fd)` on the UDP
 * reader: it recvmmsg()s a batch, parses each question directly from the
 * wire, looks it up in a native cache, and answers hits with one
 * sendmmsg() — the Python event loop only ever sees the misses.  Python
 * stays the source of truth: it resolves misses through the normal
 * engine (binder_tpu/resolver/engine.py) and pushes the completed,
 * fully-encoded response variants down with `fastpath_put`.
 *
 * The cache/serve core itself is Python-free and lives in fpcore.h (also
 * driven by the sanitized fuzz target native/fuzz/fuzz_fastpath.cpp);
 * this file is the CPython glue: capsule lifecycle, argument validation,
 * recvmmsg/sendmmsg batching, stats marshaling.
 *
 * Semantics preserved relative to the Python hit path
 * (BinderServer._on_query):
 *  - the key covers exactly the decoded fields the response depends on:
 *    RD bit, EDNS presence, effective payload ceiling, qtype, qclass,
 *    lowercased qname (wire label format).  EDNS option bytes (cookies,
 *    padding) vary per packet and are deliberately NOT keyed;
 *  - store-generation check: every entry records the mirror-cache
 *    generation it was resolved under; drain() is handed the current
 *    generation and treats stale entries as misses (lazy invalidation);
 *  - time expiry (the reference's -a expiry flag, main.js:34-38);
 *  - round-robin: multi-answer entries carry the shuffle variants the
 *    Python cache collected and hits cycle through them;
 *  - 0x20 case echo: the response's question section is patched with the
 *    client's original bytes, so mixed-case (RFC draft-vixie-dnsext-dns0x20)
 *    queries verify.
 *
 * Only plain hostname-charset names ([a-zA-Z0-9_-] labels) take the fast
 * path; anything else — multi-question, non-zero opcode, compression in
 * the question, unknown additionals, trailing bytes — falls through to
 * Python, which is always correct.
 *
 * Queries answered here never reach the Python after-hook, so the cache
 * keeps its own per-qtype counters and latency/size histogram cells
 * (bucket bounds supplied by Python at construction, matching the
 * Prometheus collectors); the server folds them in at scrape time.
 * The fast path is only engaged when per-query logging and probes are
 * off — with those on, every query must surface to Python.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>

#include "../common/dnskey.h"
#include "fastpath.h"
#include "fpcore.h"

#define FP_BATCH FASTIO_BATCH

static const char *FP_CAPSULE_NAME = "binder_tpu._binderfastio.fastpath";

static void
fp_cache_free(fp_cache_t *c)
{
    fp_core_free(c);
    free(c);
}

static void
fp_capsule_destructor(PyObject *capsule)
{
    fp_cache_t *c = PyCapsule_GetPointer(capsule, FP_CAPSULE_NAME);
    if (c != NULL)
        fp_cache_free(c);
}

static fp_cache_t *
fp_from_capsule(PyObject *capsule)
{
    return PyCapsule_GetPointer(capsule, FP_CAPSULE_NAME);
}

static int
fp_load_buckets(PyObject *seq, double *out, int *n_out, const char *what)
{
    PyObject *fast = PySequence_Fast(seq, "buckets must be a sequence");
    if (fast == NULL)
        return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    if (n > FP_MAX_BUCKETS) {
        Py_DECREF(fast);
        PyErr_Format(PyExc_ValueError, "too many %s buckets (max %d)",
                     what, FP_MAX_BUCKETS);
        return -1;
    }
    double prev = -1.0;
    for (Py_ssize_t i = 0; i < n; i++) {
        double v = PyFloat_AsDouble(PySequence_Fast_GET_ITEM(fast, i));
        if (v == -1.0 && PyErr_Occurred()) {
            Py_DECREF(fast);
            return -1;
        }
        if (v <= prev) {
            Py_DECREF(fast);
            PyErr_Format(PyExc_ValueError,
                         "%s buckets must be strictly increasing", what);
            return -1;
        }
        out[i] = v;
        prev = v;
    }
    *n_out = (int)n;
    Py_DECREF(fast);
    return 0;
}

/* Append (payload, addr) to the miss list in recv_batch's item format.
 * Returns 0 on success; -1 with a Python exception set. */
static int
surface_miss(PyObject *misses, const uint8_t *pkt, size_t plen,
             const struct sockaddr_storage *addr)
{
    PyObject *payload = PyBytes_FromStringAndSize((const char *)pkt,
                                                  (Py_ssize_t)plen);
    PyObject *addr_t = payload ? fastio_addr_to_tuple(addr) : NULL;
    PyObject *item = (payload && addr_t)
        ? PyTuple_Pack(2, payload, addr_t) : NULL;
    Py_XDECREF(payload);
    Py_XDECREF(addr_t);
    if (item == NULL)
        return -1;
    int rc = PyList_Append(misses, item);
    Py_DECREF(item);
    return rc;
}

/* ---------------- module functions ---------------- */

PyObject *
fastpath_new(PyObject *self, PyObject *args)
{
    (void)self;
    long size;
    long expiry_ms;
    PyObject *lat_buckets, *size_buckets;

    if (!PyArg_ParseTuple(args, "llOO", &size, &expiry_ms,
                          &lat_buckets, &size_buckets))
        return NULL;
    if (size < 1) {
        PyErr_SetString(PyExc_ValueError, "size must be >= 1");
        return NULL;
    }
    fp_cache_t *c = calloc(1, sizeof(*c));
    if (c == NULL)
        return PyErr_NoMemory();
    if (fp_core_init(c, size, expiry_ms) < 0) {
        free(c);
        return PyErr_NoMemory();
    }
    if (fp_load_buckets(lat_buckets, c->lat_buckets,
                        &c->n_lat_buckets, "latency") < 0 ||
        fp_load_buckets(size_buckets, c->size_buckets,
                        &c->n_size_buckets, "size") < 0) {
        fp_cache_free(c);
        return NULL;
    }
    PyObject *capsule = PyCapsule_New(c, FP_CAPSULE_NAME,
                                      fp_capsule_destructor);
    if (capsule == NULL) {
        fp_cache_free(c);
        return NULL;
    }
    return capsule;
}

/* Borrow (ptr, len) arrays for a per-variant fragment sequence.  On
 * success *fast_out holds the sequence keeping the pointers alive and
 * frag_ptrs/frag_lens are filled for exactly `expect` items.  Returns
 * 1 usable, 0 skip-the-put (wrong count / oversize / empty), -1 with a
 * Python exception set. */
static int
fp_load_frags(PyObject *frags, Py_ssize_t expect, PyObject **fast_out,
              const uint8_t **frag_ptrs, uint16_t *frag_lens)
{
    *fast_out = NULL;
    if (frags == NULL || frags == Py_None)
        return 1;                   /* no fragments: log-off posture */
    PyObject *fast = PySequence_Fast(frags, "frags must be a sequence");
    if (fast == NULL)
        return -1;
    if (PySequence_Fast_GET_SIZE(fast) != expect) {
        Py_DECREF(fast);
        return 0;                   /* per-variant mismatch: skip */
    }
    for (Py_ssize_t i = 0; i < expect; i++) {
        char *data;
        Py_ssize_t dlen;
        if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fast, i),
                                    &data, &dlen) < 0) {
            Py_DECREF(fast);
            return -1;
        }
        if (dlen < 1 || dlen > FP_MAX_FRAG) {
            Py_DECREF(fast);
            return 0;               /* unloggable: stays in Python */
        }
        frag_ptrs[i] = (const uint8_t *)data;
        frag_lens[i] = (uint16_t)dlen;
    }
    *fast_out = fast;
    return 1;
}

PyObject *
fastpath_put(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule, *wires;
    PyObject *frags = NULL;
    Py_buffer keybuf, tagbuf;
    unsigned long long gen;
    int qtype;
    long expiry_ms = -1;   /* default: the cache-wide expiry */

    tagbuf.buf = NULL;
    tagbuf.len = 0;
    tagbuf.obj = NULL;
    if (!PyArg_ParseTuple(args, "Oy*iKO|ly*O", &capsule, &keybuf, &qtype,
                          &gen, &wires, &expiry_ms, &tagbuf, &frags))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&keybuf);
        if (tagbuf.obj != NULL)
            PyBuffer_Release(&tagbuf);
        return NULL;
    }
    PyObject *fast = PySequence_Fast(wires, "wires must be a sequence");
    if (fast == NULL) {
        PyBuffer_Release(&keybuf);
        if (tagbuf.obj != NULL)
            PyBuffer_Release(&tagbuf);
        return NULL;
    }
    Py_ssize_t nw = PySequence_Fast_GET_SIZE(fast);
    int rc = 0;
    if (nw >= 1 && nw <= FP_MAX_VARIANTS) {
        /* borrow the wire pointers (valid while `fast` is held) */
        const uint8_t *wire_ptrs[FP_MAX_VARIANTS];
        uint16_t wire_lens[FP_MAX_VARIANTS];
        const uint8_t *frag_ptrs[FP_MAX_VARIANTS];
        uint16_t frag_lens[FP_MAX_VARIANTS];
        PyObject *frag_fast = NULL;
        int sizes_ok = 1;
        for (Py_ssize_t i = 0; i < nw; i++) {
            char *data;
            Py_ssize_t dlen;
            if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fast, i),
                                        &data, &dlen) < 0) {
                Py_DECREF(fast);
                PyBuffer_Release(&keybuf);
                if (tagbuf.obj != NULL)
                    PyBuffer_Release(&tagbuf);
                return NULL;
            }
            if (dlen < 12 || dlen > FP_MAX_WIRE) {
                sizes_ok = 0;       /* oversize answers stay in Python */
                break;
            }
            wire_ptrs[i] = (const uint8_t *)data;
            wire_lens[i] = (uint16_t)dlen;
        }
        int frc = sizes_ok
            ? fp_load_frags(frags, nw, &frag_fast, frag_ptrs, frag_lens)
            : 1;
        if (frc < 0) {
            Py_DECREF(fast);
            PyBuffer_Release(&keybuf);
            if (tagbuf.obj != NULL)
                PyBuffer_Release(&tagbuf);
            return NULL;
        }
        if (sizes_ok && frc > 0) {
            double expiry_s = expiry_ms >= 0 ? (double)expiry_ms / 1000.0
                                             : c->expiry_s;
            rc = fp_put_raw(c, keybuf.buf, (size_t)keybuf.len,
                            (uint16_t)qtype, (uint64_t)gen, wire_ptrs,
                            wire_lens, (int)nw, fp_now(), expiry_s,
                            (const uint8_t *)tagbuf.buf,
                            (size_t)tagbuf.len,
                            frag_fast != NULL ? frag_ptrs : NULL,
                            frag_fast != NULL ? frag_lens : NULL);
        }
        Py_XDECREF(frag_fast);
    }
    Py_DECREF(fast);
    PyBuffer_Release(&keybuf);
    if (tagbuf.obj != NULL)
        PyBuffer_Release(&tagbuf);
    if (rc < 0)
        return PyErr_NoMemory();
    if (rc == 0)
        Py_RETURN_FALSE;
    Py_RETURN_TRUE;
}

PyObject *
fastpath_zone_put(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule, *bodies;
    PyObject *frags = NULL;
    Py_buffer zkeybuf, tagbuf;
    unsigned long long gen;
    int ancount;
    int arcount = 0;

    if (!PyArg_ParseTuple(args, "Oy*KiOy*|iO", &capsule, &zkeybuf, &gen,
                          &ancount, &bodies, &tagbuf, &arcount, &frags))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    PyObject *fast = c != NULL
        ? PySequence_Fast(bodies, "bodies must be a sequence") : NULL;
    if (fast == NULL) {
        PyBuffer_Release(&zkeybuf);
        PyBuffer_Release(&tagbuf);
        return NULL;
    }
    Py_ssize_t nv = PySequence_Fast_GET_SIZE(fast);
    int rc = 0;
    if (ancount > 0 && ancount <= 0xFFFF
            && arcount >= 0 && arcount <= 0xFFFF
            && nv >= 1 && nv <= FP_MAX_VARIANTS) {
        const uint8_t *body_ptrs[FP_MAX_VARIANTS];
        uint16_t body_lens[FP_MAX_VARIANTS];
        const uint8_t *frag_ptrs[FP_MAX_VARIANTS];
        uint16_t frag_lens[FP_MAX_VARIANTS];
        PyObject *frag_fast = NULL;
        int sizes_ok = 1;
        for (Py_ssize_t i = 0; i < nv; i++) {
            char *data;
            Py_ssize_t dlen;
            if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fast, i),
                                        &data, &dlen) < 0) {
                Py_DECREF(fast);
                PyBuffer_Release(&zkeybuf);
                PyBuffer_Release(&tagbuf);
                return NULL;
            }
            if (dlen < 1 || dlen > FP_MAX_WIRE) {
                sizes_ok = 0;
                break;
            }
            body_ptrs[i] = (const uint8_t *)data;
            body_lens[i] = (uint16_t)dlen;
        }
        int frc = sizes_ok
            ? fp_load_frags(frags, nv, &frag_fast, frag_ptrs, frag_lens)
            : 1;
        if (frc < 0) {
            Py_DECREF(fast);
            PyBuffer_Release(&zkeybuf);
            PyBuffer_Release(&tagbuf);
            return NULL;
        }
        if (sizes_ok && frc > 0)
            rc = fp_zone_put(c, zkeybuf.buf, (size_t)zkeybuf.len,
                             (uint64_t)gen, (uint16_t)ancount,
                             (uint16_t)arcount, body_ptrs,
                             body_lens, (int)nv,
                             (const uint8_t *)tagbuf.buf,
                             (size_t)tagbuf.len,
                             frag_fast != NULL ? frag_ptrs : NULL,
                             frag_fast != NULL ? frag_lens : NULL);
        Py_XDECREF(frag_fast);
    }
    Py_DECREF(fast);
    PyBuffer_Release(&zkeybuf);
    PyBuffer_Release(&tagbuf);
    if (rc < 0)
        return PyErr_NoMemory();
    if (rc == 0)
        Py_RETURN_FALSE;
    Py_RETURN_TRUE;
}

PyObject *
fastpath_serve_wire(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    Py_buffer pkt;
    unsigned long long gen;
    const char *client = NULL;
    const char *proto = "tcp";
    unsigned port = 0;

    if (!PyArg_ParseTuple(args, "Oy*K|sIs", &capsule, &pkt, &gen,
                          &client, &port, &proto))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&pkt);
        return NULL;
    }
    static uint8_t out[FP_MAX_WIRE];
    uint16_t qtype = 0;
    double t0 = fp_now();
    /* logged posture: the caller must supply the client context or the
     * serve declines inside the core (parity: Python then logs) */
    fp_logsrc_t src = { client, port, proto };
    /* decline_tc: TC responses cached off the UDP path are correct for
     * UDP requesters but must never replay over TCP (Python answers
     * those in full — its cache keys carry transport semantics; this
     * entry point cannot know the transport, so the core declines every
     * truncated wire before any hit accounting) */
    size_t wlen = fp_serve_one_lx(c, pkt.buf, (size_t)pkt.len,
                                  (uint64_t)gen, t0, out, &qtype, 1,
                                  client != NULL ? &src : NULL);
    PyBuffer_Release(&pkt);
    if (wlen == 0)
        Py_RETURN_NONE;
    /* same per-qtype accounting as the drain path, so TCP/balancer
     * serves land in the identical Prometheus series at fold time */
    fp_qstat_t *qs = fp_qstat(c, qtype);
    double elapsed = fp_now() - t0;
    qs->count++;
    qs->lat_sum += elapsed;
    qs->lat_cells[fp_bucket_index(c->lat_buckets, c->n_lat_buckets,
                                  elapsed)]++;
    qs->size_sum += (double)wlen;
    qs->size_cells[fp_bucket_index(c->size_buckets, c->n_size_buckets,
                                   (double)wlen)]++;
    return PyBytes_FromStringAndSize((const char *)out,
                                     (Py_ssize_t)wlen);
}

PyObject *
fastpath_serve_frames(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    Py_buffer data;
    unsigned long long gen;
    const char *client = NULL;
    const char *proto = "tcp";
    unsigned port = 0;

    if (!PyArg_ParseTuple(args, "Oy*K|sIs", &capsule, &data, &gen,
                          &client, &port, &proto))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&data);
        return NULL;
    }
    fp_logsrc_t src = { client, port, proto };
    fp_logsrc_t *srcp = client != NULL ? &src : NULL;

    /* responses for every hit in the chunk, RFC 1035 framed, written
     * back with ONE writer call; misses surface as payload bytes for
     * the Python path.  Static arena is safe: the GIL is held for the
     * whole call (like serve_wire's). */
    static uint8_t out[262144];
    size_t out_used = 0;
    size_t consumed = 0;
    const uint8_t *p = (const uint8_t *)data.buf;
    size_t n = (size_t)data.len;
    PyObject *misses = PyList_New(0);
    if (misses == NULL) {
        PyBuffer_Release(&data);
        return NULL;
    }

    while (consumed + 2 <= n) {
        size_t flen = ((size_t)p[consumed] << 8) | p[consumed + 1];
        if (flen == 0)
            break;          /* protocol garbage: Python closes the conn */
        if (consumed + 2 + flen > n)
            break;          /* partial frame: caller keeps the tail */
        if (out_used + 2 + FP_MAX_WIRE > sizeof(out))
            break;          /* arena full: caller re-feeds the rest */
        const uint8_t *pkt = p + consumed + 2;
        uint16_t qtype = 0;
        double t0 = fp_now();
        /* decline_tc=1: cached TC wires must never replay over TCP */
        size_t wlen = fp_serve_one_lx(c, pkt, flen, (uint64_t)gen, t0,
                                      out + out_used + 2, &qtype, 1,
                                      srcp);
        if (wlen == 0) {
            PyObject *payload = PyBytes_FromStringAndSize(
                (const char *)pkt, (Py_ssize_t)flen);
            int rc = payload == NULL ? -1
                : PyList_Append(misses, payload);
            Py_XDECREF(payload);
            if (rc < 0) {
                Py_DECREF(misses);
                PyBuffer_Release(&data);
                return NULL;
            }
        } else {
            out[out_used] = (uint8_t)(wlen >> 8);
            out[out_used + 1] = (uint8_t)(wlen & 0xFF);
            out_used += 2 + wlen;
            /* same per-qtype accounting as serve_wire */
            fp_qstat_t *qs = fp_qstat(c, qtype);
            double elapsed = fp_now() - t0;
            qs->count++;
            qs->lat_sum += elapsed;
            qs->lat_cells[fp_bucket_index(c->lat_buckets,
                                          c->n_lat_buckets, elapsed)]++;
            qs->size_sum += (double)wlen;
            qs->size_cells[fp_bucket_index(c->size_buckets,
                                           c->n_size_buckets,
                                           (double)wlen)]++;
        }
        consumed += 2 + flen;
    }
    PyBuffer_Release(&data);
    PyObject *resp = PyBytes_FromStringAndSize((const char *)out,
                                               (Py_ssize_t)out_used);
    if (resp == NULL) {
        Py_DECREF(misses);
        return NULL;
    }
    return Py_BuildValue("(NnN)", resp, (Py_ssize_t)consumed, misses);
}

/* Balancer wire constants (docs/balancer-protocol.md); the Python
 * definitions in binder_tpu/dns/server.py are authoritative. */
#define BAL_HDR 21
#define BAL_VERSION 1
#define BAL_MAX_FRAME 65556
#define BAL_TRANSPORT_UDP 0

/* Flush a direct-return batch on the balancer-owned fd.  Same
 * per-destination tolerance as the drain flush.  Returns 0, or the
 * socket-fatal errno (positive) for the caller to surface. */
static int
bal_flush(int fd, struct mmsghdr *omsgs, int n_hits)
{
    int off = 0;
    while (off < n_hits) {
        int sent = sendmmsg(fd, omsgs + off, (unsigned)(n_hits - off),
                            MSG_DONTWAIT);
        if (sent >= 0) {
            fastio_io_note_send(sent);
            off += sent > 0 ? sent : 1;
            continue;
        }
        if (errno == EINTR)
            continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK)
            return 0;            /* buffer full: drop rest (UDP) */
        if (errno == EBADF || errno == ENOTSOCK || errno == EFAULT ||
            errno == ENOMEM)
            return errno;        /* fatal: caller drops direct mode */
        off += 1;                /* per-destination failure: skip one */
    }
    return 0;
}

PyObject *
fastpath_serve_balancer(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    Py_buffer data;
    unsigned long long gen;
    int fd;

    if (!PyArg_ParseTuple(args, "Oy*Ki", &capsule, &data, &gen, &fd))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&data);
        return NULL;
    }

    /* direct server return: every UDP-transport hit in the chunk is
     * answered straight onto the balancer's client-facing socket (the
     * passed fd) with the client sockaddr from the frame as msg_name —
     * the reply never re-enters the balancer process.  Everything else
     * (misses, control frames, TCP transport, unknown versions)
     * surfaces as raw frames for the Python lane. */
    static uint8_t outs[FP_BATCH][FP_MAX_WIRE];
    struct mmsghdr omsgs[FP_BATCH];
    struct iovec oiovs[FP_BATCH];
    struct sockaddr_storage oaddrs[FP_BATCH];
    int n_hits = 0;
    long served = 0;
    int fatal_errno = 0;
    memset(omsgs, 0, sizeof(omsgs));

    PyObject *misses = PyList_New(0);
    if (misses == NULL) {
        PyBuffer_Release(&data);
        return NULL;
    }

    const uint8_t *p = (const uint8_t *)data.buf;
    size_t n = (size_t)data.len;
    size_t consumed = 0;

    while (fatal_errno == 0 && consumed + 4 <= n) {
        size_t flen = ((size_t)p[consumed] << 24)
                    | ((size_t)p[consumed + 1] << 16)
                    | ((size_t)p[consumed + 2] << 8)
                    | (size_t)p[consumed + 3];
        if (flen < BAL_HDR || flen > BAL_MAX_FRAME)
            break;          /* protocol garbage: Python closes the link */
        if (consumed + 4 + flen > n)
            break;          /* partial frame: caller keeps the tail */
        const uint8_t *fr = p + consumed + 4;
        uint8_t version = fr[0], family = fr[1], transport = fr[2];
        const uint8_t *addr = fr + 3;
        uint16_t port = (uint16_t)(((uint16_t)fr[19] << 8) | fr[20]);
        const uint8_t *pkt = fr + BAL_HDR;
        size_t plen = flen - BAL_HDR;

        size_t wlen = 0;
        uint16_t qtype = 0;
        double t0 = fp_now();
        if (version == BAL_VERSION && (family == 4 || family == 6)
                && transport == BAL_TRANSPORT_UDP && plen >= 12) {
            /* logged posture: stringify the frame's client so the core
             * can emit its line (only when the ring is armed) */
            char client[INET6_ADDRSTRLEN];
            fp_logsrc_t src = { NULL, port, "udp" };
            if (c->lr.enabled
                    && inet_ntop(family == 4 ? AF_INET : AF_INET6, addr,
                                 client, sizeof(client)) != NULL)
                src.client = client;
            /* decline_tc=0: the transport is known UDP, so truncated
             * wires replay exactly as on the direct UDP drain */
            wlen = fp_serve_one_lx(c, pkt, plen, (uint64_t)gen, t0,
                                   outs[n_hits], &qtype, 0,
                                   src.client != NULL ? &src : NULL);
        }
        if (wlen == 0) {
            PyObject *raw = PyBytes_FromStringAndSize(
                (const char *)fr, (Py_ssize_t)flen);
            int rc = raw == NULL ? -1 : PyList_Append(misses, raw);
            Py_XDECREF(raw);
            if (rc < 0) {
                Py_DECREF(misses);
                PyBuffer_Release(&data);
                return NULL;
            }
        } else {
            struct sockaddr_storage *ss = &oaddrs[n_hits];
            socklen_t alen;
            memset(ss, 0, sizeof(*ss));
            if (family == 4) {
                struct sockaddr_in *sa = (struct sockaddr_in *)ss;
                sa->sin_family = AF_INET;
                memcpy(&sa->sin_addr, addr, 4);
                sa->sin_port = htons(port);
                alen = sizeof(*sa);
            } else {
                struct sockaddr_in6 *sa6 = (struct sockaddr_in6 *)ss;
                sa6->sin6_family = AF_INET6;
                memcpy(&sa6->sin6_addr, addr, 16);
                sa6->sin6_port = htons(port);
                alen = sizeof(*sa6);
            }
            oiovs[n_hits].iov_base = outs[n_hits];
            oiovs[n_hits].iov_len = wlen;
            omsgs[n_hits].msg_hdr.msg_iov = &oiovs[n_hits];
            omsgs[n_hits].msg_hdr.msg_iovlen = 1;
            omsgs[n_hits].msg_hdr.msg_name = ss;
            omsgs[n_hits].msg_hdr.msg_namelen = alen;
            n_hits++;
            served++;
            /* same per-qtype accounting as serve_wire */
            fp_qstat_t *qs = fp_qstat(c, qtype);
            double elapsed = fp_now() - t0;
            qs->count++;
            qs->lat_sum += elapsed;
            qs->lat_cells[fp_bucket_index(c->lat_buckets,
                                          c->n_lat_buckets, elapsed)]++;
            qs->size_sum += (double)wlen;
            qs->size_cells[fp_bucket_index(c->size_buckets,
                                           c->n_size_buckets,
                                           (double)wlen)]++;
            if (n_hits == FP_BATCH) {
                fatal_errno = bal_flush(fd, omsgs, n_hits);
                n_hits = 0;
                memset(omsgs, 0, sizeof(omsgs));
            }
        }
        consumed += 4 + flen;
    }
    if (fatal_errno == 0 && n_hits > 0)
        fatal_errno = bal_flush(fd, omsgs, n_hits);
    PyBuffer_Release(&data);
    if (fatal_errno != 0) {
        Py_DECREF(misses);
        errno = fatal_errno;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    return Py_BuildValue("(nlN)", (Py_ssize_t)consumed, served, misses);
}

PyObject *
fastpath_zone_reserve(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    unsigned long entries;

    if (!PyArg_ParseTuple(args, "Ok", &capsule, &entries))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL)
        return NULL;
    if (entries > FP_ZONE_MAX_SLOTS)
        entries = FP_ZONE_MAX_SLOTS;
    if (fp_zone_reserve(c, &c->zmain, (uint32_t)entries) != 0)
        return PyErr_NoMemory();
    Py_RETURN_NONE;
}

PyObject *
fastpath_invalidate(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    Py_buffer tagbuf;

    if (!PyArg_ParseTuple(args, "Oy*", &capsule, &tagbuf))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&tagbuf);
        return NULL;
    }
    uint32_t n = fp_invalidate_tag(c, tagbuf.buf, (size_t)tagbuf.len);
    PyBuffer_Release(&tagbuf);
    return PyLong_FromUnsignedLong((unsigned long)n);
}

PyObject *
fastpath_drain(PyObject *self, PyObject *args)
{
    (void)self;
    int fd, max_n = FP_BATCH;
    PyObject *capsule;
    unsigned long long gen;

    if (!PyArg_ParseTuple(args, "OiK|i", &capsule, &fd, &gen, &max_n))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL)
        return NULL;
    if (max_n < 1) max_n = 1;
    if (max_n > FP_BATCH) max_n = FP_BATCH;

    /* receive arena shared with recv_batch (GIL-serialized); the
     * response arena is fast-path-only */
    unsigned char (*bufs)[FASTIO_DGRAM_MAX] = fastio_shared_bufs;
    static unsigned char outs[FP_BATCH][FP_MAX_WIRE];
    struct mmsghdr msgs[FP_BATCH];
    struct iovec iovs[FP_BATCH];
    struct sockaddr_storage addrs[FP_BATCH];

    memset(msgs, 0, sizeof(struct mmsghdr) * (size_t)max_n);
    for (int i = 0; i < max_n; i++) {
        iovs[i].iov_base = bufs[i];
        iovs[i].iov_len = FASTIO_DGRAM_MAX;
        msgs[i].msg_hdr.msg_iov = &iovs[i];
        msgs[i].msg_hdr.msg_iovlen = 1;
        msgs[i].msg_hdr.msg_name = &addrs[i];
        msgs[i].msg_hdr.msg_namelen = sizeof(addrs[i]);
    }

    double t0 = fp_now();
    int n = recvmmsg(fd, msgs, (unsigned)max_n, MSG_DONTWAIT, NULL);
    if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
            PyObject *empty = PyList_New(0);
            if (empty == NULL)
                return NULL;
            PyObject *r = Py_BuildValue("(Ni)", empty, 0);
            return r;
        }
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    fastio_io_note_recv(n);

    PyObject *misses = PyList_New(0);
    if (misses == NULL)
        return NULL;

    struct mmsghdr omsgs[FP_BATCH];
    struct iovec oiovs[FP_BATCH];
    int n_hits = 0;
    int batch_qtype_counts[FP_MAX_QTYPES];
    memset(batch_qtype_counts, 0, sizeof(batch_qtype_counts));
    memset(omsgs, 0, sizeof(omsgs[0]) * (size_t)(n > 0 ? n : 1));

    for (int i = 0; i < n; i++) {
        const uint8_t *pkt = bufs[i];
        size_t plen = msgs[i].msg_len;
        uint16_t entry_qtype = 0;
        uint8_t *out = outs[n_hits];

        /* logged posture: stringify this packet's source so the core
         * can emit its log line (only when the ring is armed) */
        char client[INET6_ADDRSTRLEN];
        fp_logsrc_t src = { NULL, 0, "udp" };
        if (c->lr.enabled) {
            const struct sockaddr_storage *ss = &addrs[i];
            if (ss->ss_family == AF_INET) {
                const struct sockaddr_in *sa =
                    (const struct sockaddr_in *)ss;
                if (inet_ntop(AF_INET, &sa->sin_addr, client,
                              sizeof(client)) != NULL) {
                    src.client = client;
                    src.port = ntohs(sa->sin_port);
                }
            } else if (ss->ss_family == AF_INET6) {
                const struct sockaddr_in6 *sa6 =
                    (const struct sockaddr_in6 *)ss;
                if (inet_ntop(AF_INET6, &sa6->sin6_addr, client,
                              sizeof(client)) != NULL) {
                    src.client = client;
                    src.port = ntohs(sa6->sin6_port);
                }
            }
        }
        size_t wlen = fp_serve_one_lx(c, pkt, plen, (uint64_t)gen, t0,
                                      out, &entry_qtype, 0,
                                      src.client != NULL ? &src : NULL);
        if (wlen == 0) {
            /* miss: surface to Python exactly like recv_batch */
            if (surface_miss(misses, pkt, plen, &addrs[i]) < 0) {
                Py_DECREF(misses);
                return NULL;
            }
            continue;
        }

        oiovs[n_hits].iov_base = out;
        oiovs[n_hits].iov_len = wlen;
        omsgs[n_hits].msg_hdr.msg_iov = &oiovs[n_hits];
        omsgs[n_hits].msg_hdr.msg_iovlen = 1;
        omsgs[n_hits].msg_hdr.msg_name = &addrs[i];
        omsgs[n_hits].msg_hdr.msg_namelen = msgs[i].msg_hdr.msg_namelen;
        n_hits++;

        fp_qstat_t *qs = fp_qstat(c, entry_qtype);
        qs->size_sum += (double)wlen;
        qs->size_cells[fp_bucket_index(c->size_buckets,
                                       c->n_size_buckets,
                                       (double)wlen)]++;
        batch_qtype_counts[(int)(qs - c->qstats)]++;
    }

    /* flush hits; per-destination errors skip one datagram and continue
     * (same policy as send_batch — one unreachable client must not drop
     * other clients' responses) */
    int off = 0;
    while (off < n_hits) {
        int sent = sendmmsg(fd, omsgs + off, (unsigned)(n_hits - off),
                            MSG_DONTWAIT);
        if (sent >= 0) {
            fastio_io_note_send(sent);
            off += sent > 0 ? sent : 1;
            continue;
        }
        if (errno == EINTR)
            continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK)
            break;                      /* buffer full: drop rest (UDP) */
        if (errno == EBADF || errno == ENOTSOCK || errno == EFAULT ||
            errno == ENOMEM) {
            Py_DECREF(misses);
            return PyErr_SetFromErrno(PyExc_OSError);
        }
        off += 1;                       /* per-destination failure */
    }

    /* latency: the whole batch window, attributed to each hit — an
     * upper bound (a hit waited at most recv..send of its batch) */
    if (n_hits > 0) {
        double elapsed = fp_now() - t0;
        int li = fp_bucket_index(c->lat_buckets, c->n_lat_buckets,
                                 elapsed);
        for (int s = 0; s < FP_MAX_QTYPES; s++) {
            int cnt = batch_qtype_counts[s];
            if (cnt > 0) {
                c->qstats[s].count += (uint64_t)cnt;
                c->qstats[s].lat_sum += elapsed * (double)cnt;
                c->qstats[s].lat_cells[li] += (uint64_t)cnt;
            }
        }
    }

    return Py_BuildValue("(Ni)", misses, n_hits);
}

PyObject *
fastpath_invalidate_many(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule, *tags;

    if (!PyArg_ParseTuple(args, "OO", &capsule, &tags))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    PyObject *fast = c != NULL
        ? PySequence_Fast(tags, "tags must be a sequence") : NULL;
    if (fast == NULL)
        return NULL;
    Py_ssize_t total = PySequence_Fast_GET_SIZE(fast);
    if (total > INT_MAX)
        total = INT_MAX;
    /* borrow all tag pointers once; fp_invalidate_tags chunks oversize
     * batches internally (stack arrays cover the common event sizes) */
    const uint8_t *stack_ptrs[FP_INVAL_BATCH];
    size_t stack_lens[FP_INVAL_BATCH];
    const uint8_t **tag_ptrs = stack_ptrs;
    size_t *tag_lens = stack_lens;
    if (total > FP_INVAL_BATCH) {
        tag_ptrs = (const uint8_t **)malloc(
            (size_t)total * sizeof(*tag_ptrs));
        tag_lens = (size_t *)malloc((size_t)total * sizeof(*tag_lens));
        if (tag_ptrs == NULL || tag_lens == NULL) {
            free((void *)tag_ptrs == (void *)stack_ptrs ? NULL
                 : (void *)tag_ptrs);
            free(tag_lens == stack_lens ? NULL : (void *)tag_lens);
            Py_DECREF(fast);
            return PyErr_NoMemory();
        }
    }
    for (Py_ssize_t i = 0; i < total; i++) {
        char *data;
        Py_ssize_t dlen;
        if (PyBytes_AsStringAndSize(PySequence_Fast_GET_ITEM(fast, i),
                                    &data, &dlen) < 0) {
            if (tag_ptrs != stack_ptrs) {
                free((void *)tag_ptrs);
                free(tag_lens);
            }
            Py_DECREF(fast);
            return NULL;
        }
        tag_ptrs[i] = (const uint8_t *)data;
        tag_lens[i] = (size_t)dlen;
    }
    unsigned long dropped = fp_invalidate_tags(c, tag_ptrs, tag_lens,
                                               (int)total);
    if (tag_ptrs != stack_ptrs) {
        free((void *)tag_ptrs);
        free(tag_lens);
    }
    Py_DECREF(fast);
    return PyLong_FromUnsignedLong(dropped);
}

PyObject *
fastpath_log_enable(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;
    Py_buffer prefix;
    unsigned long cap = 1u << 20;

    if (!PyArg_ParseTuple(args, "Oy*|k", &capsule, &prefix, &cap))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL) {
        PyBuffer_Release(&prefix);
        return NULL;
    }
    int rc = fp_log_enable(c, (const uint8_t *)prefix.buf,
                           (size_t)prefix.len, (size_t)cap);
    PyBuffer_Release(&prefix);
    if (rc < 0) {
        PyErr_SetString(PyExc_ValueError,
                        "log ring enable failed (prefix/capacity)");
        return NULL;
    }
    Py_RETURN_NONE;
}

PyObject *
fastpath_log_drain(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;

    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL)
        return NULL;
    if (!c->lr.enabled || c->lr.len == 0)
        return PyBytes_FromStringAndSize(NULL, 0);
    PyObject *out = PyBytes_FromStringAndSize((const char *)c->lr.buf,
                                              (Py_ssize_t)c->lr.len);
    if (out == NULL)
        return NULL;
    c->lr.len = 0;
    return out;
}

PyObject *
fastpath_stats(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;

    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL)
        return NULL;

    PyObject *per = PyDict_New();
    if (per == NULL)
        return NULL;
    for (int i = 0; i < c->n_qstats; i++) {
        fp_qstat_t *s = &c->qstats[i];
        PyObject *lat = PyTuple_New(c->n_lat_buckets + 1);
        PyObject *sz = PyTuple_New(c->n_size_buckets + 1);
        if (lat == NULL || sz == NULL) {
            Py_XDECREF(lat);
            Py_XDECREF(sz);
            Py_DECREF(per);
            return NULL;
        }
        for (int b = 0; b <= c->n_lat_buckets; b++)
            PyTuple_SET_ITEM(lat, b,
                             PyLong_FromUnsignedLongLong(s->lat_cells[b]));
        for (int b = 0; b <= c->n_size_buckets; b++)
            PyTuple_SET_ITEM(sz, b,
                             PyLong_FromUnsignedLongLong(s->size_cells[b]));
        PyObject *d = Py_BuildValue(
            "{s:K,s:d,s:N,s:d,s:N}",
            "count", (unsigned long long)s->count,
            "lat_sum", s->lat_sum, "lat_cells", lat,
            "size_sum", s->size_sum, "size_cells", sz);
        if (d == NULL) {
            Py_DECREF(per);
            return NULL;
        }
        PyObject *k = PyLong_FromLong((long)s->qtype);
        int rc = k == NULL ? -1 : PyDict_SetItem(per, k, d);
        Py_XDECREF(k);
        Py_DECREF(d);
        if (rc < 0) {
            Py_DECREF(per);
            return NULL;
        }
    }
    return Py_BuildValue(
        "{s:K,s:K,s:I,s:K,s:K,s:K,s:I,s:K,s:K,s:K,s:K,s:N}",
        "hits", (unsigned long long)c->hits,
        "lookups", (unsigned long long)c->lookups,
        "entries", (unsigned)c->n_entries,
        "bytes", (unsigned long long)c->total_bytes,
        "invalidations", (unsigned long long)c->invalidations,
        "zone_hits", (unsigned long long)c->zone_hits,
        "zone_entries", (unsigned)(c->zmain.n + c->zalien.n),
        "zone_bytes", (unsigned long long)c->ztotal_bytes,
        "log_lines", (unsigned long long)c->lr.lines,
        "log_declines", (unsigned long long)c->lr.declines,
        "log_pending", (unsigned long long)c->lr.len,
        "per_qtype", per);
}

PyObject *
fastpath_clear(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *capsule;

    if (!PyArg_ParseTuple(args, "O", &capsule))
        return NULL;
    fp_cache_t *c = fp_from_capsule(capsule);
    if (c == NULL)
        return NULL;
    fp_core_clear(c);
    Py_RETURN_NONE;
}
