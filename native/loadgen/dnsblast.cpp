/*
 * dnsblast — windowed UDP DNS load generator (dnsperf-equivalent).
 *
 * The reference repo ships no load tool; its tests shell out to dig(1)
 * (reference test/dig.js:109-134), which cannot measure server capacity.
 * bench_impl.py previously drove load from Python, but on a single-core
 * machine the Python client's per-packet interpreter cost competes with
 * the server for the same CPU and caps the measurement.  This native
 * client keeps the measurement overhead at ~1-2us/query so the reported
 * number is server capacity, not client capacity.
 *
 * Protocol behavior mirrors bench_impl.BenchClient exactly:
 *   - window of W queries in flight over one connected UDP socket;
 *   - query wires are templates cycled round-robin with the 2-byte id
 *     rewritten per send (ids unique across the whole run, N <= 65536);
 *   - responses matched by id; rcode != NOERROR counts as an error;
 *   - queries unanswered for RETRY_AFTER are retransmitted (loopback UDP
 *     drops under bursts); retransmitted ids are excluded from latency.
 *
 * Usage:
 *   dnsblast -p PORT [-H HOST] [-n QUERIES] [-w WINDOW] -t FILE
 * where FILE contains length-prefixed (u16 BE) DNS query wires to cycle.
 * Output: one JSON line {qps, elapsed_s, p50_us, p99_us, errors, retries}.
 */

#include <arpa/inet.h>
#include <cerrno>
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <netinet/in.h>
#include <poll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <string>
#include <vector>

namespace {

constexpr double kRetryAfter = 1.0;      /* seconds until retransmit */
constexpr double kRunTimeout = 300.0;    /* overall safety timeout */

double now_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

struct Outstanding {
    double sent_at = 0.0;
    bool in_flight = false;
    bool retried = false;
};

void die(const char *msg) {
    perror(msg);
    exit(1);
}

std::vector<std::string> load_templates(const char *path) {
    FILE *f = fopen(path, "rb");
    if (f == nullptr) die("open template file");
    std::vector<std::string> out;
    for (;;) {
        unsigned char hdr[2];
        size_t got = fread(hdr, 1, 2, f);
        if (got == 0) break;
        if (got != 2) { fprintf(stderr, "truncated template file\n"); exit(1); }
        size_t len = ((size_t)hdr[0] << 8) | hdr[1];
        std::string wire(len, '\0');
        if (fread(&wire[0], 1, len, f) != len) {
            fprintf(stderr, "truncated template file\n");
            exit(1);
        }
        if (len < 12) { fprintf(stderr, "template shorter than DNS header\n"); exit(1); }
        out.push_back(std::move(wire));
    }
    fclose(f);
    if (out.empty()) { fprintf(stderr, "no templates\n"); exit(1); }
    return out;
}

}  // namespace

int main(int argc, char **argv) {
    const char *host = "127.0.0.1";
    const char *tmpl_path = nullptr;
    int port = 0;
    long n_queries = 50000;
    int window = 64;

    int c;
    while ((c = getopt(argc, argv, "H:p:n:w:t:")) != -1) {
        switch (c) {
        case 'H': host = optarg; break;
        case 'p': port = atoi(optarg); break;
        case 'n': n_queries = atol(optarg); break;
        case 'w': window = atoi(optarg); break;
        case 't': tmpl_path = optarg; break;
        default:
            fprintf(stderr,
                    "usage: dnsblast -p port [-H host] [-n queries] "
                    "[-w window] -t templates\n");
            return 2;
        }
    }
    if (port <= 0 || tmpl_path == nullptr) {
        fprintf(stderr, "dnsblast: -p and -t are required\n");
        return 2;
    }
    if (n_queries < 1 || n_queries > 65536) {
        /* ids must stay unique across the run for unambiguous matching */
        fprintf(stderr, "dnsblast: -n must be in [1, 65536]\n");
        return 2;
    }
    if (window < 1) window = 1;
    if ((long)window > n_queries) window = (int)n_queries;

    std::vector<std::string> templates = load_templates(tmpl_path);

    int fd = socket(AF_INET, SOCK_DGRAM, 0);
    if (fd < 0) die("socket");
    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) {
        fprintf(stderr, "dnsblast: bad host %s\n", host);
        return 2;
    }
    if (connect(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0) die("connect");
    int rcvbuf = 1 << 20;
    (void)setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf, sizeof(rcvbuf));

    std::vector<Outstanding> state(65536);
    std::vector<double> latencies;
    latencies.reserve((size_t)n_queries);
    long next_idx = 0, received = 0, errors = 0, retries = 0;
    std::string sendbuf;

    auto send_query = [&](long idx, bool is_retry) {
        const std::string &tmpl = templates[(size_t)idx % templates.size()];
        sendbuf.assign(tmpl);
        sendbuf[0] = (char)((idx >> 8) & 0xff);
        sendbuf[1] = (char)(idx & 0xff);
        Outstanding &o = state[(size_t)idx];
        o.sent_at = now_s();
        o.in_flight = true;
        if (is_retry) o.retried = true;
        /* best-effort like the Python client; drops are re-sent by the
         * retransmit sweep */
        (void)send(fd, sendbuf.data(), sendbuf.size(), 0);
    };

    double t0 = now_s();
    for (int i = 0; i < window; i++) send_query(next_idx++, false);

    unsigned char rbuf[65535];
    double last_sweep = t0;
    while (received < n_queries) {
        struct pollfd pfd = {fd, POLLIN, 0};
        int rv = poll(&pfd, 1, 250);
        double now = now_s();
        if (now - t0 > kRunTimeout) {
            fprintf(stderr, "dnsblast: run timed out (%ld/%ld answered)\n",
                    received, n_queries);
            return 1;
        }
        if (rv > 0) {
            for (;;) {
                ssize_t got = recv(fd, rbuf, sizeof(rbuf), MSG_DONTWAIT);
                if (got < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK) break;
                    if (errno == EINTR) continue;
                    die("recv");
                }
                if (got < 4) continue;
                unsigned qid = ((unsigned)rbuf[0] << 8) | rbuf[1];
                Outstanding &o = state[qid];
                if (!o.in_flight) continue;  /* dup response to a retransmit */
                now = now_s();
                o.in_flight = false;
                if (!o.retried) latencies.push_back(now - o.sent_at);
                if (rbuf[3] & 0x0f) errors++;
                received++;
                if (next_idx < n_queries) send_query(next_idx++, false);
                if (received >= n_queries) break;
            }
        }
        if (now - last_sweep >= 0.25) {
            last_sweep = now;
            for (long i = 0; i < next_idx; i++) {
                Outstanding &o = state[(size_t)i];
                if (o.in_flight && now - o.sent_at > kRetryAfter) {
                    retries++;
                    send_query(i, true);
                }
            }
        }
    }
    double elapsed = now_s() - t0;
    close(fd);

    std::sort(latencies.begin(), latencies.end());
    double p50 = 0.0, p99 = 0.0;
    if (!latencies.empty()) {
        p50 = latencies[latencies.size() / 2] * 1e6;
        p99 = latencies[(size_t)((double)latencies.size() * 0.99)] * 1e6;
    }
    printf("{\"qps\": %.1f, \"elapsed_s\": %.4f, \"p50_us\": %.1f, "
           "\"p99_us\": %.1f, \"errors\": %ld, \"retries\": %ld}\n",
           (double)n_queries / elapsed, elapsed, p50, p99, errors, retries);
    return 0;
}
