/*
 * dnsblast — windowed DNS load generator (dnsperf-equivalent).
 *
 * The reference repo ships no load tool; its tests shell out to dig(1)
 * (reference test/dig.js:109-134), which cannot measure server capacity.
 * bench_impl.py previously drove load from Python, but on a single-core
 * machine the Python client's per-packet interpreter cost competes with
 * the server for the same CPU and caps the measurement.  This native
 * client keeps the measurement overhead at ~1-2us/query so the reported
 * number is server capacity, not client capacity.
 *
 * Protocol behavior mirrors bench_impl.BenchClient exactly:
 *   - window of W queries in flight over one connected UDP socket;
 *   - query wires are templates cycled round-robin with the 2-byte id
 *     rewritten per send (ids unique across the whole run, N <= 65536);
 *   - responses matched by id; rcode != NOERROR counts as an error;
 *   - queries unanswered for RETRY_AFTER are retransmitted (loopback UDP
 *     drops under bursts); retransmitted ids are excluded from latency.
 *
 * TCP modes (reference serves TCP on the same port,
 * lib/server.js:643-653):
 *   -m tcp    W queries in flight pipelined over -T persistent
 *             connections (RFC 1035 2-byte framing), responses matched
 *             by run-unique id;
 *   -m tcp1   one CONNECTION PER QUERY, W concurrent: latency covers
 *             connect + query + response + close — what a
 *             non-keep-alive TCP client experiences.
 *
 * Usage:
 *   dnsblast -p PORT [-H HOST] [-n QUERIES] [-w WINDOW] -t FILE
 *            [-m udp|tcp|tcp1] [-T CONNS] [-S SOURCES]
 * where FILE contains length-prefixed (u16 BE) DNS query wires to cycle.
 * Output: one JSON line {qps, elapsed_s, p50_us, p99_us, errors, retries}.
 *
 * -S SOURCES (UDP mode): spread the load over that many sockets, each
 * bound to its own 127.20.x.y loopback source address (Linux accepts
 * any 127/8 address unconfigured).  One socket = one mega-client, which
 * is exactly the flood shape per-client admission control sheds; the
 * recursion bench axes use -S so they measure forwarding under the
 * server's PRODUCTION admission limits instead of lifting them in
 * config.  If a source bind fails (non-Linux), the socket falls back to
 * the default source — the load still runs, just unspread.
 */

#include <arpa/inet.h>
#include <cerrno>
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <string>
#include <vector>

namespace {

constexpr double kRetryAfter = 1.0;      /* seconds until retransmit */
constexpr double kRunTimeout = 300.0;    /* overall safety timeout */

double now_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

struct Outstanding {
    double sent_at = 0.0;
    bool in_flight = false;
    bool retried = false;
};

void die(const char *msg) {
    perror(msg);
    exit(1);
}

std::vector<std::string> load_templates(const char *path) {
    FILE *f = fopen(path, "rb");
    if (f == nullptr) die("open template file");
    std::vector<std::string> out;
    for (;;) {
        unsigned char hdr[2];
        size_t got = fread(hdr, 1, 2, f);
        if (got == 0) break;
        if (got != 2) { fprintf(stderr, "truncated template file\n"); exit(1); }
        size_t len = ((size_t)hdr[0] << 8) | hdr[1];
        std::string wire(len, '\0');
        if (fread(&wire[0], 1, len, f) != len) {
            fprintf(stderr, "truncated template file\n");
            exit(1);
        }
        if (len < 12) { fprintf(stderr, "template shorter than DNS header\n"); exit(1); }
        out.push_back(std::move(wire));
    }
    fclose(f);
    if (out.empty()) { fprintf(stderr, "no templates\n"); exit(1); }
    return out;
}

void emit_result(long n_queries, double elapsed,
                 std::vector<double> &latencies, long errors,
                 long retries) {
    std::sort(latencies.begin(), latencies.end());
    double p50 = 0.0, p99 = 0.0;
    if (!latencies.empty()) {
        p50 = latencies[latencies.size() / 2] * 1e6;
        p99 = latencies[(size_t)((double)latencies.size() * 0.99)] * 1e6;
    }
    printf("{\"qps\": %.1f, \"elapsed_s\": %.4f, \"p50_us\": %.1f, "
           "\"p99_us\": %.1f, \"errors\": %ld, \"retries\": %ld}\n",
           (double)n_queries / elapsed, elapsed, p50, p99, errors,
           retries);
}

int make_tcp_conn(const struct sockaddr_in *sa) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) die("socket");
    int one = 1;
    (void)setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    if (fcntl(fd, F_SETFL, O_NONBLOCK) != 0) die("fcntl");
    int rv = connect(fd, (const struct sockaddr *)sa, sizeof(*sa));
    if (rv != 0 && errno != EINPROGRESS) die("connect");
    return fd;
}

/* W queries pipelined over `nconns` persistent TCP connections. */
int run_tcp(const struct sockaddr_in *sa,
            const std::vector<std::string> &templates, long n_queries,
            int window, int nconns) {
    struct Conn {
        int fd = -1;
        std::string out;    /* unwritten framed queries */
        size_t out_off = 0;
        std::string in;     /* partial response frames */
    };
    if (nconns > window) nconns = window;
    std::vector<Conn> conns((size_t)nconns);
    for (auto &cn : conns) cn.fd = make_tcp_conn(sa);

    std::vector<Outstanding> state(65536);
    std::vector<double> latencies;
    latencies.reserve((size_t)n_queries);
    long next_idx = 0, received = 0, errors = 0;

    auto enqueue = [&](long idx) {
        const std::string &tmpl = templates[(size_t)idx % templates.size()];
        Conn &cn = conns[(size_t)idx % conns.size()];
        char hdr[2] = {(char)((tmpl.size() >> 8) & 0xff),
                       (char)(tmpl.size() & 0xff)};
        size_t base = cn.out.size();
        cn.out.append(hdr, 2);
        cn.out.append(tmpl);
        cn.out[base + 2] = (char)((idx >> 8) & 0xff);
        cn.out[base + 3] = (char)(idx & 0xff);
        state[(size_t)idx].sent_at = now_s();
        state[(size_t)idx].in_flight = true;
    };

    double t0 = now_s();
    for (int i = 0; i < window && next_idx < n_queries; i++)
        enqueue(next_idx++);

    std::vector<struct pollfd> pfds((size_t)nconns);
    char rbuf[65536];
    while (received < n_queries) {
        for (size_t i = 0; i < conns.size(); i++) {
            pfds[i].fd = conns[i].fd;
            pfds[i].events = POLLIN;
            if (conns[i].out_off < conns[i].out.size())
                pfds[i].events |= POLLOUT;
            pfds[i].revents = 0;
        }
        int rv = poll(pfds.data(), (nfds_t)pfds.size(), 250);
        if (now_s() - t0 > kRunTimeout) {
            fprintf(stderr, "dnsblast: tcp run timed out (%ld/%ld)\n",
                    received, n_queries);
            return 1;
        }
        if (rv <= 0) continue;
        for (size_t i = 0; i < conns.size(); i++) {
            Conn &cn = conns[i];
            if (pfds[i].revents & (POLLERR | POLLHUP)) {
                fprintf(stderr, "dnsblast: tcp connection died\n");
                return 1;
            }
            if ((pfds[i].revents & POLLOUT)
                    && cn.out_off < cn.out.size()) {
                ssize_t put = send(cn.fd, cn.out.data() + cn.out_off,
                                   cn.out.size() - cn.out_off,
                                   MSG_NOSIGNAL);
                if (put > 0) {
                    cn.out_off += (size_t)put;
                    if (cn.out_off == cn.out.size()) {
                        cn.out.clear();
                        cn.out_off = 0;
                    }
                } else if (put < 0 && errno != EAGAIN
                           && errno != EWOULDBLOCK && errno != EINTR) {
                    die("tcp send");
                }
            }
            if (pfds[i].revents & POLLIN) {
                ssize_t got = recv(cn.fd, rbuf, sizeof(rbuf),
                                   MSG_DONTWAIT);
                if (got < 0) {
                    if (errno == EAGAIN || errno == EWOULDBLOCK
                            || errno == EINTR)
                        continue;
                    die("tcp recv");
                }
                if (got == 0) {
                    fprintf(stderr, "dnsblast: server closed tcp\n");
                    return 1;
                }
                cn.in.append(rbuf, (size_t)got);
                size_t off = 0;
                while (cn.in.size() - off >= 2) {
                    size_t flen =
                        ((size_t)(unsigned char)cn.in[off] << 8)
                        | (unsigned char)cn.in[off + 1];
                    if (cn.in.size() - off - 2 < flen) break;
                    const unsigned char *resp =
                        (const unsigned char *)cn.in.data() + off + 2;
                    if (flen >= 4) {
                        unsigned qid = ((unsigned)resp[0] << 8) | resp[1];
                        Outstanding &o = state[qid];
                        if (o.in_flight) {
                            o.in_flight = false;
                            latencies.push_back(now_s() - o.sent_at);
                            if (resp[3] & 0x0f) errors++;
                            received++;
                            if (next_idx < n_queries)
                                enqueue(next_idx++);
                        }
                    }
                    off += 2 + flen;
                }
                if (off > 0) cn.in.erase(0, off);
            }
        }
    }
    double elapsed = now_s() - t0;
    for (auto &cn : conns) close(cn.fd);
    emit_result(n_queries, elapsed, latencies, errors, 0);
    return 0;
}

/* One connection per query, `window` concurrent: measures the full
 * connect+query+response+close cycle. */
int run_tcp1(const struct sockaddr_in *sa,
             const std::vector<std::string> &templates, long n_queries,
             int window) {
    struct Slot {
        int fd = -1;
        long idx = -1;
        double started = 0.0;
        bool sent = false;
        size_t out_off = 0;
        std::string out;
        std::string in;
    };
    if (window > 128) window = 128;   /* fd + accept-queue sanity */
    std::vector<Slot> slots((size_t)window);
    std::vector<double> latencies;
    latencies.reserve((size_t)n_queries);
    long next_idx = 0, received = 0, errors = 0;

    auto open_slot = [&](Slot &s) {
        if (next_idx >= n_queries) {
            s.fd = -1;
            return;
        }
        long idx = next_idx++;
        const std::string &tmpl = templates[(size_t)idx % templates.size()];
        s.fd = make_tcp_conn(sa);
        s.idx = idx;
        s.started = now_s();
        s.sent = false;
        s.out_off = 0;
        s.out.clear();
        char hdr[2] = {(char)((tmpl.size() >> 8) & 0xff),
                       (char)(tmpl.size() & 0xff)};
        s.out.append(hdr, 2);
        s.out.append(tmpl);
        s.out[2] = (char)((idx >> 8) & 0xff);
        s.out[3] = (char)(idx & 0xff);
        s.in.clear();
    };

    double t0 = now_s();
    for (auto &s : slots) open_slot(s);

    std::vector<struct pollfd> pfds((size_t)window);
    char rbuf[65536];
    while (received < n_queries) {
        size_t nfds = 0;
        for (auto &s : slots) {
            if (s.fd < 0) continue;
            pfds[nfds].fd = s.fd;
            pfds[nfds].events = (short)(POLLIN
                | (s.out_off < s.out.size() ? POLLOUT : 0));
            pfds[nfds].revents = 0;
            nfds++;
        }
        if (nfds == 0) break;
        int rv = poll(pfds.data(), (nfds_t)nfds, 250);
        if (now_s() - t0 > kRunTimeout) {
            fprintf(stderr, "dnsblast: tcp1 run timed out (%ld/%ld)\n",
                    received, n_queries);
            return 1;
        }
        if (rv <= 0) continue;
        size_t pi = 0;
        for (auto &s : slots) {
            if (s.fd < 0) continue;
            struct pollfd &p = pfds[pi++];
            if (p.revents & (POLLERR | POLLHUP)) {
                fprintf(stderr, "dnsblast: tcp1 connection died\n");
                return 1;
            }
            if ((p.revents & POLLOUT) && s.out_off < s.out.size()) {
                ssize_t put = send(s.fd, s.out.data() + s.out_off,
                                   s.out.size() - s.out_off,
                                   MSG_NOSIGNAL);
                if (put > 0) s.out_off += (size_t)put;
                else if (put < 0 && errno != EAGAIN
                         && errno != EWOULDBLOCK && errno != EINTR)
                    die("tcp1 send");
            }
            if (p.revents & POLLIN) {
                ssize_t got = recv(s.fd, rbuf, sizeof(rbuf),
                                   MSG_DONTWAIT);
                if (got == 0) {
                    /* peer EOF before a full response (cap refusal,
                     * abort): count it and recycle the slot — spinning
                     * on a readable-EOF fd would burn the run timeout */
                    errors++;
                    close(s.fd);
                    received++;
                    open_slot(s);
                    continue;
                }
                if (got > 0) s.in.append(rbuf, (size_t)got);
                if (s.in.size() >= 2) {
                    size_t flen =
                        ((size_t)(unsigned char)s.in[0] << 8)
                        | (unsigned char)s.in[1];
                    if (s.in.size() >= 2 + flen) {
                        const unsigned char *resp =
                            (const unsigned char *)s.in.data() + 2;
                        if (flen >= 4 && (resp[3] & 0x0f)) errors++;
                        latencies.push_back(now_s() - s.started);
                        received++;
                        close(s.fd);
                        open_slot(s);
                    }
                }
            }
        }
    }
    double elapsed = now_s() - t0;
    for (auto &s : slots)
        if (s.fd >= 0) close(s.fd);
    emit_result(n_queries, elapsed, latencies, errors, 0);
    return 0;
}

}  // namespace

int main(int argc, char **argv) {
    const char *host = "127.0.0.1";
    const char *tmpl_path = nullptr;
    const char *mode = "udp";
    int port = 0;
    long n_queries = 50000;
    int window = 64;
    int nconns = 8;
    int nsources = 1;

    int c;
    while ((c = getopt(argc, argv, "H:p:n:w:t:m:T:S:")) != -1) {
        switch (c) {
        case 'H': host = optarg; break;
        case 'p': port = atoi(optarg); break;
        case 'n': n_queries = atol(optarg); break;
        case 'w': window = atoi(optarg); break;
        case 't': tmpl_path = optarg; break;
        case 'm': mode = optarg; break;
        case 'T': nconns = atoi(optarg); break;
        case 'S': nsources = atoi(optarg); break;
        default:
            fprintf(stderr,
                    "usage: dnsblast -p port [-H host] [-n queries] "
                    "[-w window] [-m udp|tcp|tcp1] [-T conns] "
                    "[-S sources] -t templates\n");
            return 2;
        }
    }
    if (port <= 0 || tmpl_path == nullptr) {
        fprintf(stderr, "dnsblast: -p and -t are required\n");
        return 2;
    }
    if (n_queries < 1 || n_queries > 65536) {
        /* ids must stay unique across the run for unambiguous matching;
         * all three modes index 65536-slot state tables by query idx */
        fprintf(stderr, "dnsblast: -n must be in [1, 65536]\n");
        return 2;
    }
    if (window < 1) window = 1;
    if ((long)window > n_queries) window = (int)n_queries;
    if (nconns < 1) nconns = 1;
    if (nsources < 1) nsources = 1;
    if (nsources > 4096) nsources = 4096;  /* 127.20.x.y address budget */

    std::vector<std::string> templates = load_templates(tmpl_path);

    struct sockaddr_in sa;
    memset(&sa, 0, sizeof(sa));
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &sa.sin_addr) != 1) {
        fprintf(stderr, "dnsblast: bad host %s\n", host);
        return 2;
    }
    if (strcmp(mode, "tcp") == 0)
        return run_tcp(&sa, templates, n_queries, window, nconns);
    if (strcmp(mode, "tcp1") == 0)
        return run_tcp1(&sa, templates, n_queries, window);
    if (strcmp(mode, "udp") != 0) {
        fprintf(stderr, "dnsblast: unknown mode %s\n", mode);
        return 2;
    }

    /* -S: one socket per simulated client source (127.20.x.y); query
     * idx is pinned to socket idx % nsources so retransmits keep their
     * original 4-tuple and per-client accounting stays coherent */
    std::vector<int> fds((size_t)nsources, -1);
    for (int j = 0; j < nsources; j++) {
        int fd = socket(AF_INET, SOCK_DGRAM, 0);
        if (fd < 0) die("socket");
        if (nsources > 1) {
            struct sockaddr_in src;
            memset(&src, 0, sizeof(src));
            src.sin_family = AF_INET;
            char addr[32];
            snprintf(addr, sizeof(addr), "127.20.%d.%d", j / 250,
                     (j % 250) + 1);
            if (inet_pton(AF_INET, addr, &src.sin_addr) == 1)
                (void)bind(fd, (struct sockaddr *)&src, sizeof(src));
            /* bind failure: fall through unbound (non-Linux) */
        }
        if (connect(fd, (struct sockaddr *)&sa, sizeof(sa)) != 0)
            die("connect");
        int rcvbuf = 1 << 20;
        (void)setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &rcvbuf,
                         sizeof(rcvbuf));
        fds[(size_t)j] = fd;
    }

    std::vector<Outstanding> state(65536);
    std::vector<double> latencies;
    latencies.reserve((size_t)n_queries);
    long next_idx = 0, received = 0, errors = 0, retries = 0;
    std::string sendbuf;

    auto send_query = [&](long idx, bool is_retry) {
        const std::string &tmpl = templates[(size_t)idx % templates.size()];
        sendbuf.assign(tmpl);
        sendbuf[0] = (char)((idx >> 8) & 0xff);
        sendbuf[1] = (char)(idx & 0xff);
        Outstanding &o = state[(size_t)idx];
        o.sent_at = now_s();
        o.in_flight = true;
        if (is_retry) o.retried = true;
        /* best-effort like the Python client; drops are re-sent by the
         * retransmit sweep */
        (void)send(fds[(size_t)(idx % nsources)], sendbuf.data(),
                   sendbuf.size(), 0);
    };

    double t0 = now_s();
    for (int i = 0; i < window; i++) send_query(next_idx++, false);

    unsigned char rbuf[65535];
    double last_sweep = t0;
    std::vector<struct pollfd> pfds((size_t)nsources);
    while (received < n_queries) {
        for (size_t j = 0; j < fds.size(); j++) {
            pfds[j].fd = fds[j];
            pfds[j].events = POLLIN;
            pfds[j].revents = 0;
        }
        int rv = poll(pfds.data(), (nfds_t)pfds.size(), 250);
        double now = now_s();
        if (now - t0 > kRunTimeout) {
            fprintf(stderr, "dnsblast: run timed out (%ld/%ld answered)\n",
                    received, n_queries);
            return 1;
        }
        if (rv > 0) {
            for (size_t j = 0; j < fds.size() && received < n_queries;
                 j++) {
                if (!(pfds[j].revents & POLLIN)) continue;
                for (;;) {
                    ssize_t got = recv(fds[j], rbuf, sizeof(rbuf),
                                       MSG_DONTWAIT);
                    if (got < 0) {
                        if (errno == EAGAIN || errno == EWOULDBLOCK)
                            break;
                        if (errno == EINTR) continue;
                        die("recv");
                    }
                    if (got < 4) continue;
                    unsigned qid = ((unsigned)rbuf[0] << 8) | rbuf[1];
                    Outstanding &o = state[qid];
                    if (!o.in_flight) continue;  /* dup of a retransmit */
                    now = now_s();
                    o.in_flight = false;
                    if (!o.retried) latencies.push_back(now - o.sent_at);
                    if (rbuf[3] & 0x0f) errors++;
                    received++;
                    if (next_idx < n_queries) send_query(next_idx++, false);
                    if (received >= n_queries) break;
                }
            }
        }
        if (now - last_sweep >= 0.25) {
            last_sweep = now;
            for (long i = 0; i < next_idx; i++) {
                Outstanding &o = state[(size_t)i];
                if (o.in_flight && now - o.sent_at > kRetryAfter) {
                    retries++;
                    send_query(i, true);
                }
            }
        }
    }
    double elapsed = now_s() - t0;
    for (int fd : fds) close(fd);
    emit_result(n_queries, elapsed, latencies, errors, retries);
    return 0;
}
