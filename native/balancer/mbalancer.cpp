/*
 * mbalancer — DNS load balancer fronting N binder backend processes.
 *
 * C++ rebuild of the reference's mname-balancer (SURVEY §2.2 L1; the
 * reference submodule is not vendored, so the wire protocol is our own
 * spec, docs/balancer-protocol.md).  Behavior match:
 *
 *  - owns the public UDP + TCP DNS port and fans queries out to backend
 *    processes over per-backend UNIX stream sockets found in a socket
 *    directory (reference: /var/run/binder/sockets, boot/setup.sh);
 *  - frames carry the ORIGINAL client address + transport so backends
 *    log/answer as if they received the packet directly;
 *  - remote-IP -> backend affinity (reference g_remotes AVL,
 *    bin/balstat:19-31), round-robin assignment of new remotes across
 *    healthy backends (reference g_backends);
 *  - backends leave by unlinking their socket (reference main.js:181-193):
 *    periodic directory rescans pick up joins/leaves; send errors mark a
 *    backend unhealthy immediately;
 *  - introspection: JSON state dump served on <sockdir>/.balancer.stats
 *    (replaces the reference's mdb-based bin/balstat).
 *
 * Single-threaded epoll event loop; no allocations on the per-packet path
 * beyond buffer reuse.  Usage:
 *     mbalancer -d <sockdir> [-p port] [-b bindaddr] [-s scan_ms]
 */
#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <getopt.h>
#include <netinet/in.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/timerfd.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/un.h>
#include <unistd.h>

#include <cstdarg>
#include <deque>
#include <map>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "../common/dnskey.h"

namespace {

constexpr uint8_t kProtoVersion = 1;
constexpr size_t kFrameHdr = 21;      /* ver+family+transport+addr16+port */
constexpr size_t kMaxFrame = 65556;
constexpr uint8_t kTransportUdp = 0;
constexpr uint8_t kTransportTcp = 1;
/* response-only marker from backends: route like UDP, never cache
 * (recursion answers belong to another DC's store) */
constexpr uint8_t kTransportUdpNoStore = 2;
/* control-frame opcodes (family 0; opcode rides the transport byte).
 * 0/1 are backend->balancer; 2 is the direct-return negotiation: the
 * backend announces the capability, and the balancer answers with the
 * same opcode carrying its client-facing UDP fd via SCM_RIGHTS
 * (docs/balancer-protocol.md "Direct-return negotiation"). */
constexpr uint8_t kCtlGen = 0;
constexpr uint8_t kCtlInvalidate = 1;
constexpr uint8_t kCtlDirect = 2;
constexpr size_t kMaxUdpPacket = 65535;
/* Affinity-table cap: the map is keyed by remote host, and mbalancer owns
 * a public UDP port — without a bound, spoofed source addresses would grow
 * it until OOM.  On overflow the whole table resets (stickiness is a
 * best-effort optimization, not a correctness requirement). */
constexpr size_t kMaxRemotes = 65536;

int g_verbose = 0;
/* -D: keep every reply on the relay lane even for capable backends
 * (the bench A/B arm, and an operator escape hatch) */
int g_no_direct = 0;
/* packet-path syscall count (epoll_wait, recvmmsg, sendmmsg, read,
 * writev, accept4, the fd-pass sendmsg): with direct return the bench
 * divides this by queries to prove the per-query kernel-crossing floor
 * actually dropped, not just the cycle shares */
uint64_t g_syscalls = 0;

void logmsg(const char *fmt, ...) {
    va_list ap;
    va_start(ap, fmt);
    fprintf(stderr, "mbalancer: ");
    vfprintf(stderr, fmt, ap);
    fprintf(stderr, "\n");
    va_end(ap);
}

void tracemsg(const char *fmt, ...) {
    if (!g_verbose) return;
    va_list ap;
    va_start(ap, fmt);
    fprintf(stderr, "mbalancer: ");
    vfprintf(stderr, fmt, ap);
    fprintf(stderr, "\n");
    va_end(ap);
}

uint64_t now_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

/* ---- per-stage cycle counters ----
 *
 * Decompose the balancer's own packet path: where inside a query's
 * transit through this process do the cycles go?  Four stages cover
 * every hop a frame takes:
 *
 *   frame-parse    frame/datagram walk: backend frame validation and
 *                  control handling, TCP client reframing — excluding
 *                  the nested stages below
 *   cache-probe    answer-cache work: key build, lookup, hit serve,
 *                  response harvest into the cache
 *   backend-write  query frame build + queue + the writev flush
 *                  toward backends
 *   reply-relay    response routing to clients (UDP sendmmsg batch
 *                  add/flush, TCP framed write)
 *
 * Counters are raw TSC cycles on x86 (CLOCK_MONOTONIC ns elsewhere);
 * one pair of reads per region ~10ns, cheap enough to stay always-on.
 * `cycles_per_us` is calibrated over process lifetime at stats-read
 * time, so consumers (balstat, bench) convert without knowing the TSC
 * rate.  Nested regions subtract out: a stage's cycles are exclusive,
 * so the four cells sum to the balancer's total attributable work and
 * a share-of-total per stage is meaningful. */
static inline uint64_t cycles_now() {
#if defined(__x86_64__) || defined(__i386__)
    unsigned lo, hi;
    __asm__ __volatile__("rdtsc" : "=a"(lo), "=d"(hi));
    return ((uint64_t)hi << 32) | lo;
#else
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1000000000ull + (uint64_t)ts.tv_nsec;
#endif
}

struct StageCell {
    uint64_t cycles = 0;
    uint64_t ops = 0;
};
struct StageCounters {
    StageCell frame_parse, cache_probe, backend_write, reply_relay;
};
StageCounters g_stages;
/* gross cycles of completed nested scopes, reported upward so an
 * enclosing stage times only its own work (single-threaded loop: a
 * plain global is the whole mechanism) */
uint64_t g_nested_cycles = 0;
uint64_t g_cal_cycles0 = 0;     /* lifetime calibration anchors (main) */
double g_cal_mono0 = 0.0;

struct ScopedStage {
    StageCell &cell;
    uint64_t t0, nested0;
    explicit ScopedStage(StageCell &c)
        : cell(c), t0(cycles_now()), nested0(g_nested_cycles) {}
    ~ScopedStage() {
        uint64_t gross = cycles_now() - t0;
        uint64_t nested = g_nested_cycles - nested0;
        cell.cycles += gross > nested ? gross - nested : 0;
        cell.ops++;
        /* replace (not add to) the nested tally: our gross span already
         * contains any grandchildren, so the parent must subtract this
         * span exactly once */
        g_nested_cycles = nested0 + gross;
    }
};

/* ---- client address key: family + 16 bytes + port ---- */
struct ClientKey {
    uint8_t family;
    uint8_t addr[16];
    uint16_t port;
    bool operator==(const ClientKey &o) const {
        return family == o.family && port == o.port &&
               memcmp(addr, o.addr, 16) == 0;
    }
};
struct ClientKeyHash {
    size_t operator()(const ClientKey &k) const {
        size_t h = 1469598103934665603ULL;
        auto mix = [&h](uint8_t b) { h ^= b; h *= 1099511628211ULL; };
        mix(k.family);
        for (int i = 0; i < 16; i++) mix(k.addr[i]);
        mix(k.port & 0xff);
        mix(k.port >> 8);
        return h;
    }
};

ClientKey key_from_sockaddr(const struct sockaddr_storage &ss) {
    ClientKey k{};
    if (ss.ss_family == AF_INET) {
        auto *sin = (const struct sockaddr_in *)&ss;
        k.family = 4;
        memcpy(k.addr, &sin->sin_addr, 4);
        k.port = ntohs(sin->sin_port);
    } else {
        auto *sin6 = (const struct sockaddr_in6 *)&ss;
        k.family = 6;
        memcpy(k.addr, &sin6->sin6_addr, 16);
        k.port = ntohs(sin6->sin6_port);
    }
    return k;
}

void sockaddr_from_key(const ClientKey &k, struct sockaddr_storage *ss,
                       socklen_t *len) {
    memset(ss, 0, sizeof(*ss));
    if (k.family == 4) {
        auto *sin = (struct sockaddr_in *)ss;
        sin->sin_family = AF_INET;
        memcpy(&sin->sin_addr, k.addr, 4);
        sin->sin_port = htons(k.port);
        *len = sizeof(*sin);
    } else {
        auto *sin6 = (struct sockaddr_in6 *)ss;
        sin6->sin6_family = AF_INET6;
        memcpy(&sin6->sin6_addr, k.addr, 16);
        sin6->sin6_port = htons(k.port);
        *len = sizeof(*sin6);
    }
}

/* ---- buffered stream connection (backend or TCP client) ---- */
struct Stream {
    int fd = -1;
    std::vector<uint8_t> rbuf;
    std::deque<std::vector<uint8_t>> wq;   /* pending writes */
    size_t wq_off = 0;                     /* offset into wq.front() */
    size_t wq_bytes = 0;                   /* sum of queued buffers */
    uint64_t flushed_total = 0;            /* lifetime bytes written */

    void queue_write(std::vector<uint8_t> &&data) {
        wq_bytes += data.size();
        wq.push_back(std::move(data));
    }

    /* Drain the queue with writev — under load many query frames are
     * queued per event-loop pass (see flush_pending_backends), and one
     * gathered write moves them all in a single syscall instead of one
     * write per frame.  Returns false on fatal error. */
    bool flush() {
        while (!wq.empty()) {
            struct iovec iov[64];
            int cnt = 0;
            for (auto it = wq.begin(); it != wq.end() && cnt < 64;
                 ++it, ++cnt) {
                size_t skip = (cnt == 0) ? wq_off : 0;
                iov[cnt].iov_base = (void *)(it->data() + skip);
                iov[cnt].iov_len = it->size() - skip;
            }
            ssize_t n = writev(fd, iov, cnt);
            g_syscalls++;
            if (n < 0) {
                if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
                if (errno == EINTR) continue;
                return false;
            }
            flushed_total += (uint64_t)n;
            size_t left = (size_t)n;
            while (left > 0) {
                size_t avail = wq.front().size() - wq_off;
                if (left >= avail) {
                    left -= avail;
                    wq_bytes -= wq.front().size();
                    wq.pop_front();
                    wq_off = 0;
                } else {
                    wq_off += left;
                    left = 0;
                }
            }
        }
        return true;
    }
    bool want_write() const { return !wq.empty(); }
};

struct CacheEntry {
    double expire_at = 0;
    /* Round-robin preservation, mirroring the backend answer cache
     * (binder_tpu/resolver/answer_cache.py): multi-answer responses
     * are collected until kCacheVariants independent shuffles exist,
     * and only then served, cycling through them.  Single-answer
     * entries are complete at one variant. */
    std::vector<std::vector<uint8_t>> wires;
    /* dependency-tag hash: the store name this answer derives from,
     * derived from the key at fill time (cache_tag_hash); matched by
     * the backend's per-name invalidate control frames (opcode 1) */
    uint64_t taghash = 0;
    uint8_t next_variant = 0;
    bool complete = false;
    size_t bytes = 0;
};
constexpr size_t kCacheVariants = 8;
uint64_t g_cache_bytes = 0;           /* across all backends */

uint64_t fnv64(const uint8_t *p, size_t n) {
    uint64_t h = 1469598103934665603ull;        /* FNV-1a 64 */
    for (size_t i = 0; i < n; i++) {
        h ^= p[i];
        h *= 1099511628211ull;
    }
    return h;
}

/*
 * Dependency-tag hash for a cache key (dnskey layout: qtype at [3:5],
 * lowercased qname wire from [7]).  The tag is the store name the
 * answer derives from: for SRV qnames the resolver strips the leading
 * _service._proto labels and looks up the remainder
 * (binder_tpu/resolver/engine.py SRV_RE), so the tag is that suffix of
 * the label chain; for everything else (A-likes, PTR reverse names)
 * the qname itself.  Must stay in lockstep with the tag wires the
 * backend emits (BinderServer._on_store_invalidate -> opcode 1).
 */
uint64_t cache_tag_hash(const std::string &mkey) {
    const uint8_t *k = (const uint8_t *)mkey.data();
    size_t n = mkey.size();
    if (n < 8)
        return 0;
    uint16_t qtype = (uint16_t)((k[3] << 8) | k[4]);
    const uint8_t *qn = k + 7;
    size_t qlen = n - 7;
    if (qtype == 33) {                  /* SRV */
        const uint8_t *p = qn;
        size_t rem = qlen;
        int stripped = 0;
        for (int i = 0; i < 2; i++) {
            if (rem < 2 || p[0] == 0 || p[1] != '_')
                break;
            size_t l = p[0];
            if (1 + l >= rem)
                break;
            p += 1 + l;
            rem -= 1 + l;
            stripped++;
        }
        if (stripped == 2 && rem > 1) {
            qn = p;
            qlen = rem;
        }
    }
    return fnv64(qn, qlen);
}
constexpr size_t kMaxCacheEntriesPerBackend = 65536;
constexpr uint64_t kMaxCacheBytes = 64ull << 20;
constexpr size_t kMaxCacheWire = 4096;

/* ---- backend (one binder process behind a UNIX socket) ---- */
struct Backend {
    int id = -1;
    std::string path;          /* socket path */
    Stream conn;
    bool healthy = false;
    bool present = true;       /* socket file still exists */
    uint64_t forwarded = 0;
    uint64_t responded = 0;
    uint64_t connect_failures = 0;
    /* deferred-flush state (see flush_pending_backends) */
    bool flush_pending = false;
    size_t pending_queued = 0;
    int stall_ticks = 0;       /* consecutive no-drain ticks at depth */
    uint64_t last_flushed_total = 0;   /* drain progress marker */
    /* answer-cache invalidation state: the backend reports its mirror
     * generation over the socket (control frames); entries resolved
     * under an older generation are stale.  epoch distinguishes
     * reconnects — a restarted backend's generation counter restarts,
     * so entries from the previous process must never match. */
    uint64_t gen = 0;
    bool gen_known = false;
    uint32_t epoch = 0;
    /* direct-return negotiation state (docs/balancer-protocol.md):
     * capability announced by the backend (control opcode 2), fd
     * passed by us via SCM_RIGHTS; pending marks a deferred pass
     * (write queue busy at announce time) retried by the timer sweep */
    bool direct_capable = false;
    bool fd_passed = false;
    bool fd_pass_pending = false;
    /* per-backend answer cache (see backend_cache_clear for the
     * invalidation invariant) */
    std::unordered_map<std::string, CacheEntry> cache;
    uint64_t cache_bytes = 0;
};

/* ---- write-queue / connection bounds ----
 * Everything facing a peer that can stop reading must be bounded:
 * a stalled backend or slowloris TCP client must cost O(cap) memory
 * and eventually lose its connection, never OOM the balancer.
 * Defaults are production values; the env overrides exist so tests can
 * trip the caps without shoving megabytes through loopback. */
size_t g_max_backend_wq = 8u << 20;    /* per backend stream */
size_t g_max_client_wq = 1u << 20;     /* per TCP client */
constexpr int kBackendStallTicks = 3;  /* timer ticks at cap => down */
constexpr double kEvictIdleFloorS = 1.0;  /* min idle before cap-evict */

void load_bound_overrides() {
    const char *s = getenv("MBALANCER_MAX_BACKEND_WQ");
    if (s != nullptr && atol(s) > 0) g_max_backend_wq = (size_t)atol(s);
    s = getenv("MBALANCER_MAX_CLIENT_WQ");
    if (s != nullptr && atol(s) > 0) g_max_client_wq = (size_t)atol(s);
}

/* ---- TCP client connection state ---- */
struct TcpClient {
    Stream conn;
    ClientKey key;
    double last_active = 0;   /* mono_s() of last read/write progress */
};

struct Balancer {
    std::string sockdir;
    std::string bind_addr = "0.0.0.0";
    int port = 53;
    int scan_ms = 2000;
    int cache_ms = 60000;      /* answer-cache expiry; 0 disables */
    int tcp_idle_ms = 30000;   /* idle TCP clients are evicted */
    int max_tcp_clients = 1024;

    int epfd = -1;
    int udp_fd = -1;
    int tcp_fd = -1;
    int stats_fd = -1;
    int timer_fd = -1;

    std::vector<Backend> backends;
    std::unordered_map<std::string, int> backend_by_path;
    std::unordered_map<int, int> backend_by_fd;       /* fd -> index */
    std::unordered_map<ClientKey, int, ClientKeyHash> remotes; /* affinity */
    std::unordered_map<int, TcpClient> tcp_clients;   /* fd -> client */
    std::unordered_map<ClientKey, int, ClientKeyHash> tcp_by_key;
    int rr_next = 0;

    uint64_t udp_queries = 0, tcp_queries = 0, drops = 0;
    uint64_t cache_hits = 0;
    uint64_t cache_misses = 0;    /* key built, no fresh entry: forwarded */
    uint64_t uncacheable = 0;     /* key declined: forwarded, never filled */
    uint64_t cache_invalidations = 0;  /* entries dropped by opcode 1 */
    /* forward round-trip (query forwarded on a cache miss -> matching
     * response from the backend), so a topology-axis delta can be
     * attributed: balancer packet path (hits) vs backend round trip
     * (misses).  Log2 cells in µs: [<1, <2, <4, ..., <16384, rest]. */
    static constexpr int kRttCells = 16;
    uint64_t fwd_rtt_count = 0;
    double fwd_rtt_sum_s = 0.0;
    uint64_t fwd_rtt_cells[kRttCells] = {0};
    size_t backend_wq_peak = 0;   /* high-water backend stream queue */
    uint64_t wq_overflows = 0;    /* frames refused: stream at byte cap */
    uint64_t idle_closes = 0;     /* TCP clients evicted for idleness */
    uint64_t client_evictions = 0; /* evicted to admit a new client */
    uint64_t backend_stalls = 0;  /* backends downed for a stuck queue */
    /* direct-return accounting: fds passed (one per negotiated backend
     * connection) and queries forwarded with the reply hop eliminated */
    uint64_t fd_passes = 0;
    uint64_t direct_forwards = 0;
    /* recvmmsg batch-size histogram on the UDP front (log2 cells:
     * 1, 2-3, 4-7, ..., >=128): proves the batching survived whatever
     * the datapath change was — a collapse to cell 0 is per-packet
     * dispatch again */
    static constexpr int kBatchCells = 8;
    uint64_t udp_batch_cells[kBatchCells] = {0};
    uint64_t started_at = 0;
};

Balancer g_bal;

/* fds whose close() is deferred to the end of the current epoll batch:
 * closing mid-batch lets accept4/connect reuse the fd number while stale
 * queued events for the old owner are still pending, which would dispatch
 * against (and tear down) the new connection */
std::vector<int> g_deferred_close;

void defer_close(int fd) {
    epoll_ctl(g_bal.epfd, EPOLL_CTL_DEL, fd, nullptr);
    g_deferred_close.push_back(fd);
}

void epoll_add(int fd, uint32_t events, uint64_t tag) {
    struct epoll_event ev{};
    ev.events = events;
    ev.data.u64 = tag;
    if (epoll_ctl(g_bal.epfd, EPOLL_CTL_ADD, fd, &ev) != 0) {
        logmsg("epoll_ctl ADD failed: %s", strerror(errno));
        exit(1);
    }
}

void epoll_mod(int fd, uint32_t events, uint64_t tag) {
    struct epoll_event ev{};
    ev.events = events;
    ev.data.u64 = tag;
    (void)epoll_ctl(g_bal.epfd, EPOLL_CTL_MOD, fd, &ev);
}

/* epoll tags: low 32 bits fd, high 32 bits kind */
enum Kind : uint64_t {
    KIND_UDP = 1, KIND_TCP_LISTEN, KIND_TCP_CLIENT, KIND_BACKEND,
    KIND_STATS, KIND_TIMER,
};
uint64_t tag(Kind kind, int fd) { return ((uint64_t)kind << 32) | (uint32_t)fd; }

/* ---------------- backend management ---------------- */

void backend_cache_clear(Backend &be);   /* defined with the cache below */
void maybe_pass_fd(Backend &be);         /* defined with the framing below */

void backend_mark_down(Backend &be) {
    if (be.conn.fd >= 0) {
        g_bal.backend_by_fd.erase(be.conn.fd);
        defer_close(be.conn.fd);
        be.conn = Stream();
    }
    be.healthy = false;
    be.gen_known = false;
    be.stall_ticks = 0;
    be.last_flushed_total = 0;
    /* negotiation is per connection: a reconnected backend re-announces
     * capability and receives a fresh fd */
    be.direct_capable = false;
    be.fd_passed = false;
    be.fd_pass_pending = false;
    backend_cache_clear(be);   /* a restarted process restarts its gen */
}

bool backend_connect(Backend &be) {
    int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return false;
    struct sockaddr_un sun{};
    sun.sun_family = AF_UNIX;
    snprintf(sun.sun_path, sizeof(sun.sun_path), "%s", be.path.c_str());
    if (connect(fd, (struct sockaddr *)&sun, sizeof(sun)) != 0 &&
        errno != EINPROGRESS) {
        close(fd);
        be.connect_failures++;
        return false;
    }
    be.conn = Stream();
    be.conn.fd = fd;
    be.stall_ticks = 0;
    be.last_flushed_total = 0;
    be.direct_capable = false;
    be.fd_passed = false;
    be.fd_pass_pending = false;
    be.healthy = true;   /* optimistic; demoted on first error */
    /* new process behind the same socket path: its generation counter
     * restarts, so retire every cache entry from the previous epoch */
    be.epoch++;
    be.gen_known = false;
    g_bal.backend_by_fd[fd] = be.id;
    epoll_add(fd, EPOLLIN, tag(KIND_BACKEND, fd));
    tracemsg("backend %d connected at %s", be.id, be.path.c_str());
    return true;
}

void scan_sockdir() {
    DIR *d = opendir(g_bal.sockdir.c_str());
    if (d == nullptr) {
        logmsg("cannot open socket dir %s: %s", g_bal.sockdir.c_str(),
               strerror(errno));
        return;
    }
    for (auto &be : g_bal.backends) be.present = false;

    struct dirent *de;
    while ((de = readdir(d)) != nullptr) {
        if (de->d_name[0] == '.') continue;  /* incl. .balancer.stats */
        std::string path = g_bal.sockdir + "/" + de->d_name;
        struct stat st;
        if (stat(path.c_str(), &st) != 0 || !S_ISSOCK(st.st_mode)) continue;
        auto it = g_bal.backend_by_path.find(path);
        if (it == g_bal.backend_by_path.end()) {
            Backend be;
            be.id = (int)g_bal.backends.size();
            be.path = path;
            be.present = true;
            g_bal.backends.push_back(std::move(be));
            g_bal.backend_by_path[path] = g_bal.backends.back().id;
            backend_connect(g_bal.backends.back());
            logmsg("backend %d added: %s",
                   g_bal.backends.back().id, path.c_str());
        } else {
            Backend &be = g_bal.backends[it->second];
            be.present = true;
            if (!be.healthy) backend_connect(be);
        }
    }
    closedir(d);

    /* sockets that vanished: the backend told us it's going away */
    for (auto &be : g_bal.backends) {
        if (!be.present && be.healthy) {
            logmsg("backend %d socket removed, draining", be.id);
            backend_mark_down(be);
        }
    }
}

void tcp_client_close(int fd);   /* defined with the TCP front below */
double mono_s();                 /* defined with the cache below */

/* Periodic resource sweep (rides the sockdir-scan timer): evict TCP
 * clients idle past the deadline, and mark down backends whose write
 * queue has sat at the byte cap for kBackendStallTicks consecutive
 * ticks — a backend that stopped reading is as dead as one that
 * closed, it just fails slower. */
void sweep_connections() {
    double now = mono_s();
    if (g_bal.tcp_idle_ms > 0) {   /* -T 0 disables, like -c 0 */
        double idle_cutoff = now - (double)g_bal.tcp_idle_ms / 1000.0;
        std::vector<int> idle;
        for (const auto &p : g_bal.tcp_clients)
            if (p.second.last_active < idle_cutoff)
                idle.push_back(p.first);
        for (int fd : idle) {
            g_bal.idle_closes++;
            tracemsg("closing idle TCP client fd %d", fd);
            tcp_client_close(fd);
        }
    }
    for (auto &be : g_bal.backends) {
        if (be.conn.fd < 0) continue;
        /* "stalled" = deep queue AND zero drain progress since the
         * last tick — a saturated-but-draining backend (flushed_total
         * advancing) is busy, not dead, and must stay in rotation */
        if (be.conn.wq_bytes >= g_max_backend_wq / 2 &&
            be.conn.flushed_total == be.last_flushed_total) {
            if (++be.stall_ticks >= kBackendStallTicks) {
                logmsg("backend %d stalled (%zu bytes queued, no drain), "
                       "marking down", be.id, be.conn.wq_bytes);
                g_bal.backend_stalls++;
                backend_mark_down(be);
            }
        } else {
            be.stall_ticks = 0;
        }
        be.last_flushed_total = be.conn.flushed_total;
        if (be.fd_pass_pending)
            maybe_pass_fd(be);   /* deferred pass: queue was busy */
    }
}

int pick_backend(const ClientKey &client) {
    size_t n = g_bal.backends.size();
    if (n == 0) return -1;

    /* affinity is per remote host (reference remote_t keeps rem_addr
     * only), so ignore the source port */
    ClientKey host = client;
    host.port = 0;

    auto it = g_bal.remotes.find(host);
    if (it != g_bal.remotes.end()) {
        Backend &be = g_bal.backends[it->second];
        if (be.healthy) return it->second;
        g_bal.remotes.erase(it);   /* affinity to a dead backend */
    }
    /* round-robin over healthy backends */
    for (size_t i = 0; i < n; i++) {
        int idx = (g_bal.rr_next + (int)i) % (int)n;
        if (g_bal.backends[idx].healthy) {
            g_bal.rr_next = (idx + 1) % (int)n;
            if (g_bal.remotes.size() >= kMaxRemotes) g_bal.remotes.clear();
            g_bal.remotes[host] = idx;
            return idx;
        }
    }
    return -1;
}

/* ---------------- framing ---------------- */

std::vector<uint8_t> make_frame(const ClientKey &k, uint8_t transport,
                                const uint8_t *payload, size_t len) {
    std::vector<uint8_t> out(4 + kFrameHdr + len);
    uint32_t L = htonl((uint32_t)(kFrameHdr + len));
    memcpy(out.data(), &L, 4);
    out[4] = kProtoVersion;
    out[5] = k.family;
    out[6] = transport;
    memcpy(out.data() + 7, k.addr, 16);
    out[23] = (uint8_t)(k.port >> 8);
    out[24] = (uint8_t)(k.port & 0xff);
    memcpy(out.data() + 25, payload, len);
    return out;
}

/* ---------------- direct-return fd passing ----------------
 *
 * A capable backend (control opcode 2) receives our client-facing UDP
 * socket over the UNIX channel via SCM_RIGHTS and answers UDP clients
 * on it directly (sendmmsg with the frame's sockaddr as msg_name) —
 * the reply never re-enters this process.  The ancillary payload must
 * ride a specific sendmsg, so the pass happens only while the backend
 * stream's write queue is empty (otherwise mid-frame bytes would be
 * interleaved); a busy queue defers the pass to the timer sweep. */
void maybe_pass_fd(Backend &be) {
    if (g_no_direct || !be.direct_capable || be.fd_passed ||
        be.conn.fd < 0 || g_bal.udp_fd < 0)
        return;
    if (be.conn.want_write()) {
        be.fd_pass_pending = true;
        return;
    }
    uint8_t frame[4 + kFrameHdr];
    uint32_t L = htonl((uint32_t)kFrameHdr);
    memcpy(frame, &L, 4);
    frame[4] = kProtoVersion;
    frame[5] = 0;            /* control */
    frame[6] = kCtlDirect;   /* fd-pass */
    memset(frame + 7, 0, kFrameHdr - 3);
    struct iovec iov;
    iov.iov_base = frame;
    iov.iov_len = sizeof(frame);
    char cbuf[CMSG_SPACE(sizeof(int))];
    memset(cbuf, 0, sizeof(cbuf));
    struct msghdr msg{};
    msg.msg_iov = &iov;
    msg.msg_iovlen = 1;
    msg.msg_control = cbuf;
    msg.msg_controllen = sizeof(cbuf);
    struct cmsghdr *cm = CMSG_FIRSTHDR(&msg);
    cm->cmsg_level = SOL_SOCKET;
    cm->cmsg_type = SCM_RIGHTS;
    cm->cmsg_len = CMSG_LEN(sizeof(int));
    memcpy(CMSG_DATA(cm), &g_bal.udp_fd, sizeof(int));
    ssize_t n;
    do {
        n = sendmsg(be.conn.fd, &msg, MSG_NOSIGNAL);
    } while (n < 0 && errno == EINTR);
    g_syscalls++;
    if (n == (ssize_t)sizeof(frame)) {
        be.fd_passed = true;
        be.fd_pass_pending = false;
        g_bal.fd_passes++;
        tracemsg("backend %d: direct-return fd passed", be.id);
        return;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        be.fd_pass_pending = true;   /* socket buffer full: retry later */
        return;
    }
    /* Hard failure (or a partial write, impossible for 25 bytes into an
     * empty non-blocking stream buffer but handled): direct return is
     * an optimization, so give up on the pass and keep the relay lane;
     * a genuinely broken link fails on the next regular write/read. */
    be.fd_pass_pending = false;
    logmsg("backend %d: fd pass failed (%s), staying on relay lane",
           be.id, n < 0 ? strerror(errno) : "partial write");
}

/* ---------------- answer cache ----------------
 *
 * The balancer caches single-answer UDP responses it forwards, keyed by
 * (backend id, backend epoch, question key) — the question key is the
 * same dnskey_build the backend fast path uses.  Correctness mirrors
 * the backend's own answer cache:
 *  - entries record the backend's mirror generation at fill time;
 *    backends report it over the socket (control frames, sent on
 *    connect and on every store mutation), and stale-generation
 *    entries are lazily dropped;
 *  - a reconnect bumps the epoch, retiring all prior entries (a
 *    restarted backend's generation counter restarts);
 *  - time expiry (-c <ms>, default 60 s, 0 disables);
 *  - round-robin rotation is preserved like the backend cache
 *    preserves it: multi-answer entries collect kCacheVariants
 *    independent shuffles before serving, then cycle through them;
 *  - SERVFAIL is never cached (matches BinderServer._on_query).
 * Fill state rides a fixed pending table keyed by (client, qid): the
 * forward records the question key, the matching response harvests it.
 */
struct PendingFill {
    ClientKey client{};
    uint16_t qid = 0;
    uint16_t keylen = 0;
    int backend_id = -1;
    uint32_t epoch = 0;
    bool used = false;
    double sent_at = 0.0;         /* forward time, for the RTT cells */
    uint8_t key[DNSKEY_MAX];
};
constexpr size_t kPendingSlots = 8192;   /* power of two */
PendingFill g_pending_fill[kPendingSlots];

size_t pending_slot(const ClientKey &k, uint16_t qid) {
    size_t h = ClientKeyHash{}(k);
    h ^= (size_t)qid * 1099511628211ULL;
    return h & (kPendingSlots - 1);
}

double mono_s() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

/* Every entry in a backend's cache was filled under its *current*
 * generation and connection epoch — a generation report that advances
 * the generation, and every reconnect, clears the whole per-backend
 * map.  That keeps invalidation O(changed backend), reclaims dead
 * entries immediately (no unreachable-key garbage), and removes any
 * need for per-entry generation checks on the hit path. */
void backend_cache_clear(Backend &be) {
    g_cache_bytes -= be.cache_bytes;
    be.cache_bytes = 0;
    be.cache.clear();
}

void backend_cache_insert(Backend &be, const uint8_t *key, size_t keylen,
                          const uint8_t *wire, size_t len, bool rotatable) {
    std::string mkey((const char *)key, keylen);
    {
        /* discard-before-evict: a late fill that will be thrown away
         * must not trigger the budget eviction below (which could wipe
         * another backend's entire hot cache for a 0-byte insert) */
        auto it = be.cache.find(mkey);
        if (it != be.cache.end() &&
            (it->second.complete ||
             it->second.wires.size() >= kCacheVariants))
            return;   /* late fill from a pre-completion forward */
    }
    if (be.cache.size() >= kMaxCacheEntriesPerBackend) {
        /* bounded reset, like the affinity table: the cache is an
         * optimization, and a flood of distinct questions must not OOM */
        backend_cache_clear(be);
    }
    while (g_cache_bytes + len > kMaxCacheBytes) {
        /* The byte budget is global, so shed from whichever backend
         * holds the most — clearing the *inserting* backend would let
         * one dominant backend starve the others' (small) caches
         * without ever bringing the total under the cap. */
        Backend *fat = &be;
        for (auto &other : g_bal.backends)
            if (other.cache_bytes > fat->cache_bytes)
                fat = &other;
        if (fat->cache_bytes == 0)
            break;                     /* len alone exceeds the budget */
        backend_cache_clear(*fat);
    }
    CacheEntry &e = be.cache[mkey];
    if (e.wires.empty()) {
        e.expire_at = mono_s() + (double)g_bal.cache_ms / 1000.0;
        e.taghash = cache_tag_hash(mkey);
    }
    e.wires.emplace_back(wire, wire + len);
    e.bytes += len;
    g_cache_bytes += len;
    be.cache_bytes += len;
    /* single-answer responses have nothing to rotate; rotatable ones
     * serve only once enough independent shuffles are collected */
    e.complete = !rotatable || e.wires.size() >= kCacheVariants;
}

/* Backends with frames queued this event-loop pass; flushed once per
 * pass (flush_pending_backends) so a burst of N queries to a backend
 * costs one writev, not N writes. */
std::vector<int> g_flush_pending;

void forward_query_to(int idx, const ClientKey &client, uint8_t transport,
                      const uint8_t *payload, size_t len) {
    Backend &be = g_bal.backends[idx];
    if (be.conn.wq_bytes >= g_max_backend_wq) {
        /* backend not draining: shed this query (clients retry) rather
         * than grow the queue without bound; a persistently stuck queue
         * gets the backend marked down by the timer sweep */
        g_bal.drops++;
        g_bal.wq_overflows++;
        return;
    }
    ScopedStage _ss(g_stages.backend_write);
    be.conn.queue_write(make_frame(client, transport, payload, len));
    if (be.conn.wq_bytes > g_bal.backend_wq_peak)
        g_bal.backend_wq_peak = be.conn.wq_bytes;
    be.forwarded++;
    be.pending_queued++;
    if (!be.flush_pending) {
        be.flush_pending = true;
        g_flush_pending.push_back(idx);
    }
}

void forward_query(const ClientKey &client, uint8_t transport,
                   const uint8_t *payload, size_t len) {
    int idx = pick_backend(client);
    if (idx < 0) {
        g_bal.drops++;
        tracemsg("no healthy backend, dropping query");
        return;
    }
    forward_query_to(idx, client, transport, payload, len);
}

void flush_pending_backends() {
    if (g_flush_pending.empty()) return;
    ScopedStage _ss(g_stages.backend_write);
    for (int idx : g_flush_pending) {
        Backend &be = g_bal.backends[idx];
        be.flush_pending = false;
        size_t queued = be.pending_queued;
        be.pending_queued = 0;
        if (be.conn.fd < 0) {
            /* went down earlier in this pass; its write queue (and the
             * frames just queued) died with the connection */
            g_bal.drops += queued;
            continue;
        }
        if (!be.conn.flush()) {
            logmsg("backend %d write error: %s", be.id, strerror(errno));
            backend_mark_down(be);
            g_bal.drops += queued;
            continue;
        }
        if (be.conn.want_write())
            epoll_mod(be.conn.fd, EPOLLIN | EPOLLOUT,
                      tag(KIND_BACKEND, be.conn.fd));
    }
    g_flush_pending.clear();
}

/* UDP egress batch: responses decoded from one backend-read pass are
 * flushed with a single sendmmsg.  Payload pointers reference the
 * backend's read buffer, so the batch MUST be flushed before that
 * buffer is mutated (handle_backend flushes after each framing pass).
 * Per-destination errors skip one datagram and continue — one
 * unreachable client must not drop other clients' responses. */
struct UdpOut {
    struct mmsghdr msgs[64];
    struct iovec iovs[64];
    struct sockaddr_storage addrs[64];
    /* copy arena for cache-hit responses (they need id/question patching
     * and must outlive the cache entry until the flush) */
    uint8_t copybuf[64][kMaxCacheWire];
    int n = 0;
} g_udp_out;

void udp_out_flush() {
    if (g_udp_out.n == 0) return;
    ScopedStage _ss(g_stages.reply_relay);
    int off = 0;
    while (off < g_udp_out.n) {
        int sent = sendmmsg(g_bal.udp_fd, g_udp_out.msgs + off,
                            (unsigned)(g_udp_out.n - off), MSG_DONTWAIT);
        g_syscalls++;
        if (sent >= 0) {
            off += sent > 0 ? sent : 1;
            continue;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            /* socket buffer full: drop rest (UDP) */
            g_bal.drops += (uint64_t)(g_udp_out.n - off);
            break;
        }
        if (errno == EBADF || errno == ENOTSOCK || errno == EFAULT ||
            errno == ENOMEM) {
            /* batch-fatal, not per-destination (same policy as
             * fastpath.c's hit flush): retrying datagram-by-datagram
             * on a dead fd or OOM just spins 64 times */
            g_bal.drops += (uint64_t)(g_udp_out.n - off);
            logmsg("udp_out_flush: fatal sendmmsg errno %d", errno);
            break;
        }
        g_bal.drops += 1;
        off += 1;              /* per-destination failure: skip one */
    }
    g_udp_out.n = 0;
}

void udp_out_add(const struct sockaddr_storage &ss, socklen_t slen,
                 const uint8_t *payload, size_t len) {
    if (g_udp_out.n == 64)
        udp_out_flush();
    int i = g_udp_out.n++;
    g_udp_out.addrs[i] = ss;
    g_udp_out.iovs[i].iov_base = (void *)payload;
    g_udp_out.iovs[i].iov_len = len;
    memset(&g_udp_out.msgs[i], 0, sizeof(g_udp_out.msgs[i]));
    g_udp_out.msgs[i].msg_hdr.msg_iov = &g_udp_out.iovs[i];
    g_udp_out.msgs[i].msg_hdr.msg_iovlen = 1;
    g_udp_out.msgs[i].msg_hdr.msg_name = &g_udp_out.addrs[i];
    g_udp_out.msgs[i].msg_hdr.msg_namelen = slen;
}

/* Like udp_out_add, but copies the payload into the batch's own arena
 * and returns the copy for in-place patching (cache-hit responses). */
uint8_t *udp_out_add_copy(const struct sockaddr_storage &ss,
                          socklen_t slen, const uint8_t *payload,
                          size_t len) {
    if (g_udp_out.n == 64)
        udp_out_flush();
    uint8_t *dst = g_udp_out.copybuf[g_udp_out.n];
    memcpy(dst, payload, len);
    udp_out_add(ss, slen, dst, len);
    return dst;
}

/* ---------------- fronts ---------------- */

void handle_udp() {
    /* recvmmsg drain: up to 64 datagrams per kernel crossing (the same
     * batching the backend datapath uses, native/fastio/fastio.c) */
    static uint8_t bufs[64][kMaxUdpPacket];
    struct mmsghdr msgs[64];
    struct iovec iovs[64];
    struct sockaddr_storage addrs[64];

    for (;;) {
        memset(msgs, 0, sizeof(msgs));
        for (int i = 0; i < 64; i++) {
            iovs[i].iov_base = bufs[i];
            iovs[i].iov_len = kMaxUdpPacket;
            msgs[i].msg_hdr.msg_iov = &iovs[i];
            msgs[i].msg_hdr.msg_iovlen = 1;
            msgs[i].msg_hdr.msg_name = &addrs[i];
            msgs[i].msg_hdr.msg_namelen = sizeof(addrs[i]);
        }
        int n = recvmmsg(g_bal.udp_fd, msgs, 64, MSG_DONTWAIT, nullptr);
        g_syscalls++;
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
                break;
            logmsg("udp recv error: %s", strerror(errno));
            break;
        }
        if (n > 0) {
            int cell = 0;
            while (cell < Balancer::kBatchCells - 1 &&
                   (1 << (cell + 1)) <= n)
                cell++;
            g_bal.udp_batch_cells[cell]++;
        }
        for (int i = 0; i < n; i++) {
            size_t plen = msgs[i].msg_len;
            const uint8_t *pkt = bufs[i];
            if (plen < 12) continue;             /* short of a header */
            g_bal.udp_queries++;
            ClientKey ck = key_from_sockaddr(addrs[i]);

            /* direct-return backends answer UDP clients on our socket
             * themselves: no reply ever transits this process, so the
             * answer cache can never fill for them — skip the probe
             * and pending bookkeeping, just forward */
            {
                int didx = pick_backend(ck);
                if (didx < 0) {
                    g_bal.drops++;
                    continue;
                }
                if (g_bal.backends[didx].fd_passed) {
                    g_bal.direct_forwards++;
                    forward_query_to(didx, ck, kTransportUdp, pkt, plen);
                    continue;
                }
            }
            if (g_bal.cache_ms > 0) {
                /* attribution: key build + affinity pick + cache
                 * lookup + hit serve / miss record (the nested
                 * backend-write on a miss subtracts itself out) */
                ScopedStage _probe(g_stages.cache_probe);
                uint8_t key[DNSKEY_MAX];
                size_t qn_len = 0;
                uint16_t qtype = 0;
                size_t keylen = dnskey_build(pkt, plen, key, &qn_len,
                                             &qtype);
                if (keylen != 0) {
                    int idx = pick_backend(ck);
                    if (idx < 0) {
                        g_bal.drops++;
                        continue;
                    }
                    Backend &be = g_bal.backends[idx];
                    /* reused buffer: no per-packet allocation on the
                     * lookup path once its capacity has grown */
                    static std::string lookup_key;
                    lookup_key.assign((const char *)key, keylen);
                    auto it = be.cache.find(lookup_key);
                    if (it != be.cache.end()) {
                        CacheEntry &e = it->second;
                        if (mono_s() > e.expire_at) {
                            g_cache_bytes -= e.bytes;
                            be.cache_bytes -= e.bytes;
                            be.cache.erase(it);   /* expired */
                        } else if (e.complete) {
                            const auto &w = e.wires[
                                e.next_variant % e.wires.size()];
                            e.next_variant = (uint8_t)(
                                (e.next_variant + 1) % e.wires.size());
                            if (w.size() >= 12 + qn_len + 4) {
                                uint8_t *out = udp_out_add_copy(
                                    addrs[i], msgs[i].msg_hdr.msg_namelen,
                                    w.data(), w.size());
                                out[0] = pkt[0];    /* request id */
                                out[1] = pkt[1];
                                /* 0x20 case echo */
                                memcpy(out + 12, pkt + 12, qn_len + 4);
                                g_bal.cache_hits++;
                                continue;
                            }
                        }
                        /* incomplete: keep forwarding so responses
                         * collect more shuffle variants */
                    }
                    /* miss: remember the key so the response can fill */
                    g_bal.cache_misses++;
                    PendingFill &pf = g_pending_fill[
                        pending_slot(ck, dnskey_rd16(pkt))];
                    pf.client = ck;
                    pf.qid = dnskey_rd16(pkt);
                    pf.keylen = (uint16_t)keylen;
                    pf.backend_id = be.id;
                    pf.epoch = be.epoch;
                    pf.used = true;
                    pf.sent_at = mono_s();
                    memcpy(pf.key, key, keylen);
                    forward_query_to(idx, ck, kTransportUdp, pkt, plen);
                    continue;
                }
                g_bal.uncacheable++;
            }
            forward_query(ck, kTransportUdp, pkt, plen);
        }
        if (n < 64) break;
    }
    flush_pending_backends();
    udp_out_flush();
}

void tcp_client_close(int fd) {
    auto it = g_bal.tcp_clients.find(fd);
    if (it != g_bal.tcp_clients.end()) {
        g_bal.tcp_by_key.erase(it->second.key);
        g_bal.tcp_clients.erase(it);
    }
    defer_close(fd);
}

void handle_tcp_accept() {
    for (;;) {
        struct sockaddr_storage ss{};
        socklen_t slen = sizeof(ss);
        int fd = accept4(g_bal.tcp_fd, (struct sockaddr *)&ss, &slen,
                         SOCK_NONBLOCK);
        g_syscalls++;
        if (fd < 0) return;
        if ((int)g_bal.tcp_clients.size() >= g_bal.max_tcp_clients) {
            /* At the connection cap: evict the idlest client to admit
             * the newcomer — but only one genuinely idle (past the
             * floor).  Unconditional evict-idlest would let a cheap
             * connect() flood displace every established client, since
             * fresh attacker connections always carry newer activity
             * stamps than the legitimate ones they evict. */
            int idlest = -1;
            double oldest = 1e300;
            for (const auto &p : g_bal.tcp_clients) {
                if (p.second.last_active < oldest) {
                    oldest = p.second.last_active;
                    idlest = p.first;
                }
            }
            if (idlest >= 0 && mono_s() - oldest >= kEvictIdleFloorS) {
                g_bal.client_evictions++;
                tcp_client_close(idlest);
            } else {
                /* everyone is recently active (or cap is 0): refuse
                 * the newcomer; idle-timeout sweeps recycle slots */
                close(fd);
                continue;
            }
        }
        TcpClient tc;
        tc.conn.fd = fd;
        tc.key = key_from_sockaddr(ss);
        tc.last_active = mono_s();
        g_bal.tcp_clients[fd] = std::move(tc);
        g_bal.tcp_by_key[g_bal.tcp_clients[fd].key] = fd;
        epoll_add(fd, EPOLLIN, tag(KIND_TCP_CLIENT, fd));
    }
}

void handle_tcp_client(int fd, uint32_t events) {
    auto it = g_bal.tcp_clients.find(fd);
    if (it == g_bal.tcp_clients.end()) return;
    TcpClient &tc = it->second;

    if (events & (EPOLLHUP | EPOLLERR)) {
        tcp_client_close(fd);
        return;
    }
    if (events & EPOLLOUT) {
        if (!tc.conn.flush()) {
            tcp_client_close(fd);
            return;
        }
        tc.last_active = mono_s();
        if (!tc.conn.want_write())
            epoll_mod(fd, EPOLLIN, tag(KIND_TCP_CLIENT, fd));
    }
    if (!(events & EPOLLIN)) return;

    uint8_t buf[16384];
    for (;;) {
        ssize_t n = read(fd, buf, sizeof(buf));
        g_syscalls++;
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            flush_pending_backends();
            tcp_client_close(fd);
            return;
        }
        if (n == 0) {
            flush_pending_backends();
            tcp_client_close(fd);
            return;
        }
        tc.last_active = mono_s();
        auto &rb = tc.conn.rbuf;
        rb.insert(rb.end(), buf, buf + n);
        /* RFC 1035 4.2.2 framing: u16 length + message */
        size_t off = 0;
        {
            ScopedStage _parse(g_stages.frame_parse);
            while (rb.size() - off >= 2) {
                uint16_t mlen = (uint16_t)((rb[off] << 8) | rb[off + 1]);
                if (rb.size() - off - 2 < mlen) break;
                g_bal.tcp_queries++;
                forward_query(tc.key, kTransportTcp,
                              rb.data() + off + 2, mlen);
                off += 2 + mlen;
            }
        }
        if (off > 0) rb.erase(rb.begin(), rb.begin() + off);
        if (rb.size() > kMaxFrame) {  /* garbage flood */
            flush_pending_backends();
            tcp_client_close(fd);
            return;
        }
    }
    flush_pending_backends();
}

/* ---------------- backend responses ---------------- */

/* Harvest a forwarded response into the answer cache when its pending
 * record matches (see the miss path in handle_udp).  Non-SERVFAIL UDP
 * responses under a known backend generation are cacheable;
 * multi-answer responses enter as rotation variants (CacheEntry). */
/* The pending record alone is NOT proof the response answers the
 * recorded question: (client, qid) collide whenever a client has two
 * queries in flight under one qid (routine for stub resolvers), and a
 * slot overwrite would otherwise cache answer A under question B's key.
 * So the response's own echoed question must byte-match the key. */
bool response_matches_key(const PendingFill &pf, const uint8_t *payload,
                          size_t len) {
    uint16_t flags = dnskey_rd16(payload + 2);
    if (!(flags & 0x8000))                       /* not a response */
        return false;
    if (flags & 0x0200)                          /* truncated: payload- */
        return false;                            /* ceiling dependent */
    if (((flags >> 8) & 1) != (pf.key[0] & 1))   /* RD echo */
        return false;
    if (dnskey_rd16(payload + 4) != 1)           /* qdcount */
        return false;
    unsigned ceiling = ((unsigned)pf.key[1] << 8) | pf.key[2];
    if (len > ceiling)
        return false;
    /* question name: uncompressed labels, lowercased compare against
     * the key's qname (key layout: 7 fixed bytes + qname) */
    size_t off = 12;
    size_t klen = (size_t)pf.keylen - 7;
    const uint8_t *kn = pf.key + 7;
    for (size_t i = 0; i < klen; i++) {
        if (off + i >= len)
            return false;
        uint8_t ch = payload[off + i];
        if (ch >= 'A' && ch <= 'Z')
            ch = (uint8_t)(ch + 32);
        if (ch != kn[i])
            return false;
    }
    off += klen;
    if (off + 4 > len)
        return false;
    return payload[off] == pf.key[3] && payload[off + 1] == pf.key[4]
        && payload[off + 2] == pf.key[5] && payload[off + 3] == pf.key[6];
}

void maybe_cache_fill(Backend &be, uint8_t family, const uint8_t *addr16,
                      uint16_t port, const uint8_t *payload, size_t len) {
    if (!be.gen_known || len < 12 + 5 || len > kMaxCacheWire)
        return;
    ScopedStage _ss(g_stages.cache_probe);
    ClientKey ck{};
    ck.family = family;
    memcpy(ck.addr, addr16, 16);
    ck.port = port;
    uint16_t qid = dnskey_rd16(payload);
    PendingFill &pf = g_pending_fill[pending_slot(ck, qid)];
    if (!pf.used || pf.qid != qid || !(pf.client == ck)
            || pf.backend_id != be.id || pf.epoch != be.epoch)
        return;
    if (!response_matches_key(pf, payload, len))
        return;                                  /* qid reuse / mismatch */
    pf.used = false;
    /* matched forward->response pair: record the backend round trip */
    double rtt = mono_s() - pf.sent_at;
    if (rtt >= 0.0) {
        g_bal.fwd_rtt_count++;
        g_bal.fwd_rtt_sum_s += rtt;
        double us = rtt * 1e6;
        int cell = 0;
        while (cell < Balancer::kRttCells - 1 && us >= 1.0) {
            us /= 2.0;
            cell++;
        }
        g_bal.fwd_rtt_cells[cell]++;
    }
    if ((payload[3] & 0x0F) == 2)                /* SERVFAIL */
        return;
    backend_cache_insert(be, pf.key, pf.keylen, payload, len,
                         /* rotatable= */ dnskey_rd16(payload + 6) > 1);
}

void route_response(uint8_t family, uint8_t transport,
                    const uint8_t *addr16, uint16_t port,
                    const uint8_t *payload, size_t len) {
    ScopedStage _ss(g_stages.reply_relay);
    ClientKey k{};
    k.family = family;
    memcpy(k.addr, addr16, 16);
    k.port = port;

    if (transport == kTransportUdp) {
        struct sockaddr_storage ss;
        socklen_t slen;
        sockaddr_from_key(k, &ss, &slen);
        udp_out_add(ss, slen, payload, len);
    } else {
        auto it = g_bal.tcp_by_key.find(k);
        if (it == g_bal.tcp_by_key.end()) {
            g_bal.drops++;   /* client went away */
            return;
        }
        TcpClient &tc = g_bal.tcp_clients[it->second];
        if (tc.conn.wq_bytes >= g_max_client_wq) {
            /* client asked but stopped reading answers: disconnect
             * rather than buffer unboundedly */
            g_bal.wq_overflows++;
            tcp_client_close(it->second);
            return;
        }
        std::vector<uint8_t> out(2 + len);
        out[0] = (uint8_t)(len >> 8);
        out[1] = (uint8_t)(len & 0xff);
        memcpy(out.data() + 2, payload, len);
        tc.conn.queue_write(std::move(out));
        if (!tc.conn.flush()) {
            tcp_client_close(it->second);
            return;
        }
        /* delivering a response is activity: a client whose query takes
         * longer than tcp_idle_ms, or that receives a steady stream of
         * answers without writing again, must not be swept as idle */
        tc.last_active = mono_s();
        if (tc.conn.want_write())
            epoll_mod(tc.conn.fd, EPOLLIN | EPOLLOUT,
                      tag(KIND_TCP_CLIENT, tc.conn.fd));
    }
}

/* Append `n` bytes from a backend connection to its stream buffer and
 * walk the complete frames in it.  Returns false on a protocol error
 * (caller marks the backend down).  Split out of handle_backend so the
 * frame parser can be driven directly with hostile bytes (fuzz target
 * native/fuzz/fuzz_frames.cpp). */
bool backend_consume(Backend &be, const uint8_t *buf, size_t n) {
    /* attribution: the frame walk itself; the nested cache-probe
     * (maybe_cache_fill) and reply-relay (route_response, the batched
     * udp_out_flush) scopes subtract themselves out */
    ScopedStage _ss(g_stages.frame_parse);
    auto &rb = be.conn.rbuf;
    rb.insert(rb.end(), buf, buf + n);
    size_t off = 0;
    bool ok = true;
    std::unordered_set<uint64_t> pending_inval;
    while (rb.size() - off >= 4) {
        uint32_t L;
        memcpy(&L, rb.data() + off, 4);
        L = ntohl(L);
        if (L < kFrameHdr || L > kMaxFrame) {
            logmsg("backend %d protocol error (frame len %u)", be.id, L);
            ok = false;
            break;
        }
        if (rb.size() - off - 4 < L) break;
        const uint8_t *f = rb.data() + off + 4;
        if (f[0] != kProtoVersion) {
            logmsg("backend %d protocol version %u", be.id, f[0]);
            ok = false;
            break;
        }
        if (f[1] == 0) {
            /* control frame; opcode in the transport byte (unknown
             * opcodes are skipped so the channel can grow).
             * 0 = generation (epoch) report: 8 bytes BE in the address
             * field; an advance means a full re-mirror — every cached
             * entry from this backend is stale.
             * 1 = per-name invalidate: the payload after the frame
             * header is the tag qname wire; drop exactly the entries
             * whose answers derive from it (ordinary store churn). */
            if (f[2] == kCtlGen && L >= kFrameHdr) {
                uint64_t g = 0;
                for (int b = 0; b < 8; b++)
                    g = (g << 8) | f[3 + b];
                if (!be.gen_known || be.gen != g)
                    backend_cache_clear(be);   /* all entries stale */
                be.gen = g;
                be.gen_known = true;
            } else if (f[2] == kCtlDirect) {
                /* direct-return capability announce: answer with our
                 * UDP fd over SCM_RIGHTS (unless -D keeps the relay) */
                be.direct_capable = true;
                maybe_pass_fd(be);
            } else if (f[2] == kCtlInvalidate && L > kFrameHdr) {
                size_t tlen = L - kFrameHdr;
                if (tlen >= 2 && tlen <= 256)
                    /* batched: applied in one cache scan after the
                     * frame loop — the backend coalesces one flush of
                     * tags per mutation turn, which arrives as one
                     * read, so churn costs one scan per turn, not one
                     * per tag */
                    pending_inval.insert(fnv64(f + kFrameHdr, tlen));
            }
            off += 4 + L;
            continue;
        }
        uint16_t port = (uint16_t)((f[19] << 8) | f[20]);
        be.responded++;
        if (g_bal.cache_ms > 0 && f[2] == kTransportUdp)
            maybe_cache_fill(be, f[1], f + 3, port, f + kFrameHdr,
                             L - kFrameHdr);
        uint8_t transport = f[2] == kTransportUdpNoStore
            ? kTransportUdp : f[2];
        route_response(f[1], transport, f + 3, port, f + kFrameHdr,
                       L - kFrameHdr);
        off += 4 + L;
    }
    /* batched UDP responses reference rb — flush before it mutates */
    udp_out_flush();
    if (off > 0) rb.erase(rb.begin(), rb.begin() + off);
    if (!pending_inval.empty()) {
        for (auto it = be.cache.begin(); it != be.cache.end(); ) {
            if (pending_inval.count(it->second.taghash) != 0) {
                g_cache_bytes -= it->second.bytes;
                be.cache_bytes -= it->second.bytes;
                g_bal.cache_invalidations++;
                it = be.cache.erase(it);
            } else {
                ++it;
            }
        }
    }
    return ok;
}

void handle_backend(int fd, uint32_t events) {
    auto it = g_bal.backend_by_fd.find(fd);
    if (it == g_bal.backend_by_fd.end()) return;
    Backend &be = g_bal.backends[it->second];

    if (events & (EPOLLHUP | EPOLLERR)) {
        logmsg("backend %d connection lost", be.id);
        backend_mark_down(be);
        return;
    }
    if (events & EPOLLOUT) {
        if (!be.conn.flush()) {
            backend_mark_down(be);
            return;
        }
        if (!be.conn.want_write()) {
            epoll_mod(fd, EPOLLIN, tag(KIND_BACKEND, fd));
            if (be.fd_pass_pending)
                maybe_pass_fd(be);   /* queue just drained */
        }
    }
    if (!(events & EPOLLIN)) return;

    uint8_t buf[16384];
    for (;;) {
        ssize_t n = read(fd, buf, sizeof(buf));
        g_syscalls++;
        if (n < 0) {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            logmsg("backend %d read error: %s", be.id, strerror(errno));
            backend_mark_down(be);
            return;
        }
        if (n == 0) {
            logmsg("backend %d closed connection", be.id);
            backend_mark_down(be);
            return;
        }
        if (!backend_consume(be, buf, (size_t)n)) {
            backend_mark_down(be);
            return;
        }
    }
}

/* ---------------- stats socket ---------------- */

void handle_stats() {
    for (;;) {
        int fd = accept4(g_bal.stats_fd, nullptr, nullptr, SOCK_NONBLOCK);
        if (fd < 0) return;
        std::string out = "{\n";
        /* ~20 u64 fields at up to 20 digits each on top of ~600 bytes
         * of literal text: smaller buffers would truncate near-max
         * counters and emit unparseable stats JSON */
        char line[2048];
        snprintf(line, sizeof(line),
                 "  \"uptime_ms\": %llu,\n  \"udp_queries\": %llu,\n"
                 "  \"tcp_queries\": %llu,\n  \"drops\": %llu,\n"
                 "  \"cache_hits\": %llu,\n  \"cache_misses\": %llu,\n"
                 "  \"uncacheable\": %llu,\n  \"cache_entries\": %zu,\n"
                 "  \"cache_invalidations\": %llu,\n"
                 "  \"fwd_rtt_count\": %llu,\n"
                 "  \"fwd_rtt_sum_s\": %.6f,\n"
                 "  \"backend_wq_peak\": %zu,\n"
                 "  \"tcp_clients\": %zu,\n  \"wq_overflows\": %llu,\n"
                 "  \"idle_closes\": %llu,\n"
                 "  \"client_evictions\": %llu,\n"
                 "  \"backend_stalls\": %llu,\n"
                 "  \"direct_return\": %s,\n"
                 "  \"fd_passes\": %llu,\n"
                 "  \"direct_forwards\": %llu,\n"
                 "  \"syscalls\": %llu,\n"
                 "  \"remotes\": %zu,\n",
                 (unsigned long long)(now_ms() - g_bal.started_at),
                 (unsigned long long)g_bal.udp_queries,
                 (unsigned long long)g_bal.tcp_queries,
                 (unsigned long long)g_bal.drops,
                 (unsigned long long)g_bal.cache_hits,
                 (unsigned long long)g_bal.cache_misses,
                 (unsigned long long)g_bal.uncacheable,
                 [] { size_t n = 0;
                      for (const auto &b : g_bal.backends)
                          n += b.cache.size();
                      return n; }(),
                 (unsigned long long)g_bal.cache_invalidations,
                 (unsigned long long)g_bal.fwd_rtt_count,
                 g_bal.fwd_rtt_sum_s,
                 g_bal.backend_wq_peak,
                 g_bal.tcp_clients.size(),
                 (unsigned long long)g_bal.wq_overflows,
                 (unsigned long long)g_bal.idle_closes,
                 (unsigned long long)g_bal.client_evictions,
                 (unsigned long long)g_bal.backend_stalls,
                 g_no_direct ? "false" : "true",
                 (unsigned long long)g_bal.fd_passes,
                 (unsigned long long)g_bal.direct_forwards,
                 (unsigned long long)g_syscalls,
                 g_bal.remotes.size());
        out += line;
        /* UDP-front recvmmsg batch sizes (log2 cells: 1, 2-3, 4-7,
         * ..., >=128): mass above the first cell proves batching held */
        out += "  \"udp_batch_cells\": [";
        for (int c = 0; c < Balancer::kBatchCells; c++) {
            snprintf(line, sizeof(line), "%s%llu",
                     c == 0 ? "" : ", ",
                     (unsigned long long)g_bal.udp_batch_cells[c]);
            out += line;
        }
        out += "],\n";
        /* forward-RTT histogram: log2 µs upper bounds, open-ended last
         * cell — enough to localize a topology regression to the
         * backend round trip vs the balancer's own packet path */
        out += "  \"fwd_rtt_us_cells\": [";
        for (int c = 0; c < Balancer::kRttCells; c++) {
            snprintf(line, sizeof(line), "%s%llu",
                     c == 0 ? "" : ", ",
                     (unsigned long long)g_bal.fwd_rtt_cells[c]);
            out += line;
        }
        out += "],\n";
        /* per-stage cycle attribution (see the StageCounters comment):
         * exclusive cycles + timed-region count per stage, plus the
         * lifetime-calibrated TSC rate so consumers convert to µs */
        {
            double cal_us = (mono_s() - g_cal_mono0) * 1e6;
            double cpu = cal_us > 0.0
                ? (double)(cycles_now() - g_cal_cycles0) / cal_us : 0.0;
            snprintf(line, sizeof(line),
                     "  \"cycles_per_us\": %.1f,\n"
                     "  \"stage_cycles\": {\n"
                     "    \"frame-parse\": {\"cycles\": %llu, \"ops\": %llu},\n"
                     "    \"cache-probe\": {\"cycles\": %llu, \"ops\": %llu},\n"
                     "    \"backend-write\": {\"cycles\": %llu, \"ops\": %llu},\n"
                     "    \"reply-relay\": {\"cycles\": %llu, \"ops\": %llu}\n"
                     "  },\n",
                     cpu,
                     (unsigned long long)g_stages.frame_parse.cycles,
                     (unsigned long long)g_stages.frame_parse.ops,
                     (unsigned long long)g_stages.cache_probe.cycles,
                     (unsigned long long)g_stages.cache_probe.ops,
                     (unsigned long long)g_stages.backend_write.cycles,
                     (unsigned long long)g_stages.backend_write.ops,
                     (unsigned long long)g_stages.reply_relay.cycles,
                     (unsigned long long)g_stages.reply_relay.ops);
            out += line;
        }
        out += "  \"backends\": [\n";
        /* one pass over the affinity map (reference be_remotes), not
         * one scan per backend */
        std::vector<size_t> remote_counts(g_bal.backends.size(), 0);
        for (const auto &r : g_bal.remotes) {
            if (r.second >= 0 &&
                (size_t)r.second < remote_counts.size())
                remote_counts[r.second]++;
        }
        for (size_t i = 0; i < g_bal.backends.size(); i++) {
            const Backend &be = g_bal.backends[i];
            snprintf(line, sizeof(line),
                     "    {\"id\": %d, \"path\": \"%s\", \"healthy\": %s, "
                     "\"forwarded\": %llu, \"responded\": %llu, "
                     "\"gen_known\": %s, \"gen\": %llu, "
                     "\"wq_bytes\": %zu, \"direct\": %s, "
                     "\"remotes\": %zu}%s\n",
                     be.id, be.path.c_str(), be.healthy ? "true" : "false",
                     (unsigned long long)be.forwarded,
                     (unsigned long long)be.responded,
                     be.gen_known ? "true" : "false",
                     (unsigned long long)be.gen,
                     be.conn.wq_bytes,
                     be.fd_passed ? "true" : "false",
                     remote_counts[i],
                     i + 1 < g_bal.backends.size() ? "," : "");
            out += line;
        }
        out += "  ]\n}\n";
        (void)write(fd, out.data(), out.size());
        close(fd);
    }
}

/* ---------------- setup ---------------- */

/* Bind-address family follows -b: a ':' means IPv6 (with V6ONLY off,
 * so "::" serves both stacks — v4 clients appear as v4-mapped v6
 * addresses, which the frame protocol and backends already carry as
 * family-6). Default stays "0.0.0.0". */
/* `fatal=false` returns -1 on EADDRINUSE instead of dying — used by
 * the ephemeral pair-bind retry, where a collision on the UDP-chosen
 * port just means redraw. */
int listen_front(int socktype, const char *what, bool fatal = true) {
    bool v6 = g_bal.bind_addr.find(':') != std::string::npos;
    int fd = socket(v6 ? AF_INET6 : AF_INET, socktype | SOCK_NONBLOCK, 0);
    if (fd < 0) { perror(what); exit(1); }
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    int rc;
    if (v6) {
        int zero = 0;
        setsockopt(fd, IPPROTO_IPV6, IPV6_V6ONLY, &zero, sizeof(zero));
        struct sockaddr_in6 sin6{};
        sin6.sin6_family = AF_INET6;
        sin6.sin6_port = htons((uint16_t)g_bal.port);
        if (inet_pton(AF_INET6, g_bal.bind_addr.c_str(),
                      &sin6.sin6_addr) != 1) {
            fprintf(stderr, "mbalancer: bad bind address '%s'\n",
                    g_bal.bind_addr.c_str());
            exit(1);
        }
        rc = bind(fd, (struct sockaddr *)&sin6, sizeof(sin6));
    } else {
        struct sockaddr_in sin{};
        sin.sin_family = AF_INET;
        sin.sin_port = htons((uint16_t)g_bal.port);
        if (inet_pton(AF_INET, g_bal.bind_addr.c_str(),
                      &sin.sin_addr) != 1) {
            fprintf(stderr, "mbalancer: bad bind address '%s'\n",
                    g_bal.bind_addr.c_str());
            exit(1);
        }
        rc = bind(fd, (struct sockaddr *)&sin, sizeof(sin));
    }
    if (rc != 0) {
        if (!fatal && errno == EADDRINUSE) {
            close(fd);
            return -1;
        }
        perror(what);
        exit(1);
    }
    return fd;
}

int listen_udp() {
    return listen_front(SOCK_DGRAM, "bind udp");
}

int listen_tcp(bool fatal = true) {
    int fd = listen_front(SOCK_STREAM, "bind tcp", fatal);
    if (fd < 0)
        return -1;
    if (listen(fd, 128) != 0) {
        /* with SO_REUSEADDR a colliding port can pass bind() and fail
         * only here (peer still in its own bind->listen window): the
         * non-fatal caller's redraw loop must handle that shape too */
        if (!fatal && errno == EADDRINUSE) {
            close(fd);
            return -1;
        }
        perror("listen tcp");
        exit(1);
    }
    return fd;
}

int listen_stats() {
    std::string path = g_bal.sockdir + "/.balancer.stats";
    unlink(path.c_str());
    int fd = socket(AF_UNIX, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) { perror("socket stats"); exit(1); }
    struct sockaddr_un sun{};
    sun.sun_family = AF_UNIX;
    snprintf(sun.sun_path, sizeof(sun.sun_path), "%s", path.c_str());
    if (bind(fd, (struct sockaddr *)&sun, sizeof(sun)) != 0 ||
        listen(fd, 16) != 0) {
        perror("bind stats");
        exit(1);
    }
    return fd;
}

uint16_t local_port(int fd) {
    struct sockaddr_storage ss{};
    socklen_t slen = sizeof(ss);
    getsockname(fd, (struct sockaddr *)&ss, &slen);
    if (ss.ss_family == AF_INET6)
        return ntohs(((struct sockaddr_in6 *)&ss)->sin6_port);
    return ntohs(((struct sockaddr_in *)&ss)->sin_port);
}

void report_port() {
    /* with -p 0 (tests), report the kernel-chosen port on stdout */
    printf("PORT %d\n", local_port(g_bal.udp_fd));
    fflush(stdout);
}

}  // namespace

int main(int argc, char **argv) {
    int c;
    while ((c = getopt(argc, argv, "d:p:b:s:c:T:m:Dv")) != -1) {
        switch (c) {
        case 'd': g_bal.sockdir = optarg; break;
        case 'p': g_bal.port = atoi(optarg); break;
        case 'b': g_bal.bind_addr = optarg; break;
        case 's': g_bal.scan_ms = atoi(optarg); break;
        case 'c': g_bal.cache_ms = atoi(optarg); break;
        case 'T': g_bal.tcp_idle_ms = atoi(optarg); break;
        case 'm': g_bal.max_tcp_clients = atoi(optarg); break;
        case 'D': g_no_direct = 1; break;
        case 'v': g_verbose = 1; break;
        default:
            fprintf(stderr, "usage: mbalancer -d sockdir [-p port] "
                            "[-b bindaddr] [-s scan_ms] [-c cache_ms] "
                            "[-T tcp_idle_ms] [-m max_tcp_clients] "
                            "[-D (disable direct-return fd passing)] "
                            "[-v]\n");
            return 1;
        }
    }
    if (g_bal.sockdir.empty()) {
        fprintf(stderr, "mbalancer: -d sockdir is required\n");
        return 1;
    }
    signal(SIGPIPE, SIG_IGN);
    load_bound_overrides();
    g_bal.started_at = now_ms();
    g_cal_cycles0 = cycles_now();   /* TSC-rate calibration anchors */
    g_cal_mono0 = mono_s();

    g_bal.epfd = epoll_create1(0);
    g_bal.udp_fd = listen_udp();
    g_bal.tcp_fd = listen_tcp();
    g_bal.stats_fd = listen_stats();

    /* Both fronts bind the same port number (production :53/:53).
     * With -p 0 the kernel picks the UDP port — a number any unrelated
     * socket may already hold on TCP — so the rebind is a retry loop:
     * release the draw and redraw instead of dying (observed as a
     * transient bench startup death, "bind tcp: Address already in
     * use"; the backend's ephemeral pair bind handles the same race
     * the same way). */
    if (g_bal.port == 0) {
        close(g_bal.tcp_fd);
        for (int attempt = 0; ; attempt++) {
            g_bal.port = local_port(g_bal.udp_fd);
            g_bal.tcp_fd = listen_tcp(/*fatal=*/false);
            if (g_bal.tcp_fd >= 0)
                break;
            if (attempt >= 15) {
                fprintf(stderr,
                        "mbalancer: no bindable udp/tcp port pair\n");
                exit(1);
            }
            close(g_bal.udp_fd);
            g_bal.port = 0;
            g_bal.udp_fd = listen_udp();
        }
    }

    g_bal.timer_fd = timerfd_create(CLOCK_MONOTONIC, TFD_NONBLOCK);
    struct itimerspec its{};
    its.it_interval.tv_sec = g_bal.scan_ms / 1000;
    its.it_interval.tv_nsec = (g_bal.scan_ms % 1000) * 1000000L;
    its.it_value = its.it_interval;
    timerfd_settime(g_bal.timer_fd, 0, &its, nullptr);

    epoll_add(g_bal.udp_fd, EPOLLIN, tag(KIND_UDP, g_bal.udp_fd));
    epoll_add(g_bal.tcp_fd, EPOLLIN, tag(KIND_TCP_LISTEN, g_bal.tcp_fd));
    epoll_add(g_bal.stats_fd, EPOLLIN, tag(KIND_STATS, g_bal.stats_fd));
    epoll_add(g_bal.timer_fd, EPOLLIN, tag(KIND_TIMER, g_bal.timer_fd));

    scan_sockdir();
    report_port();
    logmsg("listening on %s:%d (udp+tcp), sockdir %s",
           g_bal.bind_addr.c_str(), g_bal.port, g_bal.sockdir.c_str());

    struct epoll_event events[64];
    for (;;) {
        int n = epoll_wait(g_bal.epfd, events, 64, -1);
        g_syscalls++;
        if (n < 0) {
            if (errno == EINTR) continue;
            perror("epoll_wait");
            return 1;
        }
        for (int i = 0; i < n; i++) {
            uint64_t t = events[i].data.u64;
            int evfd = (int)(t & 0xffffffff);
            bool closed = false;
            for (int dfd : g_deferred_close)
                if (dfd == evfd) { closed = true; break; }
            if (closed) continue;   /* stale event for a dying fd */
            Kind kind = (Kind)(t >> 32);
            int fd = (int)(t & 0xffffffff);
            switch (kind) {
            case KIND_UDP: handle_udp(); break;
            case KIND_TCP_LISTEN: handle_tcp_accept(); break;
            case KIND_TCP_CLIENT: handle_tcp_client(fd, events[i].events); break;
            case KIND_BACKEND: handle_backend(fd, events[i].events); break;
            case KIND_STATS: handle_stats(); break;
            case KIND_TIMER: {
                uint64_t expirations;
                while (read(g_bal.timer_fd, &expirations, 8) == 8) {}
                scan_sockdir();
                sweep_connections();
                break;
            }
            }
        }
        for (int dfd : g_deferred_close) close(dfd);
        g_deferred_close.clear();
    }
    return 0;
}
