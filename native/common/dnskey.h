/*
 * Shared DNS question-key builder.
 *
 * Single source of truth for the cache key used by every native answer
 * cache — the in-process fast path (native/fastio/fastpath.c) and the
 * balancer's cache (native/balancer/mbalancer.cpp) — and mirrored by the
 * Python pusher (BinderServer._fastpath_key).  The key covers exactly
 * the decoded fields a binder response depends on:
 *
 *   [0]    flags: bit0 RD, bit1 EDNS-present
 *   [1:3]  effective max UDP payload, big endian
 *   [3:5]  qtype BE
 *   [5:7]  qclass BE
 *   [7:]   lowercased qname, wire label format incl. terminating 0x00
 *
 * EDNS option bytes (cookies, padding) vary per packet and are
 * deliberately NOT keyed.  Only plain hostname-charset names take the
 * fast path; anything else — multi-question, non-QUERY opcode,
 * compression in the question, unknown additionals, trailing bytes —
 * returns 0 ("not eligible", not an error) and is handled by the full
 * resolution path, which is always correct.
 */
#ifndef BINDER_DNSKEY_H
#define BINDER_DNSKEY_H

#include <stddef.h>
#include <stdint.h>

#define DNSKEY_MAX 272            /* 7 fixed + 255 name + slack */
#define DNSKEY_CLASSIC_PAYLOAD 512 /* wire.py MAX_UDP_PAYLOAD */

/* charset a fast-path name label may use; the Python decoder replaces
 * other bytes, so only this subset round-trips identically between the
 * native and Python key builders (plain function: C++ lacks C99
 * designated array initializers) */
static inline int
dnskey_name_ok(uint8_t c)
{
    return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
           (c >= '0' && c <= '9') || c == '-' || c == '_';
}

static inline uint16_t
dnskey_rd16(const uint8_t *p)
{
    return (uint16_t)((p[0] << 8) | p[1]);
}

/*
 * Parse a query packet far enough to build its cache key.  Returns the
 * key length (>= 8) on success and fills key (>= DNSKEY_MAX bytes),
 * *qn_len_out (qname wire length incl. terminator) and *qtype_out;
 * returns 0 when the packet is not fast-path eligible.
 */
static inline size_t
dnskey_build(const uint8_t *buf, size_t len, uint8_t *key,
             size_t *qn_len_out, uint16_t *qtype_out)
{
    if (len < 12 + 1 + 4)
        return 0;
    uint16_t flags = dnskey_rd16(buf + 2);
    if (flags & 0x8000)                 /* QR: a response */
        return 0;
    if ((flags >> 11) & 0xF)            /* opcode != QUERY */
        return 0;
    if (flags & 0x0200)                 /* TC on a query: punt */
        return 0;
    uint16_t qd = dnskey_rd16(buf + 4), an = dnskey_rd16(buf + 6);
    uint16_t ns = dnskey_rd16(buf + 8), ar = dnskey_rd16(buf + 10);
    if (qd != 1 || an != 0 || ns != 0 || ar > 1)
        return 0;

    size_t off = 12;
    uint8_t *kn = key + 7;
    for (;;) {
        if (off >= len)
            return 0;
        uint8_t l = buf[off];
        if (l == 0) {
            kn[off - 12] = 0;
            off++;
            break;
        }
        if (l & 0xC0)                   /* compressed/reserved label */
            return 0;
        if (off + 1 + l > len || (off - 12) + 1 + (size_t)l > 255)
            return 0;
        kn[off - 12] = l;
        for (uint8_t i = 1; i <= l; i++) {
            uint8_t ch = buf[off + i];
            if (!dnskey_name_ok(ch))
                return 0;
            /* ASCII lowercase */
            kn[off - 12 + i] = (uint8_t)((ch >= 'A' && ch <= 'Z')
                                         ? ch + 32 : ch);
        }
        off += 1 + (size_t)l;
    }
    size_t qn_len = off - 12;           /* includes terminator */
    if (off + 4 > len)
        return 0;
    uint16_t qtype = dnskey_rd16(buf + off);
    uint16_t qclass = dnskey_rd16(buf + off + 2);
    off += 4;

    int edns = 0;
    unsigned payload = DNSKEY_CLASSIC_PAYLOAD;
    if (ar == 1) {
        /* exactly one additional, and it must be a root-name OPT that
         * ends the packet (other shapes go to the full path) */
        if (off + 11 > len)
            return 0;
        if (buf[off] != 0)
            return 0;
        uint16_t rtype = dnskey_rd16(buf + off + 1);
        if (rtype != 41)                /* not OPT (e.g. TSIG) */
            return 0;
        uint16_t rclass = dnskey_rd16(buf + off + 3);
        uint16_t rdlen = dnskey_rd16(buf + off + 9);
        if (off + 11 + (size_t)rdlen != len)
            return 0;
        edns = 1;
        /* wire.py Message.max_udp_payload: >=512 → min(size, 4096),
         * else classic 512 */
        payload = rclass >= 512 ? (rclass > 4096 ? 4096 : rclass)
                                : DNSKEY_CLASSIC_PAYLOAD;
    } else if (off != len) {
        return 0;                       /* trailing bytes: punt */
    }

    key[0] = (uint8_t)(((flags & 0x0100) ? 1 : 0) | (edns ? 2 : 0));
    key[1] = (uint8_t)(payload >> 8);
    key[2] = (uint8_t)(payload & 0xFF);
    key[3] = (uint8_t)(qtype >> 8);
    key[4] = (uint8_t)(qtype & 0xFF);
    key[5] = (uint8_t)(qclass >> 8);
    key[6] = (uint8_t)(qclass & 0xFF);
    *qn_len_out = qn_len;
    *qtype_out = qtype;
    return 7 + qn_len;
}

#endif /* BINDER_DNSKEY_H */
