/*
 * Coverage hook for the coverage-guided fuzz builds (fuzz_*_cov).
 *
 * clang/libFuzzer is not in this image; gcc still emits a call to
 * __sanitizer_cov_trace_pc in every basic block under
 * -fsanitize-coverage=trace-pc.  This TU supplies that callback —
 * compiled WITHOUT the coverage flag (see native/Makefile), or the
 * callback would instrument itself into infinite recursion — and folds
 * the return address into an AFL-style edge map: prev-location XOR
 * current-location, bucketed hit counts.  fuzz_util.h detects the hook
 * through weak symbols and switches fuzz::run into the
 * coverage-guided loop (keep inputs that light new map cells, write
 * them back to the corpus dir).
 */
#include <stdint.h>
#include <string.h>

extern "C" {

int fuzz_cov_available = 1;

enum { FUZZ_COV_MAP_SIZE = 1 << 16 };
static uint8_t cov_map[FUZZ_COV_MAP_SIZE];
static uintptr_t cov_prev;
static int cov_on;

uint8_t *fuzz_cov_map = cov_map;
/* non-const: a namespace-scope const would get internal linkage and
 * leave the weak extern in fuzz_util.h dangling */
unsigned fuzz_cov_map_size = FUZZ_COV_MAP_SIZE;

/* Collection is gated: the fuzz driver (mutate/scan/save loop) lives in
 * the instrumented TU too, so with the gate open its own edges would
 * occupy map cells — and the novelty scan would mutate the map while
 * reading it — letting harness-only behavior count as "fresh" target
 * coverage and persist junk corpus entries.  cov_run_one opens the
 * gate only around the fuzz_one call. */
void
fuzz_cov_collect(int on)
{
    cov_on = on;
}

void
fuzz_cov_reset(void)
{
    memset(cov_map, 0, sizeof(cov_map));
    cov_prev = 0;
}

void
__sanitizer_cov_trace_pc(void)
{
    if (!cov_on)
        return;
    /* PCs are rebased against the first call site so the map is stable
     * across runs despite ASLR — otherwise every run would "discover"
     * the whole corpus again and re-save near-duplicates forever */
    static uintptr_t base;
    uintptr_t pc = (uintptr_t)__builtin_return_address(0);
    if (base == 0)
        base = pc;
    uintptr_t off = pc - base;
    uintptr_t cur = (off >> 4) ^ (off << 9);
    uint8_t *cell = &cov_map[(cur ^ cov_prev) & (FUZZ_COV_MAP_SIZE - 1)];
    /* saturate: a wrapping counter reads 256 hits as 0 (coverage lost)
     * and aliases hot edges into low buckets run-to-run (spurious
     * novelty — corpus bloat) */
    if (*cell != 0xFF)
        (*cell)++;
    cov_prev = cur >> 1;
}

}  /* extern "C" */
