/*
 * Deterministic mutational fuzz driver (shared by the fuzz_* targets).
 *
 * clang/libFuzzer is not in this image, so this is a self-contained
 * substitute: load a seed corpus, then for N iterations pick a seed,
 * apply a random stack of structure-blind mutations (bit flips, byte
 * sets, truncations, extensions, splices, interesting values), and hand
 * the result to the target's fuzz_one().  The PRNG is seeded from argv
 * (default 1), so every run is reproducible; build with
 * -fsanitize=address,undefined so any memory/UB finding aborts loudly.
 *
 * Usage: fuzz_<target> <corpus_dir> [iterations] [seed]
 */
#ifndef BINDER_FUZZ_UTIL_H
#define BINDER_FUZZ_UTIL_H

#include <dirent.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <string>
#include <vector>

/* target-provided; must tolerate arbitrary bytes without crashing */
void fuzz_one(const uint8_t *data, size_t len);
/* optional per-target setup before the loop */
void fuzz_setup();

namespace fuzz {

struct Rng {
    uint64_t s;
    explicit Rng(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ULL) {}
    uint64_t next() {
        s ^= s << 13;
        s ^= s >> 7;
        s ^= s << 17;
        return s;
    }
    uint32_t below(uint32_t n) { return n ? (uint32_t)(next() % n) : 0; }
};

using Corpus = std::vector<std::vector<uint8_t>>;

inline Corpus load_corpus(const char *dir) {
    Corpus corpus;
    DIR *d = opendir(dir);
    if (d == nullptr) {
        fprintf(stderr, "fuzz: cannot open corpus dir %s\n", dir);
        exit(2);
    }
    struct dirent *de;
    while ((de = readdir(d)) != nullptr) {
        if (de->d_name[0] == '.') continue;
        std::string path = std::string(dir) + "/" + de->d_name;
        FILE *fp = fopen(path.c_str(), "rb");
        if (fp == nullptr) continue;
        std::vector<uint8_t> buf;
        uint8_t tmp[4096];
        size_t n;
        while ((n = fread(tmp, 1, sizeof(tmp), fp)) > 0)
            buf.insert(buf.end(), tmp, tmp + n);
        fclose(fp);
        corpus.push_back(std::move(buf));
    }
    closedir(d);
    if (corpus.empty()) {
        fprintf(stderr, "fuzz: empty corpus in %s\n", dir);
        exit(2);
    }
    return corpus;
}

inline void mutate(std::vector<uint8_t> &b, Rng &rng, const Corpus &corpus) {
    static const uint8_t interesting[] = {0x00, 0x01, 0x7f, 0x80, 0xc0,
                                          0xff, 0x29, 0x35};
    int ops = 1 + (int)rng.below(8);
    for (int i = 0; i < ops; i++) {
        switch (rng.below(7)) {
        case 0:   /* bit flip */
            if (!b.empty())
                b[rng.below((uint32_t)b.size())] ^=
                    (uint8_t)(1u << rng.below(8));
            break;
        case 1:   /* set byte to interesting value */
            if (!b.empty())
                b[rng.below((uint32_t)b.size())] =
                    interesting[rng.below(sizeof(interesting))];
            break;
        case 2:   /* random byte */
            if (!b.empty())
                b[rng.below((uint32_t)b.size())] = (uint8_t)rng.next();
            break;
        case 3:   /* truncate */
            if (!b.empty())
                b.resize(rng.below((uint32_t)b.size() + 1));
            break;
        case 4: { /* extend with random bytes */
            uint32_t n = 1 + rng.below(32);
            for (uint32_t k = 0; k < n; k++)
                b.push_back((uint8_t)rng.next());
            break;
        }
        case 5: { /* splice a chunk of another corpus entry */
            const auto &other = corpus[rng.below((uint32_t)corpus.size())];
            if (other.empty()) break;
            uint32_t from = rng.below((uint32_t)other.size());
            uint32_t n = 1 + rng.below((uint32_t)(other.size() - from));
            uint32_t at = b.empty() ? 0 : rng.below((uint32_t)b.size());
            b.insert(b.begin() + at, other.begin() + from,
                     other.begin() + from + n);
            break;
        }
        case 6: { /* overwrite a 2-byte BE length-looking field */
            if (b.size() < 2) break;
            uint32_t at = rng.below((uint32_t)b.size() - 1);
            uint16_t v = (uint16_t)rng.next();
            b[at] = (uint8_t)(v >> 8);
            b[at + 1] = (uint8_t)v;
            break;
        }
        }
        if (b.size() > 70000) b.resize(70000);   /* frame-ish ceiling */
    }
}

/* ---- coverage-guided mode (fuzz_*_cov builds) ----
 *
 * When covhook.cpp is linked and the target is compiled with
 * -fsanitize-coverage=trace-pc, these weak symbols resolve and run()
 * switches to a coverage-guided loop: execute, diff the edge map
 * against the accumulated "virgin" map (AFL-style bucketed hit
 * counts), keep inputs that light new cells, and write them back to
 * the corpus dir for the mutational smoke and future cov runs to seed
 * from.  Without the hook (plain fuzz_* builds) the weak symbols are
 * null and the original deterministic mutational loop runs.
 */
extern "C" int fuzz_cov_available __attribute__((weak));
extern "C" uint8_t *fuzz_cov_map __attribute__((weak));
extern "C" unsigned fuzz_cov_map_size __attribute__((weak));
extern "C" void fuzz_cov_reset(void) __attribute__((weak));
extern "C" void fuzz_cov_collect(int on) __attribute__((weak));

/* AFL hit-count bucketing: 1, 2, 3, 4-7, 8-15, 16-31, 32-127, 128+ */
inline uint8_t cov_bucket(uint8_t n) {
    if (n == 0) return 0;
    if (n == 1) return 1;
    if (n == 2) return 2;
    if (n == 3) return 4;
    if (n < 8)  return 8;
    if (n < 16) return 16;
    if (n < 32) return 32;
    if (n < 128) return 64;
    return 128;
}

/* run one input under the map; OR newly-bucketed cells into `virgin`;
 * returns 1 when the input produced a bucket bit not seen before */
inline int cov_run_one(const uint8_t *data, size_t len,
                       std::vector<uint8_t> &virgin) {
    fuzz_cov_reset();
    fuzz_cov_collect(1);        /* only the target run is measured —
                                 * harness edges must not count */
    fuzz_one(data, len);
    fuzz_cov_collect(0);
    int fresh = 0;
    for (unsigned i = 0; i < fuzz_cov_map_size; i++) {
        uint8_t b = cov_bucket(fuzz_cov_map[i]);
        if (b & ~virgin[i]) {
            virgin[i] |= b;
            fresh = 1;
        }
    }
    return fresh;
}

inline void cov_save(const char *dir, const std::vector<uint8_t> &b) {
    uint64_t h = 1469598103934665603ull;
    for (uint8_t c : b) {
        h ^= c;
        h *= 1099511628211ull;
    }
    char path[4096];
    snprintf(path, sizeof(path), "%s/cov-%016llx", dir,
             (unsigned long long)h);
    FILE *fp = fopen(path, "wb");
    if (fp == nullptr) return;
    fwrite(b.data(), 1, b.size(), fp);
    fclose(fp);
}

inline int run_cov(const char *dir, long iters, uint64_t seed,
                   const char *argv0) {
    Corpus corpus = load_corpus(dir);
    Rng rng(seed);
    fuzz_setup();
    std::vector<uint8_t> virgin(fuzz_cov_map_size, 0);
    /* Stateful targets make some coverage order-dependent, so every
     * run finds a few "new" cells; persisting those would grow the
     * checked-in corpus on every CI smoke.  FUZZ_COV_NO_SAVE=1 (the
     * smoke) keeps finds in memory only; `make fuzz` persists. */
    const int save = getenv("FUZZ_COV_NO_SAVE") == nullptr;

    /* seeds first: they define the baseline coverage (and must never
     * regress) */
    for (const auto &c : corpus)
        (void)cov_run_one(c.data(), c.size(), virgin);

    long saved = 0;
    std::vector<uint8_t> buf;
    for (long i = 0; i < iters; i++) {
        /* bias toward recent finds: they sit on fresh edges */
        uint32_t n = (uint32_t)corpus.size();
        uint32_t idx = (rng.below(4) == 0 && n > 4)
            ? n - 1 - rng.below(n / 4) : rng.below(n);
        buf = corpus[idx];
        mutate(buf, rng, corpus);
        if (cov_run_one(buf.data(), buf.size(), virgin)) {
            if (save)
                cov_save(dir, buf);
            corpus.push_back(buf);
            saved++;
        }
    }
    unsigned lit = 0;
    for (uint8_t v : virgin)
        if (v) lit++;
    fprintf(stderr,
            "fuzz: %s: %ld coverage-guided execs ok (seed %llu, corpus "
            "%zu, +%ld new inputs, %u/%u map cells)\n",
            argv0, iters, (unsigned long long)seed, corpus.size(), saved,
            lit, fuzz_cov_map_size);
    return 0;
}

inline int run(int argc, char **argv) {
    if (argc < 2) {
        fprintf(stderr, "usage: %s <corpus_dir> [iterations] [seed]\n",
                argv[0]);
        return 2;
    }
    long iters = argc > 2 ? atol(argv[2]) : 50000;
    uint64_t seed = argc > 3 ? strtoull(argv[3], nullptr, 0) : 1;
    if (&fuzz_cov_available != nullptr && fuzz_cov_reset != nullptr)
        return run_cov(argv[1], iters, seed, argv[0]);
    Corpus corpus = load_corpus(argv[1]);
    Rng rng(seed);
    fuzz_setup();

    /* every seed verbatim first: the corpus must never regress */
    for (const auto &c : corpus)
        fuzz_one(c.data(), c.size());

    std::vector<uint8_t> buf;
    for (long i = 0; i < iters; i++) {
        buf = corpus[rng.below((uint32_t)corpus.size())];
        mutate(buf, rng, corpus);
        fuzz_one(buf.data(), buf.size());
    }
    fprintf(stderr, "fuzz: %s: %ld iterations ok (seed %llu, corpus %zu)\n",
            argv[0], iters, (unsigned long long)seed, corpus.size());
    return 0;
}

}  // namespace fuzz

#endif /* BINDER_FUZZ_UTIL_H */
