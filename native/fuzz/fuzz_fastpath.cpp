/*
 * Fuzz target: the fastio answer-cache core (native/fastio/fpcore.h) —
 * the exact fill/serve/rotation code fastpath_drain and fastpath_put run
 * (VERDICT r2 weak 2: this path previously had pytest cases only, while
 * fuzz_frames covered the balancer's separate copy of the fill path).
 *
 * Two modes per input, mirroring fuzz_frames' raw/wrapped split:
 *  - serve-raw: the bytes are a client packet, exercising the wire
 *    parser (dnskey_build), lookup, and lazy gen/TTL invalidation;
 *  - fill+serve: the bytes steer a synthesized valid query (name
 *    length/charset, qtype) and the variant set (count, sizes,
 *    deliberately-short wires for the defensive path), which is inserted
 *    with fp_put_raw and immediately served back — round-trip asserts
 *    check id/0x20 patching and variant rotation.
 *
 * Cross-iteration state persists (one cache for the whole run) with a
 * deliberately small table, so probe-window eviction, replace-in-place,
 * expiry, generation bumps, and clear all fire; accounting invariants
 * are re-verified every 64 iterations.
 */
#include <assert.h>

#include "../fastio/fpcore.h"
#include "fuzz_util.h"

namespace {

fp_cache_t *fz_c = nullptr;
uint64_t fz_iter = 0;
uint64_t fz_gen = 1;
double fz_clock = 1000.0;

/* tag used by zone-mode iterations to exercise the scan path; shared so
 * other modes can clear those entries before asserting a miss */
const uint8_t fz_alien_tag[5] = {3, 'z', 'z', 'z', 0};

/* build a well-formed query: header + one question, hostname-charset
 * name derived from the input bytes */
size_t build_query(const uint8_t *data, size_t len, uint8_t *q /*512*/) {
    size_t pos = 0;
    q[pos++] = len > 0 ? data[0] : 0x12;          /* id */
    q[pos++] = len > 1 ? data[1] : 0x34;
    q[pos++] = 0x01;                              /* RD */
    q[pos++] = 0x00;
    q[pos++] = 0x00; q[pos++] = 0x01;             /* qdcount 1 */
    for (int i = 0; i < 6; i++) q[pos++] = 0x00;
    /* 1-3 labels, 1-14 chars each, derived from input */
    int n_labels = 1 + (len > 2 ? data[2] % 3 : 1);
    size_t di = 3;
    for (int l = 0; l < n_labels; l++) {
        int ll = 1 + (di < len ? data[di++] % 14 : 4);
        q[pos++] = (uint8_t)ll;
        for (int k = 0; k < ll; k++) {
            uint8_t b = di < len ? data[di++] : (uint8_t)(k + l);
            q[pos++] = (uint8_t)('a' + (b % 26));
        }
    }
    q[pos++] = 0x00;                              /* root */
    uint16_t qtype = (uint16_t)(1 + (len > 4 ? data[4] % 34 : 0));
    q[pos++] = (uint8_t)(qtype >> 8);
    q[pos++] = (uint8_t)(qtype & 0xff);
    q[pos++] = 0x00; q[pos++] = 0x01;             /* IN */
    return pos;
}

}  // namespace

void fuzz_setup() {
    fz_c = (fp_cache_t *)calloc(1, sizeof(*fz_c));
    assert(fz_c != nullptr);
    /* small table: with mutated names the probe window fills and the
     * evict-oldest path runs constantly */
    int rc = fp_core_init(fz_c, 64, 60000);
    assert(rc == 0);
}

void fuzz_one(const uint8_t *data, size_t len) {
    fz_iter++;
    fz_clock += 0.001;
    if (fz_iter % 97 == 0)
        fz_gen++;                       /* gen-mismatch invalidation */
    if (fz_iter % 53 == 0)
        fz_clock += 120.0;              /* TTL expiry (cache-wide 60s) */

    /* alternate the query-log ring on and off (small capacity so the
     * backpressure decline path fires); periodically "drain" it the
     * way the Python side does */
    if (fz_iter % 29 == 0) {
        if (fz_c->lr.enabled) {
            fp_log_disable(fz_c);
        } else {
            static const uint8_t pfx[] =
                "{\"name\":\"binder\",\"msg\":\"DNS query\",\"time\":\"";
            int lrc = fp_log_enable(fz_c, pfx, sizeof(pfx) - 1, 4096);
            assert(lrc == 0);
        }
    }
    if (fz_c->lr.enabled && fz_iter % 13 == 0)
        fz_c->lr.len = 0;               /* drained by Python */

    uint8_t out[FP_MAX_WIRE];
    /* zero-length inputs arrive with data == nullptr: every direct
     * data[0] read below must go through this guarded copy (a seed-6
     * coverage soak minted an empty corpus entry and UBSan flagged the
     * null load) */
    const uint8_t d0 = len > 0 ? data[0] : 0;

    if (fz_iter % 3 == 0) {
        /* raw client bytes straight into the serve path (cache AND
         * zone lookup paths, via fp_serve_one's miss fall-through) */
        (void)fp_serve_one(fz_c, data, len, fz_gen, fz_clock, out,
                           nullptr);
    } else if (fz_iter % 3 == 2) {
        /* zone put + serve round trip: synthesized query, precompiled
         * body, assert the assembled response */
        uint8_t q[512];
        size_t qlen = build_query(data, len, q);
        uint8_t key[FP_MAX_KEY];
        size_t qn_len = 0;
        uint16_t qtype = 0;
        size_t klen = dnskey_build(q, qlen, key, &qn_len, &qtype);
        assert(klen > 0 && klen <= FP_MAX_KEY);

        const uint8_t *tag = key + 7;     /* qname wire */
        size_t taglen = klen - 7;
        /* clear both layers for this name first, so the serve below is
         * provably a zone serve (a fill-mode cache entry for the same
         * name would otherwise shadow it) */
        (void)fp_invalidate_tag(fz_c, tag, taglen);

        int nv = 1 + (int)(len > 5 ? data[5] % FP_MAX_VARIANTS : 0);
        uint16_t ancount = (uint16_t)(1 + (len > 6 ? data[6] % 3 : 0));
        static uint8_t body_store[FP_MAX_VARIANTS][FP_MAX_WIRE];
        const uint8_t *bodies[FP_MAX_VARIANTS];
        uint16_t blens[FP_MAX_VARIANTS];
        for (int i = 0; i < nv; i++) {
            size_t bl = 1 + (len > (size_t)(7 + i)
                             ? data[7 + i] * 9u : 16u);
            if (bl > FP_MAX_WIRE) bl = FP_MAX_WIRE;
            for (size_t b = 0; b < bl; b++)
                body_store[i][b] = (uint8_t)(b * 17 + d0 + i);
            bodies[i] = body_store[i];
            blens[i] = (uint16_t)bl;
        }
        /* occasionally use an alien tag (routes to the scanned zalien
         * table), and sometimes declare trailing additionals (SRV) */
        int alien = (len > 3 && data[3] % 7 == 0);
        uint16_t arcount = (uint16_t)(len > 4 && data[4] % 3 == 0
                                      ? 1 + data[4] % 2 : 0);
        /* in ring-on iterations, push per-variant log fragments and
         * serve with a source context — exercising fp_log_append's
         * formatting and the room-decline backpressure path */
        static const uint8_t zfrag[] = "\"rcode\":\"NOERROR\",\"z\":1";
        const uint8_t *zfrags[FP_MAX_VARIANTS];
        uint16_t zflens[FP_MAX_VARIANTS];
        for (int i = 0; i < nv; i++) {
            zfrags[i] = zfrag;
            zflens[i] = (uint16_t)(sizeof(zfrag) - 1);
        }
        int ring = fz_c->lr.enabled;
        int rc = fp_zone_put(fz_c, key + 3, klen - 3, fz_gen, ancount,
                             arcount, bodies, blens, nv,
                             alien ? fz_alien_tag : tag,
                             alien ? sizeof(fz_alien_tag) : taglen,
                             ring ? zfrags : nullptr,
                             ring ? zflens : nullptr);
        assert(rc >= 0);

        if (rc == 1) {
            uint16_t got_qtype = 0;
            fp_logsrc_t zsrc = { "192.0.2.7", 5353, "udp" };
            uint64_t lines_before = fz_c->lr.lines;
            int had_room = !ring
                || fp_log_room(fz_c, sizeof(zfrag) - 1);
            size_t wlen = fp_serve_one_lx(fz_c, q, qlen, fz_gen,
                                          fz_clock, out, &got_qtype, 0,
                                          ring ? &zsrc : nullptr);
            if (ring && wlen > 0)
                assert(fz_c->lr.lines == lines_before + 1);
            size_t want = 12 + qn_len + 4 + blens[0];
            if (want > DNSKEY_CLASSIC_PAYLOAD) {
                /* would truncate: must decline to the slow path */
                assert(wlen == 0);
            } else if (!had_room) {
                /* ring backpressure: must decline, never serve-and-
                 * drop the log line */
                assert(wlen == 0);
            } else {
                assert(wlen == want);
                assert(out[0] == q[0] && out[1] == q[1]);
                assert(out[2] == 0x85);   /* QR|AA + RD echo (rd set) */
                assert(out[3] == 0x00);
                assert(dnskey_rd16(out + 6) == ancount);
                /* no EDNS on the query: ar == declared additionals */
                assert(dnskey_rd16(out + 10) == arcount);
                assert(memcmp(out + 12, q + 12, qn_len + 4) == 0);
                assert(memcmp(out + 12 + qn_len + 4, bodies[0],
                              blens[0]) == 0);
                assert(got_qtype == qtype);
            }
            /* usually KEEP the entry so the tables fill and the grow/
             * rehash path runs; every 4th, prove tag invalidation
             * drops it through whichever path applies (O(1) key drop
             * on zmain, the bounded scan on zalien) */
            if (len > 2 && data[2] % 4 == 0) {
                uint32_t dropped = fp_invalidate_tag(
                    fz_c, alien ? fz_alien_tag : tag,
                    alien ? sizeof(fz_alien_tag) : taglen);
                assert(dropped >= 1);
                assert(fp_ztab_find(&fz_c->zmain, key + 3,
                                    klen - 3) == nullptr);
                assert(fp_ztab_find(&fz_c->zalien, key + 3,
                                    klen - 3) == nullptr);
            }
        }
    } else {
        uint8_t q[512];
        size_t qlen = build_query(data, len, q);
        uint8_t key[FP_MAX_KEY];
        size_t qn_len = 0;
        uint16_t qtype = 0;
        size_t klen = dnskey_build(q, qlen, key, &qn_len, &qtype);
        assert(klen > 0 && klen <= FP_MAX_KEY);   /* we built it valid */

        /* synthesize 1..FP_MAX_VARIANTS response wires; variant 0 always
         * embeds the question (the normal shape), later variants may be
         * deliberately short to drive the defensive serve path */
        int nw = 1 + (int)(len > 5 ? data[5] % FP_MAX_VARIANTS : 0);
        static uint8_t wire_store[FP_MAX_VARIANTS][FP_MAX_WIRE];
        const uint8_t *wires[FP_MAX_VARIANTS];
        uint16_t lens[FP_MAX_VARIANTS];
        for (int i = 0; i < nw; i++) {
            uint8_t *w = wire_store[i];
            size_t base = 12 + qn_len + 4;
            size_t extra = (len > (size_t)(6 + i))
                ? data[6 + i] * 7u : 0;
            size_t wl = base + extra;
            if (wl > FP_MAX_WIRE) wl = FP_MAX_WIRE;
            if (i > 0 && (d0 + i) % 5 == 0)
                wl = 12 + (size_t)(d0 % (qn_len + 4));  /* short */
            memcpy(w, q, 12);
            w[2] |= 0x80;               /* QR */
            if (wl >= base)
                memcpy(w + 12, q + 12, qn_len + 4);
            for (size_t b = (wl >= base ? base : 12); b < wl; b++)
                w[b] = (uint8_t)(b * 31 + d0);
            wires[i] = w;
            lens[i] = (uint16_t)wl;
        }

        /* tag = the query's own qname wire (what the Python pusher does
         * for host answers); qname starts at key offset 7 */
        const uint8_t *tag = key + 7;
        size_t taglen = klen - 7;
        static const uint8_t cfrag[] =
            "\"cached\":true,\"rcode\":\"NOERROR\"";
        const uint8_t *cfrags[FP_MAX_VARIANTS];
        uint16_t cflens[FP_MAX_VARIANTS];
        for (int i = 0; i < nw; i++) {
            cfrags[i] = cfrag;
            cflens[i] = (uint16_t)(sizeof(cfrag) - 1);
        }
        int ring = fz_c->lr.enabled;
        int rc = fp_put_raw(fz_c, key, klen, qtype, fz_gen, wires, lens,
                            nw, fz_clock, fz_c->expiry_s, tag, taglen,
                            ring ? cfrags : nullptr,
                            ring ? cflens : nullptr);
        assert(rc >= 0);                /* OOM is the only -1 */

        if (rc == 1 && fz_iter % 31 == 0) {
            /* tag invalidation: the entry just stored must be dropped
             * and the following serve must miss.  Zone-mode iterations
             * leave persistent entries — qname-tagged ones fall to the
             * same invalidation, but alien-tagged ones for this name
             * survive it by design, so clear those first or the serve
             * below would (correctly) answer from the zone */
            uint32_t dropped = fp_invalidate_tag(fz_c, tag, taglen);
            assert(dropped >= 1);
            (void)fp_invalidate_tag(fz_c, fz_alien_tag,
                                    sizeof(fz_alien_tag));
            assert(fp_serve_one(fz_c, q, qlen, fz_gen, fz_clock, out,
                                nullptr) == 0);
            rc = 0;                     /* skip the hit asserts below */
        }

        if (rc == 1) {
            /* round-trip: serving the same query must hit variant 0 and
             * patch the id + question bytes back in */
            uint16_t got_qtype = 0;
            fp_logsrc_t csrc = { "2001:db8::1", 65535, "udp" };
            int had_room = !ring
                || fp_log_room(fz_c, sizeof(cfrag) - 1);
            size_t wlen = fp_serve_one_lx(fz_c, q, qlen, fz_gen,
                                          fz_clock, out, &got_qtype, 0,
                                          ring ? &csrc : nullptr);
            if (ring && !had_room) {
                assert(wlen == 0);      /* backpressure decline */
            } else {
                assert(wlen > 0);
                assert(wlen == lens[0]);
                assert(out[0] == q[0] && out[1] == q[1]);
                assert(memcmp(out + 12, q + 12, qn_len + 4) == 0);
                assert(got_qtype == qtype);
            }
            /* second serve rotates to variant 1 (or back to 0) — a
             * short variant must be dropped defensively, never served.
             * (ring-on with a NULL source must decline outright) */
            size_t w2 = fp_serve_one(fz_c, q, qlen, fz_gen, fz_clock,
                                     out, nullptr);
            if (ring)
                assert(w2 == 0);
            else if (w2 != 0)
                assert(w2 >= 12 + qn_len + 4);
        }
    }

    if (fz_iter % 211 == 0)
        fp_core_clear(fz_c);

    /* accounting invariants must hold whatever the inputs were */
    if (fz_iter % 64 == 0) {
        uint64_t bytes = 0;
        uint32_t used = 0;
        for (uint32_t i = 0; i <= fz_c->mask; i++) {
            const fp_entry_t *e = &fz_c->slots[i];
            if (!e->used) {
                assert(e->n_variants == 0);
                continue;
            }
            used++;
            assert(e->n_variants >= 1);
            for (int j = 0; j < e->n_variants; j++) {
                bytes += e->wire_lens[j];
                if (e->frags[j] != nullptr)
                    bytes += e->frag_lens[j];
            }
        }
        assert(bytes == fz_c->total_bytes);
        assert(used == fz_c->n_entries);
        assert(fz_c->hits <= fz_c->lookups);
        assert(fz_c->total_bytes <= FP_MAX_TOTAL_BYTES);
        uint64_t zbytes = 0;
        for (fp_ztab_t *t : {&fz_c->zmain, &fz_c->zalien}) {
            if (t->slots == nullptr) {
                assert(t->n == 0);
                continue;
            }
            uint32_t zused = 0;
            for (uint32_t i = 0; i <= t->mask; i++) {
                const fp_zentry_t *e = &t->slots[i];
                if (!e->used) {
                    assert(e->n_variants == 0);
                    continue;
                }
                zused++;
                assert(e->n_variants >= 1);
                for (int j = 0; j < e->n_variants; j++) {
                    zbytes += e->body_lens[j];
                    if (e->frags[j] != nullptr)
                        zbytes += e->frag_lens[j];
                }
                /* every live entry must stay findable within the probe
                 * window — one displaced past it (e.g. by a rehash)
                 * would evade per-name invalidation and could serve
                 * stale answers after a later rehash */
                assert(fp_ztab_find(t, e->key, e->keylen) ==
                       (fp_zentry_t *)e);
            }
            assert(zused == t->n);
        }
        assert(zbytes == fz_c->ztotal_bytes);
        assert(fz_c->ztotal_bytes <= FP_ZONE_MAX_BYTES);
    }
}

int main(int argc, char **argv) { return fuzz::run(argc, argv); }
