/*
 * Fuzz target: the balancer's backend frame parser and the answer-cache
 * fill path behind it (backend_consume -> maybe_cache_fill ->
 * response_matches_key -> backend_cache_insert -> route_response).
 *
 * Includes mbalancer.cpp directly (its internals live in an anonymous
 * namespace) with main() renamed away.  Two modes per input:
 *  - raw: the bytes are the stream, exercising framing/resync;
 *  - wrapped: the bytes become the payload of a well-formed data frame
 *    addressed at a planted pending-fill slot, exercising the response
 *    matcher and cache insert deep paths.
 */
#define main mbalancer_main_unused
#include "../balancer/mbalancer.cpp"
#undef main

#include <assert.h>

#include "fuzz_util.h"

namespace {

Backend *fz_be = nullptr;
uint64_t fz_iter = 0;

/* a canned well-formed query for planting pending fills */
const uint8_t kQuery[] = {
    0x12, 0x34, 0x01, 0x00,              /* id, RD query */
    0x00, 0x01, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x03, 'w', 'e', 'b', 0x03, 'f', 'o', 'o', 0x03, 'c', 'o', 'm', 0x00,
    0x00, 0x01, 0x00, 0x01,              /* A IN */
};

void plant_pending(const ClientKey &ck, uint16_t qid) {
    uint8_t key[DNSKEY_MAX];
    size_t qn_len = 0;
    uint16_t qtype = 0;
    size_t klen = dnskey_build(kQuery, sizeof(kQuery), key, &qn_len,
                               &qtype);
    assert(klen > 0 && klen <= DNSKEY_MAX);
    PendingFill &pf = g_pending_fill[pending_slot(ck, qid)];
    pf.client = ck;
    pf.qid = qid;
    pf.keylen = (uint16_t)klen;
    pf.backend_id = fz_be->id;
    pf.epoch = fz_be->epoch;
    pf.used = true;
    memcpy(pf.key, key, klen);
}

}  // namespace

void fuzz_setup() {
    /* logmsg() fires per protocol error — i.e. on most mutated inputs;
     * success is the exit code.  FUZZ_KEEP_STDERR=1 keeps the stream
     * for debugging a silent nonzero exit (sanitizer reports and
     * fail-fast messages land here too). */
    int devnull = getenv("FUZZ_KEEP_STDERR") != nullptr
        ? -1 : open("/dev/null", O_WRONLY);
    if (devnull >= 0) {
        dup2(devnull, 2);
        close(devnull);
    }
    g_bal.cache_ms = 60000;            /* enable the cache fill path */
    g_bal.udp_fd = -1;                 /* sends fail fast (EBADF) */
    Backend be;
    be.id = 0;
    be.path = "/nonexistent/fuzz";
    be.conn.fd = -1;
    be.healthy = true;
    g_bal.backends.push_back(std::move(be));
    fz_be = &g_bal.backends[0];
}

void fuzz_one(const uint8_t *data, size_t len) {
    fz_iter++;
    Backend &be = *fz_be;

    /* periodically refresh generation state so both the gen-known and
     * gen-unknown fill paths run */
    if (fz_iter % 3 == 0) {
        be.gen = fz_iter;
        be.gen_known = true;
    } else if (fz_iter % 7 == 0) {
        be.gen_known = false;
    }

    if (fz_iter % 2 == 0) {
        /* raw stream bytes */
        (void)backend_consume(be, data, len);
    } else {
        /* wrap as a valid data frame addressed at a planted pending
         * fill: version 1, family 4, transport UDP, addr+port, payload */
        ClientKey ck{};
        ck.family = 4;
        ck.addr[0] = 127; ck.addr[3] = 1;
        ck.port = 5353;
        uint16_t qid = len >= 2 ? dnskey_rd16(data) : 0;
        plant_pending(ck, qid);

        size_t plen = len > kMaxFrame - kFrameHdr
            ? kMaxFrame - kFrameHdr : len;
        std::vector<uint8_t> frame(4 + kFrameHdr + plen);
        uint32_t L = htonl((uint32_t)(kFrameHdr + plen));
        memcpy(frame.data(), &L, 4);
        frame[4] = kProtoVersion;
        frame[5] = 4;                      /* family */
        frame[6] = kTransportUdp;
        memcpy(frame.data() + 7, ck.addr, 16);
        frame[23] = (uint8_t)(ck.port >> 8);
        frame[24] = (uint8_t)(ck.port & 0xff);
        if (plen > 0)   /* empty input: data may be null (UB in memcpy) */
            memcpy(frame.data() + 4 + kFrameHdr, data, plen);
        (void)backend_consume(be, frame.data(), frame.size());
    }

    /* keep cross-iteration state bounded so the fuzzer's memory stays
     * flat (the production caps are exercised, not relied on here) */
    if (be.conn.rbuf.size() > 4 * kMaxFrame)
        be.conn.rbuf.clear();
    if (be.cache_bytes > (8u << 20) || be.cache.size() > 10000)
        backend_cache_clear(be);
    if (fz_iter % 4096 == 0)
        for (auto &pf : g_pending_fill)
            pf = PendingFill();
    /* accounting invariants must hold whatever the input was */
    assert(g_cache_bytes >= be.cache_bytes);
    if (g_bal.backends.size() == 1)
        assert(g_cache_bytes == be.cache_bytes);
}

int main(int argc, char **argv) { return fuzz::run(argc, argv); }
