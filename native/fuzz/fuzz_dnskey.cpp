/*
 * Fuzz target: the shared DNS question-key builder (common/dnskey.h),
 * the parser every hostile UDP packet hits first on the fast path
 * (native/fastio/fastpath.c) and in the balancer cache.
 *
 * Beyond memory safety (ASan/UBSan), asserts the key-layout invariants
 * the consumers rely on: bounded key length, name length consistency,
 * lowercased charset-restricted name bytes.
 */
#include <assert.h>

#include "../common/dnskey.h"
#include "fuzz_util.h"

void fuzz_setup() {}

void fuzz_one(const uint8_t *data, size_t len) {
    uint8_t key[DNSKEY_MAX];
    /* canary beyond the documented max: the builder must never write
     * past DNSKEY_MAX even for hostile input */
    uint8_t guarded[DNSKEY_MAX + 8];
    memset(guarded, 0xA5, sizeof(guarded));
    size_t qn_len = 0;
    uint16_t qtype = 0;
    size_t klen = dnskey_build(data, len, guarded, &qn_len, &qtype);
    for (int i = 0; i < 8; i++)
        assert(guarded[DNSKEY_MAX + i] == 0xA5);
    if (klen == 0)
        return;                       /* not eligible: fine */
    assert(klen >= 8 && klen <= DNSKEY_MAX);
    assert(qn_len >= 1 && qn_len <= 256);
    assert(klen == 7 + qn_len);
    /* qname: well-formed label sequence, lowercase charset */
    const uint8_t *kn = guarded + 7;
    size_t off = 0;
    for (;;) {
        assert(off < qn_len);
        uint8_t l = kn[off];
        if (l == 0) {
            assert(off + 1 == qn_len);
            break;
        }
        assert((l & 0xC0) == 0);
        for (uint8_t i = 1; i <= l; i++) {
            uint8_t ch = kn[off + i];
            assert(dnskey_name_ok(ch));
            assert(!(ch >= 'A' && ch <= 'Z'));
        }
        off += 1 + (size_t)l;
    }
    /* determinism: same input -> same key */
    size_t qn2 = 0;
    uint16_t qt2 = 0;
    size_t k2 = dnskey_build(data, len, key, &qn2, &qt2);
    assert(k2 == klen && qn2 == qn_len && qt2 == qtype);
    assert(memcmp(key, guarded, klen) == 0);
}

int main(int argc, char **argv) { return fuzz::run(argc, argv); }
