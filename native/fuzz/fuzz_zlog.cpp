/*
 * Fuzz target: zlogcat's txnlog record walk + body decoders
 * (zklog/zlogcat.cpp do_buffer), which parse forensic files that may be
 * torn, truncated, or corrupted (the reference tool mmaps and walks
 * with hand-checked offsets, src/zklog.c:262-268 — same paranoia
 * expected here, enforced by ASan/UBSan).
 *
 * stdout is redirected to /dev/null: the decoder prints a JSON line per
 * record and the fuzzer would otherwise spend its time in write(2).
 */
#define main zlogcat_main_unused
#include "../zklog/zlogcat.cpp"
#undef main

#include <fcntl.h>
#include <unistd.h>

#include "fuzz_util.h"

void fuzz_setup() {
    /* stderr too: the decoder prints a diagnostic per bad record, which
     * is every mutated input; success is the exit code */
    int devnull = open("/dev/null", O_WRONLY);
    if (devnull >= 0) {
        dup2(devnull, 1);
        dup2(devnull, 2);
        close(devnull);
    }
}

void fuzz_one(const uint8_t *data, size_t len) {
    Filters f;
    Stats st;
    (void)do_buffer("<fuzz>", data, len, f, &st);

    /* filter paths too (time window / session / server id) */
    Filters f2;
    f2.time_from = 0;
    f2.time_to = 1;
    f2.has_session = true;
    f2.session = 0x100000042;
    Stats st2;
    (void)do_buffer("<fuzz>", data, len, f2, &st2);
}

int main(int argc, char **argv) { return fuzz::run(argc, argv); }
