/*
 * instance_adjust — idempotent reconciler for a set of binder instances.
 *
 * C++ rebuild of the reference's smf_adjust + smfx + nvlist_equal
 * (SURVEY §2.2, §3.6): bring the set of running service instances
 * "<base>-<port>" in line with a plan of N instances on consecutive ports,
 * creating/configuring/starting the missing ones and stopping/removing the
 * surplus, with configuration no-op detection so unchanged instances are
 * not restarted.
 *
 * The reference reconciles against illumos SMF (libscf).  This rebuild
 * reconciles against a portable process-supervision state directory — the
 * service-manager role the reference delegates to SMF:
 *
 *   <statedir>/<name>.props   property group {instance, socket_path, exec}
 *                             (the config PG smf_adjust writes,
 *                             src/smf_adjust.c:44,1060-1090)
 *   <statedir>/<name>.pid     supervised process id
 *   <statedir>/<name>.log     instance stdout/stderr
 *
 * Reconciliation semantics preserved from the reference:
 *  - planned set built first, existing instances walked and unwanted ones
 *    marked (smf_adjust.c:964-1015);
 *  - surplus removed via stop -> poll-until-gone -> delete
 *    (remove_instance, smf_adjust.c:189-257);
 *  - per-instance config compared order-insensitively against the current
 *    property group; identical config skips the restart entirely
 *    (nvlist_equal no-op detection, smf_adjust.c:337-455);
 *  - dead-but-registered instances are restarted (flush_status analog,
 *    smfx.c:242-336);
 *  - -w waits up to 60s for instances to come online (process alive +
 *    balancer socket present) (smf_adjust.c:457-544);
 *  - -r <cmd> runs once after changes, re-publishing metric ports (the
 *    metric-ports-updater restart, smf_adjust.c:1119-1136).
 *
 * Usage:
 *   instance_adjust -s <statedir> -b <base> -B <baseport> -i <count>
 *                   -e <exec-template> [-d <sockdir>] [-r <cmd>] [-w] [-n]
 *
 * The exec template may contain %P (port), %S (socket path), %N (name).
 * -n = dry run (print actions only).
 */
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <getopt.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <string>
#include <vector>

namespace {

constexpr int kStopWaitMs = 10000;    /* disable poll (smf_adjust.c:189) */
constexpr int kOnlineWaitMs = 60000;  /* -w bound (smf_adjust.c:457) */

struct Options {
    std::string statedir;
    std::string base = "binder";
    int baseport = 5301;
    int count = -1;
    std::string exec_template;
    std::string sockdir;
    std::string refresh_cmd;
    bool wait_online = false;
    bool dry_run = false;
};

using Props = std::map<std::string, std::string>;

void msleep(int ms) {
    struct timespec ts = {ms / 1000, (long)(ms % 1000) * 1000000L};
    while (nanosleep(&ts, &ts) != 0 && errno == EINTR) {}
}

std::string path_join(const std::string &a, const std::string &b) {
    return a + "/" + b;
}

/* ---- property-group file I/O (the SMF config PG analog) ---- */

bool read_props(const std::string &file, Props *out) {
    FILE *f = fopen(file.c_str(), "r");
    if (f == nullptr) return false;
    char line[1024];
    while (fgets(line, sizeof(line), f) != nullptr) {
        char *nl = strchr(line, '\n');
        if (nl) *nl = '\0';
        char *eq = strchr(line, '=');
        if (eq == nullptr || line[0] == '#') continue;
        *eq = '\0';
        (*out)[line] = eq + 1;
    }
    fclose(f);
    return true;
}

bool write_props(const std::string &file, const Props &props) {
    std::string tmp = file + ".tmp";
    FILE *f = fopen(tmp.c_str(), "w");
    if (f == nullptr) return false;
    for (const auto &kv : props)
        fprintf(f, "%s=%s\n", kv.first.c_str(), kv.second.c_str());
    fclose(f);
    return rename(tmp.c_str(), file.c_str()) == 0;
}

/* order-insensitive structural equality (nvlist_equal analog,
 * src/nvlist_equal.c:260-304 — two half-subset passes collapsed into the
 * std::map comparison) */
bool props_equal(const Props &a, const Props &b) {
    return a == b;
}

/* ---- process supervision ---- */

pid_t read_pid(const std::string &pidfile) {
    FILE *f = fopen(pidfile.c_str(), "r");
    if (f == nullptr) return -1;
    long pid = -1;
    if (fscanf(f, "%ld", &pid) != 1) pid = -1;
    fclose(f);
    return (pid_t)pid;
}

bool process_alive(pid_t pid) {
    if (pid <= 0) return false;
    if (kill(pid, 0) != 0 && errno != EPERM) return false;
    /* a zombie still answers kill(0); treat it as dead (orphans are not
     * reaped promptly in minimal containers) */
    char path[64], buf[512];
    snprintf(path, sizeof(path), "/proc/%d/stat", (int)pid);
    FILE *f = fopen(path, "r");
    if (f == nullptr) return false;
    size_t n = fread(buf, 1, sizeof(buf) - 1, f);
    fclose(f);
    buf[n] = '\0';
    const char *paren = strrchr(buf, ')');
    if (paren == nullptr || paren[1] == '\0') return true;
    return paren[2] != 'Z';
}

std::string substitute(const std::string &tmpl, int port,
                       const std::string &sock, const std::string &name) {
    std::string out;
    for (size_t i = 0; i < tmpl.size(); i++) {
        if (tmpl[i] == '%' && i + 1 < tmpl.size()) {
            switch (tmpl[i + 1]) {
            case 'P': out += std::to_string(port); i++; continue;
            case 'S': out += sock; i++; continue;
            case 'N': out += name; i++; continue;
            default: break;
            }
        }
        out.push_back(tmpl[i]);
    }
    return out;
}

/* ---- one instance ---- */

struct Instance {
    std::string name;
    int port = 0;
    bool planned = false;   /* in the desired set */
    bool exists = false;    /* props file present */
};

struct Reconciler {
    Options opt;
    std::vector<Instance> insts;
    bool changed = false;

    std::string props_file(const std::string &n) {
        return path_join(opt.statedir, n + ".props");
    }
    std::string pid_file(const std::string &n) {
        return path_join(opt.statedir, n + ".pid");
    }
    std::string log_file(const std::string &n) {
        return path_join(opt.statedir, n + ".log");
    }
    std::string socket_path(int port) {
        if (opt.sockdir.empty()) return "";
        return path_join(opt.sockdir, std::to_string(port));
    }

    Props desired_props(const Instance &in) {
        Props p;
        p["instance"] = std::to_string(in.port);
        std::string sock = socket_path(in.port);
        if (!sock.empty()) p["socket_path"] = sock;
        p["exec"] = substitute(opt.exec_template, in.port, sock, in.name);
        return p;
    }

    /* plan + walk (smf_adjust.c:964-1015) */
    void build_sets() {
        std::map<std::string, Instance> by_name;
        for (int i = 0; i < opt.count; i++) {
            Instance in;
            in.port = opt.baseport + i;
            in.name = opt.base + "-" + std::to_string(in.port);
            in.planned = true;
            by_name[in.name] = in;
        }
        DIR *d = opendir(opt.statedir.c_str());
        if (d != nullptr) {
            struct dirent *de;
            std::string suffix = ".props";
            while ((de = readdir(d)) != nullptr) {
                std::string fn = de->d_name;
                if (fn.size() <= suffix.size() ||
                    fn.compare(fn.size() - suffix.size(), suffix.size(),
                               suffix) != 0)
                    continue;
                std::string name = fn.substr(0, fn.size() - suffix.size());
                if (name.compare(0, opt.base.size() + 1, opt.base + "-") != 0)
                    continue;   /* not ours */
                /* the suffix must be a bare port number, or another
                 * instance set sharing a prefix (binder vs binder-blue)
                 * would be claimed and torn down */
                std::string tail = name.substr(opt.base.size() + 1);
                if (tail.empty() ||
                    tail.find_first_not_of("0123456789") != std::string::npos)
                    continue;
                auto it = by_name.find(name);
                if (it == by_name.end()) {
                    Instance in;       /* unwanted: marked for removal */
                    in.name = name;
                    in.exists = true;
                    by_name[name] = in;
                } else {
                    it->second.exists = true;
                }
            }
            closedir(d);
        }
        for (auto &kv : by_name) insts.push_back(kv.second);
    }

    /* stop -> poll -> delete (remove_instance, smf_adjust.c:189-257) */
    bool remove_instance(const Instance &in) {
        printf("remove %s\n", in.name.c_str());
        changed = true;
        if (opt.dry_run) return true;
        pid_t pid = read_pid(pid_file(in.name));
        if (process_alive(pid)) {
            kill(pid, SIGTERM);
            int waited = 0;
            while (process_alive(pid) && waited < kStopWaitMs) {
                msleep(100);
                waited += 100;
            }
            if (process_alive(pid)) {
                fprintf(stderr, "instance_adjust: %s did not stop, "
                                "killing\n", in.name.c_str());
                kill(pid, SIGKILL);
                msleep(100);
            }
        }
        unlink(pid_file(in.name).c_str());
        unlink(props_file(in.name).c_str());
        return true;
    }

    bool stop_instance(const Instance &in) {
        pid_t pid = read_pid(pid_file(in.name));
        if (!process_alive(pid)) return true;
        kill(pid, SIGTERM);
        int waited = 0;
        while (process_alive(pid) && waited < kStopWaitMs) {
            msleep(100);
            waited += 100;
        }
        if (process_alive(pid)) kill(pid, SIGKILL);
        unlink(pid_file(in.name).c_str());
        return true;
    }

    /* configure with no-op detection (smf_adjust.c:337-455) */
    bool configure_instance(const Instance &in, bool *needs_restart,
                            bool *noop) {
        Props current, desired = desired_props(in);
        bool had = read_props(props_file(in.name), &current);
        if (had && props_equal(current, desired)) {
            *needs_restart = false;
            *noop = true;
            return true;
        }
        printf("%s %s\n", had ? "configure" : "create", in.name.c_str());
        changed = true;
        *noop = false;
        *needs_restart = had;   /* fresh instances just start */
        if (opt.dry_run) return true;
        return write_props(props_file(in.name), desired);
    }

    bool start_instance(const Instance &in) {
        printf("start %s\n", in.name.c_str());
        changed = true;
        if (opt.dry_run) return true;
        Props props;
        read_props(props_file(in.name), &props);
        std::string cmd = props["exec"];
        if (cmd.empty()) {
            fprintf(stderr, "instance_adjust: %s has no exec\n",
                    in.name.c_str());
            return false;
        }
        pid_t pid = fork();
        if (pid < 0) return false;
        if (pid == 0) {
            setsid();
            int logfd = open(log_file(in.name).c_str(),
                             O_WRONLY | O_CREAT | O_APPEND, 0644);
            if (logfd >= 0) {
                dup2(logfd, 1);
                dup2(logfd, 2);
                if (logfd > 2) close(logfd);
            }
            int devnull = open("/dev/null", O_RDONLY);
            if (devnull >= 0) {
                dup2(devnull, 0);
                if (devnull > 2) close(devnull);
            }
            execl("/bin/sh", "sh", "-c", cmd.c_str(), (char *)nullptr);
            _exit(127);
        }
        FILE *f = fopen(pid_file(in.name).c_str(), "w");
        if (f != nullptr) {
            fprintf(f, "%d\n", (int)pid);
            fclose(f);
        }
        return true;
    }

    /* enable + optional online wait (smf_adjust.c:457-544) */
    bool ensure_running(const Instance &in) {
        pid_t pid = read_pid(pid_file(in.name));
        if (process_alive(pid)) return true;
        if (pid > 0) {
            /* registered but dead: clear stale state and restart
             * (flush_status analog) */
            printf("restore %s\n", in.name.c_str());
            if (!opt.dry_run) unlink(pid_file(in.name).c_str());
        }
        return start_instance(in);
    }

    bool wait_online(const Instance &in) {
        int waited = 0;
        std::string sock = socket_path(in.port);
        while (waited < kOnlineWaitMs) {
            pid_t pid = read_pid(pid_file(in.name));
            bool alive = process_alive(pid);
            bool sock_ok = sock.empty() || access(sock.c_str(), F_OK) == 0;
            if (alive && sock_ok) {
                /* "online" means stably up, not merely spawned: an
                 * instance that crashes on startup is briefly alive */
                msleep(500);
                if (process_alive(pid)) return true;
            }
            if (!alive && waited > 1000) break;   /* crashed on startup */
            msleep(200);
            waited += 200;
        }
        fprintf(stderr, "instance_adjust: %s did not come online\n",
                in.name.c_str());
        return false;
    }

    int run() {
        build_sets();
        bool ok = true;

        /* removals first, to free ports/sockets (smf_adjust.c:1025-1039) */
        for (const auto &in : insts)
            if (!in.planned) ok &= remove_instance(in);

        for (auto &in : insts) {
            if (!in.planned) continue;
            bool needs_restart = false, noop = false;
            if (!configure_instance(in, &needs_restart, &noop)) {
                ok = false;
                continue;
            }
            if (needs_restart && !opt.dry_run) stop_instance(in);
            if (!opt.dry_run) {
                bool was_running =
                    process_alive(read_pid(pid_file(in.name)));
                ok &= ensure_running(in);
                if (noop && was_running)
                    printf("unchanged %s\n", in.name.c_str());
            }
        }

        if (opt.wait_online && !opt.dry_run) {
            for (const auto &in : insts)
                if (in.planned) ok &= wait_online(in);
        }

        /* metric-ports re-publication hook (smf_adjust.c:1119-1136) */
        if (changed && !opt.refresh_cmd.empty() && !opt.dry_run) {
            printf("refresh-hook\n");
            int rc = system(opt.refresh_cmd.c_str());
            if (rc != 0) {
                fprintf(stderr, "instance_adjust: refresh hook exited %d\n",
                        rc);
                ok = false;
            }
        }
        return ok ? 0 : 1;
    }
};

}  // namespace

int main(int argc, char **argv) {
    Options opt;
    int c;
    while ((c = getopt(argc, argv, "s:b:B:i:e:d:r:wn")) != -1) {
        switch (c) {
        case 's': opt.statedir = optarg; break;
        case 'b': opt.base = optarg; break;
        case 'B': opt.baseport = atoi(optarg); break;
        case 'i': opt.count = atoi(optarg); break;
        case 'e': opt.exec_template = optarg; break;
        case 'd': opt.sockdir = optarg; break;
        case 'r': opt.refresh_cmd = optarg; break;
        case 'w': opt.wait_online = true; break;
        case 'n': opt.dry_run = true; break;
        default:
            fprintf(stderr,
                    "usage: instance_adjust -s statedir -b base -B baseport "
                    "-i count -e exec [-d sockdir] [-r cmd] [-w] [-n]\n");
            return 2;
        }
    }
    if (opt.statedir.empty() || opt.count < 0 ||
        (opt.exec_template.empty() && !opt.dry_run)) {
        fprintf(stderr, "instance_adjust: -s, -i and -e are required "
                        "(max instances: 32, ports %d..%d)\n",
                opt.baseport, opt.baseport + 31);
        return 2;
    }
    if (opt.count > 32) {   /* reference bound (boot/setup.sh:17) */
        fprintf(stderr, "instance_adjust: count > 32\n");
        return 2;
    }
    mkdir(opt.statedir.c_str(), 0755);
    if (!opt.sockdir.empty()) mkdir(opt.sockdir.c_str(), 0755);

    Reconciler rec;
    rec.opt = opt;
    return rec.run();
}
