/*
 * instance_adjust — idempotent reconciler for a set of binder instances.
 *
 * C++ rebuild of the reference's smf_adjust + smfx + nvlist_equal
 * (SURVEY §2.2, §3.6): bring the set of running service instances
 * "<base>-<port>" in line with a plan of N instances on consecutive ports,
 * creating/configuring/starting the missing ones and stopping/removing the
 * surplus, with configuration no-op detection so unchanged instances are
 * not restarted.
 *
 * The reference reconciles against illumos SMF (libscf).  This rebuild
 * supports two service managers behind one plan/diff/no-op core:
 *
 *  -m systemd   (production; auto-selected when systemd is booted)
 *    Drives the shipped template units deploy/systemd/binder@.service via
 *    systemctl.  The per-instance config property group smf_adjust writes
 *    (src/smf_adjust.c:44,1060-1090) becomes a drop-in
 *    <dropin-root>/<base>@<port>.service.d/50-instance.conf setting
 *    BINDER_PORT / BINDER_SOCKET_PATH; drop-in equality is the
 *    nvlist_equal no-op check, `systemctl reset-failed` + start is the
 *    maintenance/degraded restore (flush_status, smfx.c:242-336), and
 *    disable --now -> poll is-active -> delete drop-in mirrors the
 *    disable/wait/delete removal loop (smf_adjust.c:189-257).
 *
 *  -m statedir  (supervisor-less fallback: containers, dev, tests)
 *    A built-in pid-file supervisor over a state directory:
 *      <statedir>/<name>.props   property group {instance, socket_path, exec}
 *      <statedir>/<name>.pid     supervised process id
 *      <statedir>/<name>.log     instance stdout/stderr
 *
 * Reconciliation semantics preserved from the reference in both backends:
 *  - planned set built first, existing instances walked and unwanted ones
 *    marked (smf_adjust.c:964-1015);
 *  - surplus removed via stop -> poll-until-gone -> delete
 *    (remove_instance, smf_adjust.c:189-257);
 *  - per-instance config compared order-insensitively against the current
 *    property group; identical config skips the restart entirely
 *    (nvlist_equal no-op detection, smf_adjust.c:337-455);
 *  - failed/dead-but-registered instances are restored (flush_status
 *    analog, smfx.c:242-336);
 *  - -w waits up to 60s for instances to come online (unit active /
 *    process alive + balancer socket present) (smf_adjust.c:457-544);
 *  - -r <cmd> runs once after changes, re-publishing metric ports (the
 *    metric-ports-updater restart, smf_adjust.c:1119-1136).
 *
 * Usage:
 *   instance_adjust [-m auto|systemd|statedir]
 *                   -s <statedir> | -D <dropin-root>
 *                   -b <base> -B <baseport> -i <count>
 *                   [-e <exec-template>] [-d <sockdir>] [-r <cmd>] [-w] [-n]
 *
 * The exec template (statedir backend) may contain %P (port), %S (socket
 * path), %N (name).  -n = dry run (print actions only).  systemctl is
 * resolved via PATH so tests can substitute a fake.
 */
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <getopt.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

namespace {

constexpr int kStopWaitMs = 10000;    /* disable poll (smf_adjust.c:189) */
constexpr int kOnlineWaitMs = 60000;  /* -w bound (smf_adjust.c:457) */

struct Options {
    std::string manager = "auto";
    std::string statedir;
    std::string dropin_root = "/etc/systemd/system";
    std::string base = "binder";
    int baseport = 5301;
    int count = -1;
    std::string exec_template;
    std::string sockdir;
    std::string refresh_cmd;
    bool wait_online = false;
    bool dry_run = false;
};

using Props = std::map<std::string, std::string>;

void msleep(int ms) {
    struct timespec ts = {ms / 1000, (long)(ms % 1000) * 1000000L};
    while (nanosleep(&ts, &ts) != 0 && errno == EINTR) {}
}

std::string path_join(const std::string &a, const std::string &b) {
    return a + "/" + b;
}

/* run argv, capture stdout; returns exit status or -1 */
int run_capture(const std::vector<std::string> &argv, std::string *out) {
    int fds[2];
    if (pipe(fds) != 0) return -1;
    pid_t pid = fork();
    if (pid < 0) {
        close(fds[0]);
        close(fds[1]);
        return -1;
    }
    if (pid == 0) {
        close(fds[0]);
        dup2(fds[1], 1);
        if (fds[1] > 2) close(fds[1]);
        std::vector<char *> cargv;
        for (const auto &a : argv)
            cargv.push_back(const_cast<char *>(a.c_str()));
        cargv.push_back(nullptr);
        execvp(cargv[0], cargv.data());
        _exit(127);
    }
    close(fds[1]);
    if (out != nullptr) {
        char buf[4096];
        ssize_t n;
        while ((n = read(fds[0], buf, sizeof(buf))) > 0)
            out->append(buf, (size_t)n);
    }
    close(fds[0]);
    int status = 0;
    while (waitpid(pid, &status, 0) < 0 && errno == EINTR) {}
    return WIFEXITED(status) ? WEXITSTATUS(status) : -1;
}

/* ---- property-group file I/O (the SMF config PG analog) ---- */

bool read_props(const std::string &file, Props *out) {
    FILE *f = fopen(file.c_str(), "r");
    if (f == nullptr) return false;
    char line[1024];
    while (fgets(line, sizeof(line), f) != nullptr) {
        char *nl = strchr(line, '\n');
        if (nl) *nl = '\0';
        char *eq = strchr(line, '=');
        if (eq == nullptr || line[0] == '#') continue;
        *eq = '\0';
        (*out)[line] = eq + 1;
    }
    fclose(f);
    return true;
}

bool write_props(const std::string &file, const Props &props) {
    std::string tmp = file + ".tmp";
    FILE *f = fopen(tmp.c_str(), "w");
    if (f == nullptr) return false;
    for (const auto &kv : props)
        fprintf(f, "%s=%s\n", kv.first.c_str(), kv.second.c_str());
    fclose(f);
    return rename(tmp.c_str(), file.c_str()) == 0;
}

/* order-insensitive structural equality (nvlist_equal analog,
 * src/nvlist_equal.c:260-304 — two half-subset passes collapsed into the
 * std::map comparison) */
bool props_equal(const Props &a, const Props &b) {
    return a == b;
}

/* ---- process supervision (statedir backend) ---- */

pid_t read_pid(const std::string &pidfile) {
    FILE *f = fopen(pidfile.c_str(), "r");
    if (f == nullptr) return -1;
    long pid = -1;
    if (fscanf(f, "%ld", &pid) != 1) pid = -1;
    fclose(f);
    return (pid_t)pid;
}

bool process_alive(pid_t pid) {
    if (pid <= 0) return false;
    if (kill(pid, 0) != 0 && errno != EPERM) return false;
    /* a zombie still answers kill(0); treat it as dead (orphans are not
     * reaped promptly in minimal containers) */
    char path[64], buf[512];
    snprintf(path, sizeof(path), "/proc/%d/stat", (int)pid);
    FILE *f = fopen(path, "r");
    if (f == nullptr) return false;
    size_t n = fread(buf, 1, sizeof(buf) - 1, f);
    fclose(f);
    buf[n] = '\0';
    const char *paren = strrchr(buf, ')');
    if (paren == nullptr || paren[1] == '\0') return true;
    return paren[2] != 'Z';
}

std::string substitute(const std::string &tmpl, int port,
                       const std::string &sock, const std::string &name) {
    std::string out;
    for (size_t i = 0; i < tmpl.size(); i++) {
        if (tmpl[i] == '%' && i + 1 < tmpl.size()) {
            switch (tmpl[i + 1]) {
            case 'P': out += std::to_string(port); i++; continue;
            case 'S': out += sock; i++; continue;
            case 'N': out += name; i++; continue;
            default: break;
            }
        }
        out.push_back(tmpl[i]);
    }
    return out;
}

/* ---- one instance ---- */

struct Instance {
    std::string name;       /* <base>-<port> (display / statedir key) */
    int port = 0;
    bool planned = false;   /* in the desired set */
    bool exists = false;    /* known to the service manager */
};

/* A numeric tail after "<base>-" / "<base>@"; anything else belongs to
 * another instance set sharing a prefix (binder vs binder-blue) and must
 * not be claimed and torn down. */
bool parse_port_tail(const std::string &tail, int *port) {
    if (tail.empty() ||
        tail.find_first_not_of("0123456789") != std::string::npos)
        return false;
    errno = 0;
    char *end = nullptr;
    long v = strtol(tail.c_str(), &end, 10);
    if (errno != 0 || *end != '\0' || v < 0 || v > 65535)
        return false;
    /* the round-trip check rejects leading zeros (binder@007), whose
     * port would map back to a differently-named unit */
    if (std::to_string(v) != tail)
        return false;
    *port = (int)v;
    return true;
}

/* Service-manager backend: everything below the shared plan/diff core.
 * The reference's equivalent split is smf_adjust (plan) over smfx
 * (manager eccentricities). */
struct ServiceManager {
    virtual ~ServiceManager() = default;
    /* existing instance names+ports (the libscf instance walk,
     * smf_adjust.c:975-1015) */
    virtual std::vector<Instance> discover() = 0;
    virtual bool remove_instance(const Instance &in) = 0;
    /* write desired config; *noop=true if identical (nvlist_equal path) */
    virtual bool configure_instance(const Instance &in, bool *needs_restart,
                                    bool *noop) = 0;
    /* *acted reports whether anything was done (drives both the
     * refresh hook and the "unchanged" no-op report) */
    virtual bool ensure_running(const Instance &in, bool needs_restart,
                                bool *acted) = 0;
    virtual bool wait_online(const Instance &in) = 0;
    /* end-of-run hook (e.g. flush a pending config reload after a
     * removal-only converge) */
    virtual void finish() {}
};

/* ---- statedir backend: built-in pid-file supervisor ---- */

struct StatedirManager : ServiceManager {
    Options opt;
    bool *changed;

    StatedirManager(const Options &o, bool *ch) : opt(o), changed(ch) {}

    std::string props_file(const std::string &n) {
        return path_join(opt.statedir, n + ".props");
    }
    std::string pid_file(const std::string &n) {
        return path_join(opt.statedir, n + ".pid");
    }
    std::string log_file(const std::string &n) {
        return path_join(opt.statedir, n + ".log");
    }
    std::string socket_path(int port) {
        if (opt.sockdir.empty()) return "";
        return path_join(opt.sockdir, std::to_string(port));
    }

    Props desired_props(const Instance &in) {
        Props p;
        p["instance"] = std::to_string(in.port);
        std::string sock = socket_path(in.port);
        if (!sock.empty()) p["socket_path"] = sock;
        p["exec"] = substitute(opt.exec_template, in.port, sock, in.name);
        return p;
    }

    std::vector<Instance> discover() override {
        std::vector<Instance> out;
        DIR *d = opendir(opt.statedir.c_str());
        if (d == nullptr) return out;
        struct dirent *de;
        std::string suffix = ".props";
        while ((de = readdir(d)) != nullptr) {
            std::string fn = de->d_name;
            if (fn.size() <= suffix.size() ||
                fn.compare(fn.size() - suffix.size(), suffix.size(),
                           suffix) != 0)
                continue;
            std::string name = fn.substr(0, fn.size() - suffix.size());
            if (name.compare(0, opt.base.size() + 1, opt.base + "-") != 0)
                continue;   /* not ours */
            Instance in;
            if (!parse_port_tail(name.substr(opt.base.size() + 1), &in.port))
                continue;
            in.name = name;
            in.exists = true;
            out.push_back(in);
        }
        closedir(d);
        return out;
    }

    /* stop -> poll -> delete (remove_instance, smf_adjust.c:189-257) */
    bool remove_instance(const Instance &in) override {
        printf("remove %s\n", in.name.c_str());
        *changed = true;
        if (opt.dry_run) return true;
        pid_t pid = read_pid(pid_file(in.name));
        if (process_alive(pid)) {
            kill(pid, SIGTERM);
            int waited = 0;
            while (process_alive(pid) && waited < kStopWaitMs) {
                msleep(100);
                waited += 100;
            }
            if (process_alive(pid)) {
                fprintf(stderr, "instance_adjust: %s did not stop, "
                                "killing\n", in.name.c_str());
                kill(pid, SIGKILL);
                msleep(100);
            }
        }
        unlink(pid_file(in.name).c_str());
        unlink(props_file(in.name).c_str());
        return true;
    }

    bool stop_instance(const Instance &in) {
        pid_t pid = read_pid(pid_file(in.name));
        if (!process_alive(pid)) return true;
        kill(pid, SIGTERM);
        int waited = 0;
        while (process_alive(pid) && waited < kStopWaitMs) {
            msleep(100);
            waited += 100;
        }
        if (process_alive(pid)) kill(pid, SIGKILL);
        unlink(pid_file(in.name).c_str());
        return true;
    }

    /* configure with no-op detection (smf_adjust.c:337-455) */
    bool configure_instance(const Instance &in, bool *needs_restart,
                            bool *noop) override {
        Props current, desired = desired_props(in);
        bool had = read_props(props_file(in.name), &current);
        if (had && props_equal(current, desired)) {
            *needs_restart = false;
            *noop = true;
            return true;
        }
        printf("%s %s\n", had ? "configure" : "create", in.name.c_str());
        *changed = true;
        *noop = false;
        *needs_restart = had;   /* fresh instances just start */
        if (opt.dry_run) return true;
        return write_props(props_file(in.name), desired);
    }

    bool start_instance(const Instance &in) {
        printf("start %s\n", in.name.c_str());
        if (opt.dry_run) return true;
        Props props;
        read_props(props_file(in.name), &props);
        std::string cmd = props["exec"];
        if (cmd.empty()) {
            fprintf(stderr, "instance_adjust: %s has no exec\n",
                    in.name.c_str());
            return false;
        }
        pid_t pid = fork();
        if (pid < 0) return false;
        if (pid == 0) {
            setsid();
            int logfd = open(log_file(in.name).c_str(),
                             O_WRONLY | O_CREAT | O_APPEND, 0644);
            if (logfd >= 0) {
                dup2(logfd, 1);
                dup2(logfd, 2);
                if (logfd > 2) close(logfd);
            }
            int devnull = open("/dev/null", O_RDONLY);
            if (devnull >= 0) {
                dup2(devnull, 0);
                if (devnull > 2) close(devnull);
            }
            execl("/bin/sh", "sh", "-c", cmd.c_str(), (char *)nullptr);
            _exit(127);
        }
        FILE *f = fopen(pid_file(in.name).c_str(), "w");
        if (f != nullptr) {
            fprintf(f, "%d\n", (int)pid);
            fclose(f);
        }
        return true;
    }

    /* enable + restore (smf_adjust.c:457-544; flush_status analog) */
    bool ensure_running(const Instance &in, bool needs_restart,
                        bool *acted) override {
        if (needs_restart && !opt.dry_run) {
            stop_instance(in);
            *acted = true;
        }
        pid_t pid = read_pid(pid_file(in.name));
        if (process_alive(pid)) return true;
        if (pid > 0) {
            /* registered but dead: clear stale state and restart
             * (flush_status analog) */
            printf("restore %s\n", in.name.c_str());
            if (!opt.dry_run) unlink(pid_file(in.name).c_str());
        }
        *acted = true;
        return start_instance(in);
    }

    bool wait_online(const Instance &in) override {
        int waited = 0;
        std::string sock = socket_path(in.port);
        while (waited < kOnlineWaitMs) {
            pid_t pid = read_pid(pid_file(in.name));
            bool alive = process_alive(pid);
            bool sock_ok = sock.empty() || access(sock.c_str(), F_OK) == 0;
            if (alive && sock_ok) {
                /* "online" means stably up, not merely spawned: an
                 * instance that crashes on startup is briefly alive */
                msleep(500);
                if (process_alive(pid)) return true;
            }
            if (!alive && waited > 1000) break;   /* crashed on startup */
            msleep(200);
            waited += 200;
        }
        fprintf(stderr, "instance_adjust: %s did not come online\n",
                in.name.c_str());
        return false;
    }
};

/* ---- systemd backend: drives deploy/systemd/binder@.service ---- */

struct SystemdManager : ServiceManager {
    Options opt;
    bool *changed;
    bool reload_pending = false;

    SystemdManager(const Options &o, bool *ch) : opt(o), changed(ch) {}

    std::string unit(int port) {
        return opt.base + "@" + std::to_string(port) + ".service";
    }
    std::string dropin_dir(int port) {
        return path_join(opt.dropin_root, unit(port) + ".d");
    }
    std::string dropin_file(int port) {
        return path_join(dropin_dir(port), "50-instance.conf");
    }
    std::string socket_path(int port) {
        std::string dir = opt.sockdir.empty() ? "/run/binder/sockets"
                                              : opt.sockdir;
        return path_join(dir, std::to_string(port));
    }

    int sysctl(const std::vector<std::string> &args, std::string *out) {
        std::vector<std::string> argv = {"systemctl"};
        argv.insert(argv.end(), args.begin(), args.end());
        return run_capture(argv, out);
    }

    /* batch daemon-reload: run once before the first start/restart after
     * any drop-in edit */
    void maybe_reload() {
        if (!reload_pending || opt.dry_run) return;
        sysctl({"daemon-reload"}, nullptr);
        reload_pending = false;
    }

    /* a removal-only converge deletes drop-ins without a later
     * start/restart; systemd must still drop its cached copies */
    void finish() override { maybe_reload(); }

    std::string active_state(int port) {
        std::string out;
        if (sysctl({"show", "-p", "ActiveState", "--value", unit(port)},
                   &out) != 0)
            return "unknown";
        while (!out.empty() && (out.back() == '\n' || out.back() == ' '))
            out.pop_back();
        return out.empty() ? "unknown" : out;
    }

    /* the config property group, as drop-in Environment= lines */
    Props desired_props(const Instance &in) {
        Props p;
        p["BINDER_PORT"] = std::to_string(in.port);
        p["BINDER_SOCKET_PATH"] = socket_path(in.port);
        return p;
    }

    bool read_dropin(int port, Props *out) {
        FILE *f = fopen(dropin_file(port).c_str(), "r");
        if (f == nullptr) return false;
        char line[1024];
        while (fgets(line, sizeof(line), f) != nullptr) {
            char *nl = strchr(line, '\n');
            if (nl) *nl = '\0';
            if (strncmp(line, "Environment=", 12) != 0) continue;
            char *eq = strchr(line + 12, '=');
            if (eq == nullptr) continue;
            *eq = '\0';
            (*out)[line + 12] = eq + 1;
        }
        fclose(f);
        return true;
    }

    bool write_dropin(int port, const Props &props) {
        mkdir(dropin_dir(port).c_str(), 0755);
        std::string tmp = dropin_file(port) + ".tmp";
        FILE *f = fopen(tmp.c_str(), "w");
        if (f == nullptr) return false;
        fprintf(f, "# written by instance_adjust; the per-instance config\n"
                   "# property group (ref src/smf_adjust.c:1060-1090)\n"
                   "[Service]\n");
        for (const auto &kv : props)
            fprintf(f, "Environment=%s=%s\n", kv.first.c_str(),
                    kv.second.c_str());
        fclose(f);
        if (rename(tmp.c_str(), dropin_file(port).c_str()) != 0)
            return false;
        reload_pending = true;
        return true;
    }

    /* union of: configured drop-ins, enabled unit files, loaded units —
     * the libscf instance-iteration analog (smf_adjust.c:975-1015) */
    std::vector<Instance> discover() override {
        std::set<int> ports;

        DIR *d = opendir(opt.dropin_root.c_str());
        if (d != nullptr) {
            struct dirent *de;
            std::string prefix = opt.base + "@";
            std::string suffix = ".service.d";
            while ((de = readdir(d)) != nullptr) {
                std::string fn = de->d_name;
                if (fn.compare(0, prefix.size(), prefix) != 0) continue;
                if (fn.size() <= prefix.size() + suffix.size() ||
                    fn.compare(fn.size() - suffix.size(), suffix.size(),
                               suffix) != 0)
                    continue;
                int port;
                if (parse_port_tail(fn.substr(prefix.size(),
                        fn.size() - prefix.size() - suffix.size()), &port))
                    ports.insert(port);
            }
            closedir(d);
        }

        for (const char *mode : {"units", "unit-files"}) {
            std::string out;
            std::vector<std::string> args = {std::string("list-") + mode};
            if (strcmp(mode, "units") == 0) {
                args.push_back("--all");
                args.push_back("--plain");   /* list-unit-files rejects it */
            }
            args.push_back("--no-legend");
            args.push_back(opt.base + "@*.service");
            if (sysctl(args, &out) != 0) continue;
            size_t pos = 0;
            while (pos < out.size()) {
                size_t eol = out.find('\n', pos);
                if (eol == std::string::npos) eol = out.size();
                std::string line = out.substr(pos, eol - pos);
                pos = eol + 1;
                size_t sp = line.find_first_of(" \t");
                std::string uname =
                    sp == std::string::npos ? line : line.substr(0, sp);
                std::string prefix = opt.base + "@";
                std::string suffix = ".service";
                if (uname.compare(0, prefix.size(), prefix) != 0) continue;
                if (uname.size() <= prefix.size() + suffix.size()) continue;
                if (uname.compare(uname.size() - suffix.size(),
                                  suffix.size(), suffix) != 0)
                    continue;
                int port;
                if (parse_port_tail(uname.substr(prefix.size(),
                        uname.size() - prefix.size() - suffix.size()),
                        &port))
                    ports.insert(port);
            }
        }

        std::vector<Instance> out;
        for (int port : ports) {
            Instance in;
            in.port = port;
            in.name = opt.base + "-" + std::to_string(port);
            in.exists = true;
            out.push_back(in);
        }
        return out;
    }

    /* disable --now -> poll is-active -> delete drop-in
     * (remove_instance, smf_adjust.c:189-257) */
    bool remove_instance(const Instance &in) override {
        printf("remove %s\n", in.name.c_str());
        *changed = true;
        if (opt.dry_run) return true;
        sysctl({"disable", "--now", unit(in.port)}, nullptr);
        int waited = 0;
        std::string st;
        while (waited < kStopWaitMs) {
            st = active_state(in.port);
            if (st != "active" && st != "deactivating") break;
            msleep(100);
            waited += 100;
        }
        if (st == "active" || st == "deactivating") {
            /* still (de)activating after the poll bound: the process may
             * hold the port/socket — fail like the statedir backend */
            fprintf(stderr, "instance_adjust: %s did not stop\n",
                    in.name.c_str());
            return false;
        }
        /* clear any failed remnant so a later re-add starts clean */
        sysctl({"reset-failed", unit(in.port)}, nullptr);
        unlink(dropin_file(in.port).c_str());
        std::string tmp = dropin_file(in.port) + ".tmp";
        unlink(tmp.c_str());
        rmdir(dropin_dir(in.port).c_str());
        reload_pending = true;
        return true;
    }

    bool configure_instance(const Instance &in, bool *needs_restart,
                            bool *noop) override {
        Props current, desired = desired_props(in);
        bool had = read_dropin(in.port, &current);
        if (had && props_equal(current, desired)) {
            *needs_restart = false;
            *noop = true;
            return true;
        }
        printf("%s %s\n", had ? "configure" : "create", in.name.c_str());
        *changed = true;
        *noop = false;
        /* like the reference, only a *running* instance with changed
         * config is restarted; stopped ones just start (running-snapshot
         * compare, smf_adjust.c:384-448).  This includes a hand-started
         * unit getting its first drop-in — its live environment is stale */
        *needs_restart = active_state(in.port) == "active";
        if (opt.dry_run) return true;
        return write_dropin(in.port, desired);
    }

    bool ensure_running(const Instance &in, bool needs_restart,
                        bool *acted) override {
        if (opt.dry_run) {
            if (needs_restart) {
                printf("restart %s\n", in.name.c_str());
                *acted = true;
            } else if (active_state(in.port) != "active") {
                printf("start %s\n", in.name.c_str());
                *acted = true;
            }
            return true;
        }
        if (needs_restart) {
            printf("restart %s\n", in.name.c_str());
            *acted = true;
            maybe_reload();
            return sysctl({"restart", unit(in.port)}, nullptr) == 0;
        }
        std::string st = active_state(in.port);
        if (st == "active") {
            /* idempotent enable so the instance survives reboot (the
             * reference's instances are persistently enabled) */
            sysctl({"enable", unit(in.port)}, nullptr);
            return true;
        }
        if (st == "failed") {
            /* maintenance/degraded restore: clear restarter state first
             * (flush_status, smfx.c:242-336) */
            printf("restore %s\n", in.name.c_str());
            *acted = true;
            sysctl({"reset-failed", unit(in.port)}, nullptr);
            maybe_reload();
            sysctl({"enable", unit(in.port)}, nullptr);
            return sysctl({"start", unit(in.port)}, nullptr) == 0;
        }
        printf("start %s\n", in.name.c_str());
        *acted = true;
        maybe_reload();
        sysctl({"enable", unit(in.port)}, nullptr);
        return sysctl({"start", unit(in.port)}, nullptr) == 0;
    }

    bool wait_online(const Instance &in) override {
        int waited = 0;
        std::string sock = socket_path(in.port);
        while (waited < kOnlineWaitMs) {
            std::string st = active_state(in.port);
            bool sock_ok = access(sock.c_str(), F_OK) == 0;
            if (st == "active" && sock_ok) {
                /* stability recheck, as in the statedir backend */
                msleep(500);
                if (active_state(in.port) == "active") return true;
                st = "unknown";
            }
            if (st == "failed") break;
            msleep(200);
            waited += 200;
        }
        fprintf(stderr, "instance_adjust: %s did not come online\n",
                in.name.c_str());
        return false;
    }
};

/* ---- the shared plan/diff core (smf_adjust.c:866-1051) ---- */

struct Reconciler {
    Options opt;
    ServiceManager *mgr;
    std::vector<Instance> insts;
    bool changed = false;

    /* plan + walk (smf_adjust.c:964-1015) */
    void build_sets() {
        std::map<std::string, Instance> by_name;
        for (int i = 0; i < opt.count; i++) {
            Instance in;
            in.port = opt.baseport + i;
            in.name = opt.base + "-" + std::to_string(in.port);
            in.planned = true;
            by_name[in.name] = in;
        }
        for (const Instance &found : mgr->discover()) {
            auto it = by_name.find(found.name);
            if (it == by_name.end()) {
                by_name[found.name] = found;   /* unwanted: removal mark */
            } else {
                it->second.exists = true;
            }
        }
        for (auto &kv : by_name) insts.push_back(kv.second);
    }

    int run() {
        build_sets();
        bool ok = true;

        /* removals first, to free ports/sockets (smf_adjust.c:1025-1039) */
        for (const auto &in : insts)
            if (!in.planned) ok &= mgr->remove_instance(in);

        /* configure everything before starting anything, so backends can
         * batch config reloads (ensure/configure then enable phasing,
         * smf_adjust.c:1040-1090) */
        struct Work { const Instance *in; bool needs_restart; bool noop; };
        std::vector<Work> work;
        for (auto &in : insts) {
            if (!in.planned) continue;
            Work w = {&in, false, false};
            if (!mgr->configure_instance(in, &w.needs_restart, &w.noop)) {
                ok = false;
                continue;
            }
            work.push_back(w);
        }
        for (const auto &w : work) {
            bool acted = false;
            ok &= mgr->ensure_running(*w.in, w.needs_restart, &acted);
            if (acted)
                changed = true;
            if (w.noop && !acted)
                printf("unchanged %s\n", w.in->name.c_str());
        }

        mgr->finish();

        if (opt.wait_online && !opt.dry_run) {
            for (const auto &in : insts)
                if (in.planned) ok &= mgr->wait_online(in);
        }

        /* metric-ports re-publication hook (smf_adjust.c:1119-1136) */
        if (changed && !opt.refresh_cmd.empty() && !opt.dry_run) {
            printf("refresh-hook\n");
            int rc = system(opt.refresh_cmd.c_str());
            if (rc != 0) {
                fprintf(stderr, "instance_adjust: refresh hook exited %d\n",
                        rc);
                ok = false;
            }
        }
        return ok ? 0 : 1;
    }
};

}  // namespace

int main(int argc, char **argv) {
    Options opt;
    int c;
    while ((c = getopt(argc, argv, "m:s:D:b:B:i:e:d:r:wn")) != -1) {
        switch (c) {
        case 'm': opt.manager = optarg; break;
        case 's': opt.statedir = optarg; break;
        case 'D': opt.dropin_root = optarg; break;
        case 'b': opt.base = optarg; break;
        case 'B': opt.baseport = atoi(optarg); break;
        case 'i': opt.count = atoi(optarg); break;
        case 'e': opt.exec_template = optarg; break;
        case 'd': opt.sockdir = optarg; break;
        case 'r': opt.refresh_cmd = optarg; break;
        case 'w': opt.wait_online = true; break;
        case 'n': opt.dry_run = true; break;
        default:
            fprintf(stderr,
                    "usage: instance_adjust [-m auto|systemd|statedir] "
                    "-s statedir | -D dropin-root -b base -B baseport "
                    "-i count [-e exec] [-d sockdir] [-r cmd] [-w] [-n]\n");
            return 2;
        }
    }
    if (opt.manager == "auto") {
        /* an explicit -s statedir wins (existing callers: binder-topology,
         * tests — auto must never redirect them onto the host's real
         * systemd); otherwise systemd iff the system booted with it */
        if (!opt.statedir.empty())
            opt.manager = "statedir";
        else
            opt.manager = access("/run/systemd/system", F_OK) == 0
                              ? "systemd" : "statedir";
    }
    if (opt.manager != "systemd" && opt.manager != "statedir") {
        fprintf(stderr, "instance_adjust: unknown manager '%s'\n",
                opt.manager.c_str());
        return 2;
    }
    if (opt.count < 0) {
        fprintf(stderr, "instance_adjust: -i is required "
                        "(max instances: 32, ports %d..%d)\n",
                opt.baseport, opt.baseport + 31);
        return 2;
    }
    if (opt.count > 32) {   /* reference bound (boot/setup.sh:17) */
        fprintf(stderr, "instance_adjust: count > 32\n");
        return 2;
    }

    Reconciler rec;
    rec.opt = opt;
    std::unique_ptr<ServiceManager> mgr;
    if (opt.manager == "statedir") {
        if (opt.statedir.empty() ||
            (opt.exec_template.empty() && !opt.dry_run)) {
            fprintf(stderr, "instance_adjust: -m statedir requires -s "
                            "and -e\n");
            return 2;
        }
        mkdir(opt.statedir.c_str(), 0755);
        if (!opt.sockdir.empty()) mkdir(opt.sockdir.c_str(), 0755);
        mgr.reset(new StatedirManager(opt, &rec.changed));
    } else {
        mgr.reset(new SystemdManager(opt, &rec.changed));
    }
    rec.mgr = mgr.get();
    return rec.run();
}
