/*
 * zlogcat — forensic decoder for ZooKeeper replicated transaction logs.
 *
 * C++ rebuild of the reference's src/zklog.c (SURVEY §2.2): mmap a txnlog,
 * validate the FileHeader (magic "ZKLG" = 0x5A4B4C47, version 2), walk the
 * checksummed, length-prefixed transaction records with strict bounds
 * checking, and print one JSON object per transaction.  Tracks session
 * lifetimes (createSession -> closeSession) to report durations, and can
 * dump sessions still open at the end of the log.
 *
 * The on-disk format is the public ZooKeeper jute serialization:
 *   FileHeader { int magic; int version; long dbid; }
 *   repeated:  [ long adler32 ][ int txnlen ][ txn bytes ][ 0x42 EOR ]
 *   txn bytes: TxnHeader { long clientId; int cxid; long zxid; long time;
 *              int type; } + per-type record body.
 * Preallocated zero padding terminates the walk (txnlen == 0).
 *
 * Usage: zlogcat [-t from-to] [-s sessionid] [-z serverid] [-S] <log>...
 *   -t ms_from-ms_to   only txns inside the time window
 *   -s 0xID            only txns from one session (clientId)
 *   -z N               only sessions created on server id N (high byte of
 *                      the session id)
 *   -S                 after decoding, dump sessions still open
 */
#include <fcntl.h>
#include <getopt.h>
#include <inttypes.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x5A4B4C47;  /* "ZKLG" */
constexpr int kVersion = 2;
constexpr uint8_t kEor = 0x42;

/* txn types (public ZooKeeper OpCode values) */
enum TxnType : int32_t {
    kNotification = 0,
    kCreate = 1,
    kDelete = 2,
    kExists = 3,
    kGetData = 4,
    kSetData = 5,
    kGetACL = 6,
    kSetACL = 7,
    kGetChildren = 8,
    kSync = 9,
    kPing = 11,
    kGetChildren2 = 12,
    kCheck = 13,
    kMulti = 14,
    kCreate2 = 15,
    kReconfig = 16,
    kCreateContainer = 19,
    kDeleteContainer = 20,
    kCreateTTL = 21,
    kAuth = 100,
    kSetWatches = 101,
    kCreateSession = -10,
    kCloseSession = -11,
    kError = -1,
};

const char *txn_type_name(int32_t t) {
    switch (t) {
    case kCreate: return "create";
    case kCreate2: return "create2";
    case kCreateContainer: return "createContainer";
    case kCreateTTL: return "createTTL";
    case kDelete: return "delete";
    case kDeleteContainer: return "deleteContainer";
    case kSetData: return "setData";
    case kSetACL: return "setACL";
    case kCheck: return "check";
    case kMulti: return "multi";
    case kCreateSession: return "createSession";
    case kCloseSession: return "closeSession";
    case kError: return "error";
    default: return "unknown";
    }
}

/* ---- bounds-checked big-endian reader over the mmap'd file ---- */
struct Reader {
    const uint8_t *data;
    size_t len;
    size_t off = 0;
    bool ok = true;

    bool need(size_t n) {
        if (!ok || len - off < n) {
            ok = false;
            return false;
        }
        return true;
    }
    uint8_t u8() {
        if (!need(1)) return 0;
        return data[off++];
    }
    uint32_t u32() {
        if (!need(4)) return 0;
        uint32_t v = ((uint32_t)data[off] << 24) |
                     ((uint32_t)data[off + 1] << 16) |
                     ((uint32_t)data[off + 2] << 8) | data[off + 3];
        off += 4;
        return v;
    }
    int32_t i32() { return (int32_t)u32(); }
    uint64_t u64() {
        uint64_t hi = u32();
        return (hi << 32) | u32();
    }
    int64_t i64() { return (int64_t)u64(); }
    /* jute string/buffer: i32 length (-1 = null) + bytes */
    bool bytes(std::string *out, bool *is_null) {
        int32_t n = i32();
        if (!ok) return false;
        if (n < 0) {
            *is_null = true;
            out->clear();
            return true;
        }
        *is_null = false;
        if ((uint32_t)n > len - off) {
            ok = false;
            return false;
        }
        out->assign((const char *)data + off, (size_t)n);
        off += (size_t)n;
        return true;
    }
};

/* ---- JSON string escaping for paths/data ---- */
void json_escape(const std::string &in, std::string *out) {
    out->push_back('"');
    for (unsigned char c : in) {
        switch (c) {
        case '"': *out += "\\\""; break;
        case '\\': *out += "\\\\"; break;
        case '\n': *out += "\\n"; break;
        case '\r': *out += "\\r"; break;
        case '\t': *out += "\\t"; break;
        default:
            if (c < 0x20 || c >= 0x7f) {
                char buf[8];
                snprintf(buf, sizeof(buf), "\\u%04x", c);
                *out += buf;
            } else {
                out->push_back((char)c);
            }
        }
    }
    out->push_back('"');
}

struct Filters {
    int64_t time_from = -1, time_to = -1;
    bool has_session = false;   /* -1 is a valid session id high-byte */
    int64_t session = 0;
    int server_id = -1;      /* high byte of the session id */
    bool dump_open = false;
};

struct SessionInfo {
    int64_t opened_at = 0;
    int32_t timeout = 0;
};

struct Stats {
    uint64_t txns = 0, bad = 0;
    std::unordered_map<int64_t, SessionInfo> open_sessions;
};

/* decode one typed txn body into JSON fields appended to *out */
bool decode_body(Reader *r, int32_t type, std::string *out, int depth);

bool decode_create(Reader *r, std::string *out, bool with_cversion,
                   bool with_ttl) {
    std::string path, data;
    bool null_path, null_data;
    if (!r->bytes(&path, &null_path) || !r->bytes(&data, &null_data))
        return false;
    /* acl vector: i32 count (-1 = null), each {i32 perms, string scheme,
     * string id} */
    int32_t nacl = r->i32();
    for (int32_t i = 0; r->ok && i < nacl; i++) {
        (void)r->i32();
        std::string s;
        bool n;
        if (!r->bytes(&s, &n) || !r->bytes(&s, &n)) return false;
    }
    uint8_t ephemeral = r->u8();
    /* parentCVersion exists from ZK 3.4 on; older logs omit it */
    bool have_cversion = with_cversion && r->len - r->off >= 4;
    int32_t cversion = have_cversion ? r->i32() : 0;
    int64_t ttl = with_ttl ? r->i64() : 0;
    if (!r->ok) return false;
    *out += ", \"path\": ";
    json_escape(path, out);
    *out += ", \"dataLen\": " + std::to_string(data.size());
    /* znode payloads in binder deployments are JSON; show a prefix */
    std::string preview = data.substr(0, 64);
    *out += ", \"data\": ";
    json_escape(preview, out);
    *out += ", \"ephemeral\": ";
    *out += ephemeral ? "true" : "false";
    if (have_cversion)
        *out += ", \"parentCVersion\": " + std::to_string(cversion);
    if (with_ttl) *out += ", \"ttl\": " + std::to_string(ttl);
    return true;
}

bool decode_body(Reader *r, int32_t type, std::string *out, int depth) {
    std::string s;
    bool is_null;
    switch (type) {
    case kCreate:
    case kCreateContainer:
        return decode_create(r, out, true, false);
    case kCreate2:
        return decode_create(r, out, true, false);
    case kCreateTTL:
        return decode_create(r, out, true, true);
    case kDelete:
    case kDeleteContainer:
        if (!r->bytes(&s, &is_null)) return false;
        *out += ", \"path\": ";
        json_escape(s, out);
        return true;
    case kSetData: {
        std::string data;
        bool null_data;
        if (!r->bytes(&s, &is_null) || !r->bytes(&data, &null_data))
            return false;
        int32_t version = r->i32();
        if (!r->ok) return false;
        *out += ", \"path\": ";
        json_escape(s, out);
        *out += ", \"dataLen\": " + std::to_string(data.size());
        std::string preview = data.substr(0, 64);
        *out += ", \"data\": ";
        json_escape(preview, out);
        *out += ", \"version\": " + std::to_string(version);
        return true;
    }
    case kSetACL: {
        if (!r->bytes(&s, &is_null)) return false;
        int32_t nacl = r->i32();
        for (int32_t i = 0; r->ok && i < nacl; i++) {
            (void)r->i32();
            std::string t;
            bool n;
            if (!r->bytes(&t, &n) || !r->bytes(&t, &n)) return false;
        }
        int32_t version = r->i32();
        if (!r->ok) return false;
        *out += ", \"path\": ";
        json_escape(s, out);
        *out += ", \"version\": " + std::to_string(version);
        return true;
    }
    case kCheck: {
        if (!r->bytes(&s, &is_null)) return false;
        int32_t version = r->i32();
        if (!r->ok) return false;
        *out += ", \"path\": ";
        json_escape(s, out);
        *out += ", \"version\": " + std::to_string(version);
        return true;
    }
    case kCreateSession: {
        int32_t timeout = r->i32();
        if (!r->ok) return false;
        *out += ", \"timeoutMs\": " + std::to_string(timeout);
        return true;
    }
    case kCloseSession:
        /* 3.5 and earlier: empty body; 3.6+: vector of paths to delete —
         * tolerate either by consuming an optional path vector */
        if (r->len - r->off >= 4) {
            int32_t n = r->i32();
            if (r->ok && n >= 0) {
                for (int32_t i = 0; r->ok && i < n; i++) {
                    std::string t;
                    bool isn;
                    if (!r->bytes(&t, &isn)) return false;
                }
            }
        }
        return true;
    case kError: {
        int32_t err = r->i32();
        if (!r->ok) return false;
        *out += ", \"err\": " + std::to_string(err);
        return true;
    }
    case kMulti: {
        /* vector of Txn { i32 type; buffer data } */
        int32_t n = r->i32();
        if (!r->ok || depth > 4) return false;
        *out += ", \"ops\": [";
        for (int32_t i = 0; i < n && r->ok; i++) {
            int32_t sub_type = r->i32();
            std::string sub;
            bool isn;
            if (!r->bytes(&sub, &isn)) {
                *out += "]";   /* keep the JSON line well-formed */
                return false;
            }
            Reader sr{(const uint8_t *)sub.data(), sub.size()};
            if (i) *out += ", ";
            *out += "{\"type\": \"";
            *out += txn_type_name(sub_type);
            *out += "\"";
            if (!decode_body(&sr, sub_type, out, depth + 1)) {
                *out += "}]";
                return false;
            }
            *out += "}";
        }
        *out += "]";
        return r->ok;
    }
    default:
        /* unknown type: skip the rest of the record (length-delimited by
         * the outer walk, so this is safe) */
        *out += ", \"undecoded\": true";
        r->off = r->len;
        return true;
    }
}

int session_server_id(int64_t session_id) {
    return (int)((uint64_t)session_id >> 56) & 0xff;
}

/* ZooKeeper stores an Adler-32 of the txn bytes as the record checksum */
uint32_t adler32(const uint8_t *data, size_t len) {
    uint32_t a = 1, b = 0;
    for (size_t i = 0; i < len; i++) {
        a = (a + data[i]) % 65521;
        b = (b + a) % 65521;
    }
    return (b << 16) | a;
}

/* Decode one mapped txnlog buffer.  Split from do_file so the record
 * walk can be driven directly with hostile bytes (fuzz target
 * native/fuzz/fuzz_zlog.cpp). */
bool do_buffer(const char *fname, const uint8_t *data, size_t size,
               const Filters &f, Stats *st) {
    Reader r{data, size};
    uint32_t magic = r.u32();
    int32_t version = r.i32();
    int64_t dbid = r.i64();
    if (!r.ok || magic != kMagic || version != kVersion) {
        fprintf(stderr,
                "zlogcat: %s: bad file header (magic 0x%08X version %d)\n",
                fname, magic, version);
        return false;
    }
    printf("{\"file\": \"%s\", \"dbid\": %" PRId64 "}\n", fname, dbid);

    for (;;) {
        if (r.len - r.off < 12) break;        /* no room for crc+len */
        uint64_t crc = r.u64();
        int32_t txnlen = r.i32();
        if (txnlen <= 0 || crc == 0) break;   /* preallocated padding */
        if ((uint32_t)txnlen > r.len - r.off) {
            fprintf(stderr, "zlogcat: %s: record at offset %zu overruns "
                            "file (len %d)\n", fname, r.off, txnlen);
            st->bad++;
            break;
        }
        Reader tr{r.data + r.off, (size_t)txnlen};
        bool crc_ok = adler32(r.data + r.off, (size_t)txnlen) ==
                      (uint32_t)crc;
        r.off += (size_t)txnlen;
        if (r.u8() != kEor) {
            fprintf(stderr, "zlogcat: %s: missing end-of-record marker\n",
                    fname);
            st->bad++;
            break;
        }
        if (!crc_ok) {
            fprintf(stderr, "zlogcat: %s: checksum mismatch at offset %zu\n",
                    fname, r.off);
            st->bad++;
            continue;
        }

        int64_t client_id = tr.i64();
        int32_t cxid = tr.i32();
        int64_t zxid = tr.i64();
        int64_t time_ms = tr.i64();
        int32_t type = tr.i32();
        if (!tr.ok) {
            st->bad++;
            continue;
        }

        /* session bookkeeping runs before filters so -S is accurate */
        if (type == kCreateSession) {
            Reader peek{tr.data + tr.off, tr.len - tr.off};
            SessionInfo si;
            si.opened_at = time_ms;
            si.timeout = peek.i32();
            st->open_sessions[client_id] = si;
        }
        int64_t duration_ms = -1;
        if (type == kCloseSession) {
            auto it = st->open_sessions.find(client_id);
            if (it != st->open_sessions.end()) {
                duration_ms = time_ms - it->second.opened_at;
                st->open_sessions.erase(it);
            }
        }

        if (f.time_from >= 0 && (time_ms < f.time_from ||
                                 time_ms > f.time_to))
            continue;
        if (f.has_session && client_id != f.session) continue;
        if (f.server_id >= 0 && session_server_id(client_id) != f.server_id)
            continue;

        std::string line = "{";
        char head[256];
        snprintf(head, sizeof(head),
                 "\"zxid\": \"0x%" PRIx64 "\", \"time\": %" PRId64
                 ", \"session\": \"0x%" PRIx64 "\", \"cxid\": %d, "
                 "\"type\": \"%s\"",
                 (uint64_t)zxid, time_ms, (uint64_t)client_id, cxid,
                 txn_type_name(type));
        line += head;
        if (!decode_body(&tr, type, &line, 0)) {
            st->bad++;
            line += ", \"decodeError\": true";
        }
        if (duration_ms >= 0)
            line += ", \"sessionDurationMs\": " + std::to_string(duration_ms);
        line += "}";
        puts(line.c_str());
        st->txns++;
    }

    return true;
}

bool do_file(const char *fname, const Filters &f, Stats *st) {
    int fd = open(fname, O_RDONLY);
    if (fd < 0) {
        fprintf(stderr, "zlogcat: cannot open %s: %s\n", fname,
                strerror(errno));
        return false;
    }
    struct stat sb;
    if (fstat(fd, &sb) != 0 || sb.st_size < 16) {
        fprintf(stderr, "zlogcat: %s: too short for a txnlog\n", fname);
        close(fd);
        return false;
    }
    void *map = mmap(nullptr, (size_t)sb.st_size, PROT_READ, MAP_PRIVATE,
                     fd, 0);
    close(fd);
    if (map == MAP_FAILED) {
        fprintf(stderr, "zlogcat: mmap %s: %s\n", fname, strerror(errno));
        return false;
    }
    bool ok = do_buffer(fname, (const uint8_t *)map, (size_t)sb.st_size,
                        f, st);
    munmap(map, (size_t)sb.st_size);
    return ok;
}

}  // namespace

int main(int argc, char **argv) {
    Filters f;
    int c;
    while ((c = getopt(argc, argv, "t:s:z:S")) != -1) {
        switch (c) {
        case 't': {
            char *dash = strchr(optarg, '-');
            if (dash == nullptr) {
                fprintf(stderr, "zlogcat: -t wants from-to (ms)\n");
                return 1;
            }
            f.time_from = strtoll(optarg, nullptr, 0);
            f.time_to = strtoll(dash + 1, nullptr, 0);
            break;
        }
        case 's':
            f.has_session = true;
            f.session = (int64_t)strtoull(optarg, nullptr, 0);
            break;
        case 'z':
            f.server_id = (int)strtol(optarg, nullptr, 0);
            break;
        case 'S':
            f.dump_open = true;
            break;
        default:
            fprintf(stderr, "usage: zlogcat [-t from-to] [-s session] "
                            "[-z serverid] [-S] <txnlog>...\n");
            return 1;
        }
    }
    if (optind >= argc) {
        fprintf(stderr, "zlogcat: no input files\n");
        return 1;
    }

    Stats st;
    int rc = 0;
    for (int i = optind; i < argc; i++)
        if (!do_file(argv[i], f, &st)) rc = 1;

    if (f.dump_open) {
        for (const auto &kv : st.open_sessions) {
            printf("{\"openSession\": \"0x%" PRIx64 "\", \"openedAt\": "
                   "%" PRId64 ", \"timeoutMs\": %d, \"serverId\": %d}\n",
                   (uint64_t)kv.first, kv.second.opened_at,
                   kv.second.timeout, session_server_id(kv.first));
        }
    }
    fprintf(stderr, "zlogcat: %" PRIu64 " txns decoded, %" PRIu64 " bad\n",
            st.txns, st.bad);
    return rc;
}
